"""Audit harness: build tiny trainers on a CPU mesh and trace their
jitted programs abstractly.

The jaxpr audit needs *real* trainer-constructed programs — the same
``_train_step_jit`` / ``_sample_jit`` callables production uses — traced
with ``jax.make_jaxpr`` on shape-only inputs. This module owns the tiny
configs (bf16 compute / f32 params, the production default, so the
precision-leak rule sees the real dtype story) and the abstract input
construction for all four trainers:

- ``ppo``      — ``PPOTrainer``          (causal gpt2)
- ``ilql``     — ``ILQLTrainer``         (causal gpt2)
- ``grpo``     — ``GRPOTrainer``         (causal gpt2, grouped rollouts)
- ``seq2seq``  — ``Seq2SeqPPOTrainer``   (T5)

Runs on any device count: the audit mesh uses ``tp=2``/``fsdp=2`` when the
host exposes enough (virtual) devices — ``python -m trlx_tpu.analysis``
forces 8 virtual CPU devices before importing jax — and degrades to
single-axis otherwise.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Iterator, List, Optional, Sequence, Set, Tuple

TRAINER_KINDS = ("ppo", "ilql", "grpo", "seq2seq")


def audit_mesh_config() -> Dict[str, int]:
    """Mesh axis sizes for the audit, adapted to the device count."""
    import jax

    n = len(jax.devices())
    tp = 2 if n % 2 == 0 and n >= 2 else 1
    fsdp = 2 if n % (2 * tp) == 0 and n >= 2 * tp else 1
    return {"dp": -1, "fsdp": fsdp, "tp": tp}


def audit_mesh():
    from trlx_tpu.parallel.mesh import make_mesh

    return make_mesh(audit_mesh_config())


_CAUSAL_ARCH = {
    "vocab_size": 32,
    "n_positions": 32,
    "n_embd": 32,
    "n_layer": 2,
    "n_head": 2,
}

_T5_ARCH = {
    "vocab_size": 32,
    "d_model": 32,
    "d_kv": 8,
    "d_ff": 64,
    "num_layers": 2,
    "num_decoder_layers": 2,
    "num_heads": 4,
    "relative_attention_num_buckets": 8,
    "relative_attention_max_distance": 16,
    "feed_forward_proj": "gated-gelu",
    "tie_word_embeddings": False,
}


def _base_train(mesh: Dict[str, int]) -> Dict[str, Any]:
    return {
        "seq_length": 8,
        "batch_size": 8,
        "epochs": 1,
        "total_steps": 4,
        "eval_interval": 1000,
        "checkpoint_interval": 100000,
        "mesh": mesh,
        # production defaults: bf16 compute over f32 masters — the
        # precision-leak rule audits the dtype story the TPU runs
        "dtype": "bfloat16",
        "param_dtype": "float32",
    }


def tiny_config_dict(
    kind: str,
    mesh: Optional[Dict[str, int]] = None,
    train_overrides: Optional[Dict[str, Any]] = None,
) -> Dict:
    mesh = dict(mesh or audit_mesh_config())
    train = _base_train(mesh)
    # harness-level knobs (the lockstep simulator enables train.health so
    # the rank-0 monitor/flight-recorder construction paths are exercised
    # per simulated host); applied before the per-kind sections so those
    # keep the last word on their own keys
    train.update(dict(train_overrides or {}))
    if kind in ("ppo", "grpo"):
        method: Dict[str, Any] = {
            "name": "GRPOConfig" if kind == "grpo" else "PPOConfig",
            "num_rollouts": 8,
            "chunk_size": 8,
            "ppo_epochs": 1,
            "init_kl_coef": 0.02,
            "gen_kwargs": {
                "max_new_tokens": 6,
                "do_sample": True,
                "eos_token_id": 30,
                "pad_token_id": 31,
            },
        }
        if kind == "grpo":
            method["group_size"] = 4
            train["trainer"] = "GRPOTrainer"
        return {
            "model": {"model_type": "gpt2", "model_arch": dict(_CAUSAL_ARCH)},
            "train": train,
            "method": method,
        }
    if kind == "ilql":
        train["trainer"] = "ILQLTrainer"
        train["orchestrator"] = "OfflineOrchestrator"
        return {
            "model": {"model_type": "gpt2", "model_arch": dict(_CAUSAL_ARCH)},
            "train": train,
            "method": {
                "name": "ILQLConfig",
                "gen_kwargs": {
                    "max_new_tokens": 6,
                    "do_sample": False,
                    "eos_token_id": 30,
                    "pad_token_id": 31,
                },
            },
        }
    if kind == "seq2seq":
        train["trainer"] = "Seq2SeqPPOTrainer"
        return {
            "model": {"model_type": "t5", "model_arch": dict(_T5_ARCH)},
            "train": train,
            "method": {
                "name": "PPOConfig",
                "num_rollouts": 8,
                "chunk_size": 8,
                "ppo_epochs": 1,
                "init_kl_coef": 0.02,
                "gen_kwargs": {
                    "max_new_tokens": 5,
                    "do_sample": True,
                    "eos_token_id": 1,
                    "pad_token_id": 0,
                    "decoder_start_token_id": 0,
                },
            },
        }
    raise ValueError(f"unknown trainer kind {kind!r}; know {TRAINER_KINDS}")


def build_trainer(
    kind: str,
    mesh: Optional[Dict[str, int]] = None,
    train_overrides: Optional[Dict[str, Any]] = None,
):
    from trlx_tpu.data.configs import TRLConfig

    config = TRLConfig.from_dict(
        tiny_config_dict(kind, mesh, train_overrides=train_overrides)
    )
    if kind in ("ppo",):
        from trlx_tpu.trainer.ppo_trainer import PPOTrainer

        return PPOTrainer(config)
    if kind == "grpo":
        from trlx_tpu.trainer.grpo_trainer import GRPOTrainer

        return GRPOTrainer(config)
    if kind == "ilql":
        from trlx_tpu.trainer.ilql_trainer import ILQLTrainer

        return ILQLTrainer(config)
    from trlx_tpu.trainer.seq2seq_ppo_trainer import Seq2SeqPPOTrainer

    return Seq2SeqPPOTrainer(config)


@dataclass
class TracedProgram:
    subject: str  # e.g. "ppo.train_step"
    closed_jaxpr: Any
    mesh_axes: Set[str]
    # flat state-leaf count the step must donate; None = no donation rule
    n_donated_state_leaves: Optional[int] = None
    # flat keypath label per program input (make_jaxpr flattening order) —
    # lets value-contract engines (nan_flow) seed facts like "masks are
    # 0/1" and "adam nu is nonnegative" at the program boundary
    input_paths: Optional[List[str]] = None
    # mesh axis name -> size of the mesh the program was traced on — the
    # resource auditor's collective cost model needs participant counts
    mesh_shape: Optional[Dict[str, int]] = None
    # per-flat-input sharding divisor (total elements / per-device shard
    # elements, from the trainer's declared in_shardings) — the resource
    # auditor divides each input's bytes by this to get per-device HBM
    input_divisors: Optional[List[int]] = None
    # per-flat-input tuple of mesh-split dimensions (same order) — the
    # HLO auditor's spmd-concat-hazard walk only treats a concatenate as
    # the PR-2 shape when the concat dimension is one the mesh splits
    input_sharded_dims: Optional[List[Tuple[int, ...]]] = None
    # (file, line) of the traced callable's def — findings with no eqn to
    # anchor to (donation-ignored, alias-escape) attach here so inline
    # `# tpu-lint: disable=` directives still work
    def_site: Optional[Tuple[str, int]] = None
    # the jitted callable itself plus the abstract args it was traced
    # with — the HLO auditor AOT-lowers `jit_fn.lower(*example_args)`
    # to get the optimized post-SPMD module XLA actually emits (the
    # jaxpr above is intent; this is ground truth)
    jit_fn: Any = None
    example_args: Any = None


def callable_def_site(fn) -> Optional[Tuple[str, int]]:
    """(file, first line) of the function a jit wrapper wraps."""
    inner = getattr(fn, "__wrapped__", fn)
    code = getattr(inner, "__code__", None)
    if code is None:
        return None
    return code.co_filename, code.co_firstlineno


def _flat_sharding_info(arg_trees, sharding_trees) -> List[Tuple[int, Tuple[int, ...]]]:
    """Per-flat-leaf ``(divisor, sharded_dims)`` in make_jaxpr order.

    ``sharding_trees`` mirrors ``arg_trees``; an entry of ``None`` (or a
    leaf without ``shard_shape``) means replicated -> ``(1, ())``. The
    divisor is ``total elements / per-device shard elements``; the dims
    are the axes along which the per-device shard is strictly smaller
    than the global shape (i.e. the dimensions the mesh actually
    splits).
    """
    import math

    import jax

    info: List[Tuple[int, Tuple[int, ...]]] = []
    for args, shardings in zip(arg_trees, sharding_trees):
        leaves = jax.tree_util.tree_leaves(args)
        if shardings is None:
            info += [(1, ())] * len(leaves)
            continue
        sh_leaves = jax.tree_util.tree_leaves(
            shardings, is_leaf=lambda x: hasattr(x, "shard_shape")
        )
        if len(sh_leaves) == 1 and len(leaves) > 1:
            # one sharding for a whole tree (e.g. batch_sharding)
            sh_leaves = sh_leaves * len(leaves)
        for leaf, sh in zip(leaves, sh_leaves):
            shape = tuple(getattr(leaf, "shape", ()))
            if not hasattr(sh, "shard_shape") or not shape:
                info.append((1, ()))
                continue
            try:
                shard = sh.shard_shape(shape)
                total = math.prod(shape)
                per_dev = math.prod(shard)
                dims = tuple(
                    d for d, (g, s) in enumerate(zip(shape, shard)) if s < g
                )
                info.append((max(1, total // max(1, per_dev)), dims))
            except Exception:
                info.append((1, ()))
        info += [(1, ())] * (len(leaves) - min(len(leaves), len(sh_leaves)))
    return info


def flat_sharding_divisors(arg_trees, sharding_trees) -> List[int]:
    """Per-flat-leaf sharding divisor (total / per-device elements)."""
    return [d for d, _ in _flat_sharding_info(arg_trees, sharding_trees)]


def flat_sharded_dims(arg_trees, sharding_trees) -> List[Tuple[int, ...]]:
    """Per-flat-leaf tuple of mesh-split dimensions — lets the HLO
    auditor's concat-hazard walk tell a concat *along* a sharded axis
    (the PR-2 miscompile shape) from a benign local concat along a
    replicated one."""
    return [dims for _, dims in _flat_sharding_info(arg_trees, sharding_trees)]


def flat_input_paths(*trees, prefixes: Optional[Sequence[str]] = None) -> List[str]:
    """Flat keypath labels for argument trees, in make_jaxpr's
    flattening order."""
    import jax

    names: List[str] = []
    for i, tree in enumerate(trees):
        prefix = prefixes[i] if prefixes else f"arg{i}"
        for path, _leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
            names.append(prefix + jax.tree_util.keystr(path))
    return names


def _sds(tree):
    import jax

    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), tree
    )


def _ppo_minibatch_sds(trainer):
    import jax
    import jax.numpy as jnp

    from trlx_tpu.data.ppo_types import PPORolloutBatch

    B = trainer.config.train.batch_size
    Q = trainer.query_length
    R = trainer.gen_config.max_new_tokens
    i32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.int32)
    f32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.float32)
    return PPORolloutBatch(
        query_tokens=i32(B, Q),
        query_mask=i32(B, Q),
        response_tokens=i32(B, R),
        response_mask=i32(B, R),
        logprobs=f32(B, R),
        values=f32(B, R),
        rewards=f32(B, R),
    )


def _ilql_minibatch_sds(trainer):
    import jax
    import jax.numpy as jnp

    from trlx_tpu.data.ilql_types import ILQLBatch

    B = trainer.config.train.batch_size
    T = trainer.config.train.seq_length
    A = trainer.gen_config.max_new_tokens
    S = A + 1
    i32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.int32)
    f32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.float32)
    return ILQLBatch(
        input_ids=i32(B, T),
        attention_mask=i32(B, T),
        rewards=f32(B, A),
        states_ixs=i32(B, S),
        actions_ixs=i32(B, A),
        dones=i32(B, S),
        actions_mask=i32(B, A),
    )


def trace_train_step(kind: str, mesh: Optional[Dict[str, int]] = None):
    """Abstractly trace just one trainer's jitted train step on ``mesh``
    (the collective-divergence engine traces the same step on several
    meshes; the full program set would triple the tracing cost)."""
    import jax

    trainer = build_trainer(kind, mesh)
    state_sds = _sds(trainer.state)
    mb = _ilql_minibatch_sds(trainer) if kind == "ilql" else _ppo_minibatch_sds(trainer)
    return jax.make_jaxpr(trainer._train_step_jit)(state_sds, mb)


def trace_train_step_program(
    kind: str, mesh: Optional[Dict[str, int]] = None
) -> TracedProgram:
    """Like :func:`trace_train_step` but packaged as a
    :class:`TracedProgram` with the jit handle attached — the HLO
    auditor compiles the step on each mesh of the collective-divergence
    matrix (the PR-2 replica-sum only mis-lowered on meshes with a
    spare axis, so single-mesh compiled coverage is not enough)."""
    import jax

    trainer = build_trainer(kind, mesh)
    state_sds = _sds(trainer.state)
    mb = _ilql_minibatch_sds(trainer) if kind == "ilql" else _ppo_minibatch_sds(trainer)
    return TracedProgram(
        subject=f"{kind}.train_step",
        closed_jaxpr=jax.make_jaxpr(trainer._train_step_jit)(state_sds, mb),
        mesh_axes=set(trainer.mesh.axis_names),
        mesh_shape={k: int(v) for k, v in trainer.mesh.shape.items()},
        def_site=callable_def_site(trainer._train_step_jit),
        jit_fn=trainer._train_step_jit,
        example_args=(state_sds, mb),
    )


def concrete_minibatch(trainer, kind: str, seed: int = 0):
    """A concrete, numerically-plausible rollout minibatch for the
    sanitizer's eqn-level replay (abstract tracing can't evaluate
    values): logprobs are small negatives, values/rewards small normals,
    masks cover a realistic prefix of the response."""
    import numpy as np

    import jax.numpy as jnp

    from trlx_tpu.data.ilql_types import ILQLBatch
    from trlx_tpu.data.ppo_types import PPORolloutBatch

    rng = np.random.default_rng(seed)
    B = trainer.config.train.batch_size
    vocab = 30
    if kind == "ilql":
        T = trainer.config.train.seq_length
        A = trainer.gen_config.max_new_tokens
        S = A + 1
        return ILQLBatch(
            input_ids=jnp.asarray(rng.integers(1, vocab, (B, T)), jnp.int32),
            attention_mask=jnp.ones((B, T), jnp.int32),
            rewards=jnp.asarray(rng.normal(0, 0.5, (B, A)), jnp.float32),
            states_ixs=jnp.asarray(
                np.tile(np.arange(S), (B, 1)), jnp.int32
            ),
            actions_ixs=jnp.asarray(
                np.tile(np.arange(A), (B, 1)), jnp.int32
            ),
            dones=jnp.ones((B, S), jnp.int32),
            actions_mask=jnp.ones((B, A), jnp.int32),
        )
    Q = trainer.query_length
    R = trainer.gen_config.max_new_tokens
    lengths = rng.integers(max(1, R - 2), R + 1, B)
    response_mask = (np.arange(R)[None, :] < lengths[:, None]).astype(np.int32)
    return PPORolloutBatch(
        query_tokens=jnp.asarray(rng.integers(1, vocab, (B, Q)), jnp.int32),
        query_mask=jnp.ones((B, Q), jnp.int32),
        response_tokens=jnp.asarray(rng.integers(1, vocab, (B, R)), jnp.int32),
        response_mask=jnp.asarray(response_mask),
        logprobs=jnp.asarray(-np.abs(rng.normal(1.5, 0.7, (B, R))), jnp.float32),
        values=jnp.asarray(rng.normal(0, 0.3, (B, R)), jnp.float32),
        rewards=jnp.asarray(rng.normal(0, 0.5, (B, R)) * response_mask, jnp.float32),
    )


def trace_trainer(
    kind: str, mesh: Optional[Dict[str, int]] = None
) -> List[TracedProgram]:
    """Build one tiny trainer and abstractly trace its jitted programs."""
    import jax
    import jax.numpy as jnp

    from trlx_tpu.parallel.mesh import batch_sharding

    trainer = build_trainer(kind, mesh)
    axes = set(trainer.mesh.axis_names)
    mesh_shape = {k: int(v) for k, v in trainer.mesh.shape.items()}
    batch_sh = batch_sharding(trainer.mesh)
    state_sds = _sds(trainer.state)
    n_state = len(jax.tree_util.tree_leaves(state_sds))
    if kind == "ilql":
        mb = _ilql_minibatch_sds(trainer)
    else:
        mb = _ppo_minibatch_sds(trainer)

    step_paths = flat_input_paths(state_sds, mb, prefixes=("state", "batch"))
    programs = [
        TracedProgram(
            subject=f"{kind}.train_step",
            closed_jaxpr=jax.make_jaxpr(trainer._train_step_jit)(
                state_sds, mb
            ),
            mesh_axes=axes,
            n_donated_state_leaves=n_state,
            input_paths=step_paths,
            mesh_shape=mesh_shape,
            input_divisors=flat_sharding_divisors(
                (state_sds, mb), (trainer.state_shardings, batch_sh)
            ),
            input_sharded_dims=flat_sharded_dims(
                (state_sds, mb), (trainer.state_shardings, batch_sh)
            ),
            def_site=callable_def_site(trainer._train_step_jit),
            jit_fn=trainer._train_step_jit,
            example_args=(state_sds, mb),
        )
    ]

    B = trainer.config.train.batch_size
    Q = trainer.query_length
    prompt = jax.ShapeDtypeStruct((B, Q), jnp.int32)
    key = jax.ShapeDtypeStruct((2,), jnp.uint32)
    if kind == "ilql":
        bundle = {
            "params": _sds(trainer.state.params),
            "target": _sds(trainer.state.target_q_params),
        }
        sample_jaxpr = jax.make_jaxpr(trainer._sample_jit)(
            bundle, prompt, prompt, key
        )
    else:
        sample_jaxpr = jax.make_jaxpr(trainer._sample_jit)(
            _sds(trainer.state.params), prompt, prompt, key
        )
    rollout_args = (
        (bundle, prompt, prompt, key)
        if kind == "ilql"
        else (_sds(trainer.state.params), prompt, prompt, key)
    )
    rollout_shardings = (
        (
            {
                "params": trainer.state_shardings.params,
                "target": trainer.state_shardings.target_q_params,
            }
            if kind == "ilql"
            else trainer.state_shardings.params
        ),
        batch_sh,
        batch_sh,
        None,
    )
    programs.append(
        TracedProgram(
            subject=f"{kind}.rollout",
            closed_jaxpr=sample_jaxpr,
            mesh_axes=axes,
            input_paths=flat_input_paths(
                *rollout_args,
                prefixes=("params", "prompt_ids", "prompt_mask", "key"),
            ),
            mesh_shape=mesh_shape,
            input_divisors=flat_sharding_divisors(
                rollout_args, rollout_shardings
            ),
            input_sharded_dims=flat_sharded_dims(
                rollout_args, rollout_shardings
            ),
            def_site=callable_def_site(trainer._sample_jit),
            jit_fn=trainer._sample_jit,
            example_args=rollout_args,
        )
    )

    if kind != "ilql":
        # the fused buffer pass (scan over stacked minibatches) is the
        # production train path — audit it too, with its own donation.
        # Under the streamed collect→train phase (docs/async_pipeline.md)
        # this same program runs the residual epochs 2..ppo_epochs, and
        # `train_step` above IS the streamed epoch-1 step — both streamed
        # dispatch modes are covered by these traces.
        stacked = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct((2,) + x.shape, x.dtype), mb
        )
        from trlx_tpu.parallel.mesh import stacked_batch_sharding

        programs.append(
            TracedProgram(
                subject=f"{kind}.train_phase",
                closed_jaxpr=jax.make_jaxpr(trainer._train_phase_jit)(
                    state_sds, stacked
                ),
                mesh_axes=axes,
                n_donated_state_leaves=n_state,
                input_paths=flat_input_paths(
                    state_sds, stacked, prefixes=("state", "batch")
                ),
                mesh_shape=mesh_shape,
                input_divisors=flat_sharding_divisors(
                    (state_sds, stacked),
                    (
                        trainer.state_shardings,
                        stacked_batch_sharding(trainer.mesh),
                    ),
                ),
                input_sharded_dims=flat_sharded_dims(
                    (state_sds, stacked),
                    (
                        trainer.state_shardings,
                        stacked_batch_sharding(trainer.mesh),
                    ),
                ),
                def_site=callable_def_site(trainer._train_phase_jit),
                jit_fn=trainer._train_phase_jit,
                example_args=(state_sds, stacked),
            )
        )
        # the streamed phase's behavior-policy snapshot (compute-dtype
        # cast + donation-safe per-leaf copy): every sampler/ref forward
        # of an overlapped phase consumes its output, so its dtype story
        # belongs in the audit
        params_sds = _sds(trainer.state.params)
        programs.append(
            TracedProgram(
                subject=f"{kind}.behavior_snapshot",
                closed_jaxpr=jax.make_jaxpr(
                    trainer._behavior_snapshot_jit
                )(params_sds),
                mesh_axes=axes,
                input_paths=flat_input_paths(
                    params_sds, prefixes=("params",)
                ),
                mesh_shape=mesh_shape,
                input_divisors=flat_sharding_divisors(
                    (params_sds,), (trainer.state_shardings.params,)
                ),
                input_sharded_dims=flat_sharded_dims(
                    (params_sds,), (trainer.state_shardings.params,)
                ),
                def_site=callable_def_site(trainer._behavior_snapshot_jit),
                jit_fn=trainer._behavior_snapshot_jit,
                example_args=(params_sds,),
            )
        )
    if kind == "ppo":
        # the continuous-batching rollout engine's jitted programs
        # (docs/inference.md) — traced once on the ppo trainer (every
        # causal family shares the same engine code path)
        programs.extend(_trace_engine_programs(trainer, kind, mesh_shape))
        # the async actor–learner programs (docs/async_pipeline.md),
        # traced once on the ppo trainer (the only kind the async mode
        # composes with today): the mid-generation weight push the
        # actors receive, and the stream store's donating versioned
        # landing program
        programs.extend(_trace_async_programs(trainer, kind, mesh_shape))
    return programs


def _trace_async_programs(trainer, kind: str, mesh_shape) -> List[TracedProgram]:
    """Trace the asynchronous actor–learner path's jitted programs
    (``trlx_tpu/trainer/async_rl.py``, docs/async_pipeline.md):

    - ``async_weight_push`` — the refreshed behavior policy pushed to
      the actors MID-generation (compute-dtype cast + donation-safe
      per-leaf copy; a separate jit instance from the phase-start
      snapshot, so the program the async path actually dispatches is
      what gets audited);
    - ``versioned_land`` — the stream store's landing program
      (``pipeline/ppo_buffer.py::land_rows``): one fused, store-DONATING
      ``dynamic_update_slice`` write of a harvest chunk at a dynamic
      offset (the device half of the version-tagged landing; the
      version column itself is host-side plan metadata).

    Traced regardless of the configured ``train.async_rl`` — like the
    engine programs, the audit covers the async path even while a run
    defaults to synchronous.
    """
    import jax
    import jax.numpy as jnp

    from trlx_tpu.parallel.mesh import batch_sharding
    from trlx_tpu.pipeline import ppo_buffer

    axes = set(trainer.mesh.axis_names)
    batch_sh = batch_sharding(trainer.mesh)
    params_sds = _sds(trainer.state.params)
    mb = _ppo_minibatch_sds(trainer)
    # a two-chunk stream store with one harvest-chunk landing at a
    # dynamic offset — the steady-state shape pair of a streamed phase
    store_sds = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct((2 * x.shape[0],) + x.shape[1:], x.dtype),
        mb,
    )
    offset_sds = jax.ShapeDtypeStruct((), jnp.int32)
    land_args = (store_sds, mb, offset_sds)
    return [
        TracedProgram(
            subject=f"{kind}.async_weight_push",
            closed_jaxpr=jax.make_jaxpr(trainer._weight_push_jit)(
                params_sds
            ),
            mesh_axes=axes,
            input_paths=flat_input_paths(params_sds, prefixes=("params",)),
            mesh_shape=mesh_shape,
            input_divisors=flat_sharding_divisors(
                (params_sds,), (trainer.state_shardings.params,)
            ),
            input_sharded_dims=flat_sharded_dims(
                (params_sds,), (trainer.state_shardings.params,)
            ),
            def_site=callable_def_site(trainer._weight_push_jit),
            jit_fn=trainer._weight_push_jit,
            example_args=(params_sds,),
        ),
        TracedProgram(
            subject=f"{kind}.versioned_land",
            closed_jaxpr=jax.make_jaxpr(ppo_buffer._land_rows_jit)(
                *land_args
            ),
            mesh_axes=axes,
            n_donated_state_leaves=len(
                jax.tree_util.tree_leaves(store_sds)
            ),
            input_paths=flat_input_paths(
                *land_args, prefixes=("store", "chunk", "offset")
            ),
            mesh_shape=mesh_shape,
            input_divisors=flat_sharding_divisors(
                land_args, (batch_sh, batch_sh, None)
            ),
            input_sharded_dims=flat_sharded_dims(
                land_args, (batch_sh, batch_sh, None)
            ),
            def_site=callable_def_site(ppo_buffer._land_rows_jit),
            jit_fn=ppo_buffer._land_rows_jit,
            example_args=land_args,
        ),
    ]


def _trace_engine_programs(trainer, kind: str, mesh_shape) -> List[TracedProgram]:
    """Trace the continuous-batching engine's prefill / decode_step /
    refill (slot-recycle) programs (``trlx_tpu/inference/engine.py``).

    The engine is built from the trainer's model/shardings regardless of
    the configured ``train.rollout`` engine — the audit covers the
    continuous path even while a run defaults to ``fixed``. Donation:
    prefill/decode take (params, state) with the STATE donated, which the
    donation rule (state-first contract) cannot express — only ``refill``
    (state-first) carries the donation contract here.
    """
    import jax
    import jax.numpy as jnp

    from trlx_tpu.parallel.mesh import batch_sharding

    axes = set(trainer.mesh.axis_names)
    engine = trainer.rollout_engine_obj
    state_sds = jax.eval_shape(engine._make_state)
    params_sds = _sds(trainer.state.params)
    A, C, Q = engine.admit_width, engine.harvest_width, engine.Q
    i32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.int32)
    key_sds = jax.ShapeDtypeStruct((2,), jnp.uint32)
    state_sh = engine.state_sharding()
    batch_sh = batch_sharding(trainer.mesh)
    params_sh = trainer.state_shardings.params
    n_state = len(jax.tree_util.tree_leaves(state_sds))

    prefill_args = (
        params_sds, state_sds, i32(A), i32(A, Q), i32(A, Q), i32(A),
        i32(A), key_sds,
    )
    prefill_prefixes = (
        "params", "state", "slots", "prompt_ids", "prompt_mask",
        "rows", "turns", "phase_key",
    )
    prefill_shardings = (
        params_sh, state_sh, None, batch_sh, batch_sh, None, None, None,
    )
    decode_args = (params_sds, state_sds)
    refill_args = (state_sds, i32(C))
    return [
        TracedProgram(
            subject=f"{kind}.engine_prefill",
            closed_jaxpr=jax.make_jaxpr(engine.prefill_jit)(*prefill_args),
            mesh_axes=axes,
            input_paths=flat_input_paths(
                *prefill_args, prefixes=prefill_prefixes
            ),
            mesh_shape=mesh_shape,
            input_divisors=flat_sharding_divisors(
                prefill_args, prefill_shardings
            ),
            input_sharded_dims=flat_sharded_dims(
                prefill_args, prefill_shardings
            ),
            def_site=callable_def_site(engine.prefill_jit),
            jit_fn=engine.prefill_jit,
            example_args=prefill_args,
        ),
        TracedProgram(
            subject=f"{kind}.engine_decode_step",
            closed_jaxpr=jax.make_jaxpr(engine.decode_step_jit)(
                *decode_args
            ),
            mesh_axes=axes,
            input_paths=flat_input_paths(
                *decode_args, prefixes=("params", "state")
            ),
            mesh_shape=mesh_shape,
            input_divisors=flat_sharding_divisors(
                decode_args, (params_sh, state_sh)
            ),
            input_sharded_dims=flat_sharded_dims(
                decode_args, (params_sh, state_sh)
            ),
            def_site=callable_def_site(engine.decode_step_jit),
            jit_fn=engine.decode_step_jit,
            example_args=decode_args,
        ),
        TracedProgram(
            subject=f"{kind}.engine_refill",
            closed_jaxpr=jax.make_jaxpr(engine.refill_jit)(*refill_args),
            mesh_axes=axes,
            n_donated_state_leaves=n_state,
            input_paths=flat_input_paths(
                *refill_args, prefixes=("state", "slots")
            ),
            mesh_shape=mesh_shape,
            input_divisors=flat_sharding_divisors(
                refill_args, (state_sh, None)
            ),
            input_sharded_dims=flat_sharded_dims(
                refill_args, (state_sh, None)
            ),
            def_site=callable_def_site(engine.refill_jit),
            jit_fn=engine.refill_jit,
            example_args=refill_args,
        ),
    ] + _trace_chunked_prefill_programs(
        trainer, engine, kind, mesh_shape, shared=False
    ) + _trace_serving_engine_programs(
        trainer, engine, kind, mesh_shape
    ) + _trace_spec_engine_programs(trainer, engine, kind, mesh_shape)


def _trace_chunked_prefill_programs(
    trainer, base_engine, kind: str, mesh_shape, shared: bool
) -> List[TracedProgram]:
    """Trace the CHUNKED prefill variant (``rollout.prefill_chunk > 0``,
    docs/inference.md "Chunked prefill"): the same engine geometry with
    the monolithic admission prefill replaced by the
    ``prefill_chunks`` scan (lax.cond-gated block-aligned prompt-column
    chunks) plus the always-run ``prefill_finish`` program. Separate
    subjects with their own resource-budget entries — the default
    engine's ``engine_prefill`` stays byte-identical, and the engine-7
    FLOP count pins the chunked pair strictly below the monolithic
    entry at the audit shape (attention runs on the prompt-wide view,
    never the full Q+R capacity).
    """
    import jax
    import jax.numpy as jnp

    from trlx_tpu.inference.engine import ContinuousBatchingEngine
    from trlx_tpu.parallel.mesh import batch_sharding

    engine = ContinuousBatchingEngine(
        apply_fn=base_engine._apply_fn,
        init_cache_fn=base_engine._init_cache_fn,
        gen_config=base_engine.gen_config,
        query_length=base_engine.Q,
        vocab_size=base_engine.vocab_size,
        num_slots=base_engine.num_slots,
        admit_width=base_engine.admit_width,
        harvest_width=base_engine.harvest_width,
        block_size=base_engine.block_size,
        mesh=base_engine.mesh,
        param_shardings=base_engine._param_shardings,
        cache_sharding=base_engine._cache_sharding,
        with_values=base_engine.with_values,
        prefix_pool_blocks=(
            max(2, base_engine.Q // base_engine.block_size)
            if shared
            else 0
        ),
        stream_taps=shared,
        prefill_chunk=max(1, base_engine.Q // 2),
    )
    axes = set(trainer.mesh.axis_names)
    state_sds = jax.eval_shape(engine._make_state)
    params_sds = _sds(trainer.state.params)
    A, Q, nb = engine.admit_width, engine.Q, engine.n_blocks
    n_scan = engine.n_prefill_chunks - 1
    i32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.int32)
    key_sds = jax.ShapeDtypeStruct((2,), jnp.uint32)
    state_sh = engine.state_sharding()
    batch_sh = batch_sharding(trainer.mesh)
    params_sh = trainer.state_shardings.params
    suffix = "_shared" if shared else ""

    chunks_args = (
        params_sds, state_sds, i32(A), i32(A, Q), i32(A, Q), i32(A),
        jax.ShapeDtypeStruct((max(1, n_scan),), jnp.bool_),
    )
    chunks_prefixes = (
        "params", "state", "slots", "prompt_ids", "prompt_mask",
        "turns", "need",
    )
    chunks_shardings = (
        params_sh, state_sh, None, batch_sh, batch_sh, None, None,
    )
    finish_args = (
        params_sds, state_sds, i32(A), i32(A, Q), i32(A, Q), i32(A),
        i32(A), key_sds,
    )
    finish_prefixes = (
        "params", "state", "slots", "prompt_ids", "prompt_mask",
        "rows", "turns", "phase_key",
    )
    finish_shardings = (
        params_sh, state_sh, None, batch_sh, batch_sh, None, None, None,
    )
    if shared:
        chunks_args += (i32(A, nb), i32(A, nb))
        chunks_prefixes += ("shared_map", "publish_map")
        chunks_shardings += (None, None)
        finish_args += (i32(A, nb), i32(A, nb))
        finish_prefixes += ("shared_map", "publish_map")
        finish_shardings += (None, None)

    out: List[TracedProgram] = []
    if engine.prefill_chunks_jit is not None and n_scan > 0:
        out.append(
            TracedProgram(
                subject=f"{kind}.engine_prefill_chunked{suffix}",
                closed_jaxpr=jax.make_jaxpr(engine.prefill_chunks_jit)(
                    *chunks_args
                ),
                mesh_axes=axes,
                input_paths=flat_input_paths(
                    *chunks_args, prefixes=chunks_prefixes
                ),
                mesh_shape=mesh_shape,
                input_divisors=flat_sharding_divisors(
                    chunks_args, chunks_shardings
                ),
                input_sharded_dims=flat_sharded_dims(
                    chunks_args, chunks_shardings
                ),
                def_site=callable_def_site(engine.prefill_chunks_jit),
                jit_fn=engine.prefill_chunks_jit,
                example_args=chunks_args,
            )
        )
    out.append(
        TracedProgram(
            subject=f"{kind}.engine_prefill_finish{suffix}",
            closed_jaxpr=jax.make_jaxpr(engine.prefill_finish_jit)(
                *finish_args
            ),
            mesh_axes=axes,
            input_paths=flat_input_paths(
                *finish_args, prefixes=finish_prefixes
            ),
            mesh_shape=mesh_shape,
            input_divisors=flat_sharding_divisors(
                finish_args, finish_shardings
            ),
            input_sharded_dims=flat_sharded_dims(
                finish_args, finish_shardings
            ),
            def_site=callable_def_site(engine.prefill_finish_jit),
            jit_fn=engine.prefill_finish_jit,
            example_args=finish_args,
        )
    )
    return out


def _trace_serving_engine_programs(
    trainer, engine, kind: str, mesh_shape
) -> List[TracedProgram]:
    """Trace the SERVING-tier engine variant (``trlx_tpu/serving``,
    docs/serving.md): the same engine built with a shared-prefix pool
    (``prefix_pool_blocks > 0`` — the cache layers carry the
    replicated ``shared_k/v`` pool plus share/publish tables, and
    prefill takes the per-row sharing maps) and streaming taps
    (``decode_step`` additionally returns this step's (token, live)
    emissions), plus the placeholder ``release`` program. The trainer
    collect path never builds this variant — its three programs above
    stay byte-identical — so these four are separate subjects with
    their own resource-budget entries.
    """
    import jax
    import jax.numpy as jnp

    from trlx_tpu.inference.engine import ContinuousBatchingEngine
    from trlx_tpu.parallel.mesh import batch_sharding

    serving_engine = ContinuousBatchingEngine(
        apply_fn=engine._apply_fn,
        init_cache_fn=engine._init_cache_fn,
        gen_config=engine.gen_config,
        query_length=engine.Q,
        vocab_size=engine.vocab_size,
        num_slots=engine.num_slots,
        admit_width=engine.admit_width,
        harvest_width=engine.harvest_width,
        block_size=engine.block_size,
        mesh=engine.mesh,
        param_shardings=engine._param_shardings,
        cache_sharding=engine._cache_sharding,
        with_values=engine.with_values,
        prefix_pool_blocks=max(2, engine.Q // engine.block_size),
        stream_taps=True,
    )
    axes = set(trainer.mesh.axis_names)
    state_sds = jax.eval_shape(serving_engine._make_state)
    params_sds = _sds(trainer.state.params)
    A, C, Q = (
        serving_engine.admit_width,
        serving_engine.harvest_width,
        serving_engine.Q,
    )
    nb = serving_engine.n_blocks
    i32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.int32)
    key_sds = jax.ShapeDtypeStruct((2,), jnp.uint32)
    state_sh = serving_engine.state_sharding()
    batch_sh = batch_sharding(trainer.mesh)
    params_sh = trainer.state_shardings.params
    n_state = len(jax.tree_util.tree_leaves(state_sds))

    prefill_args = (
        params_sds, state_sds, i32(A), i32(A, Q), i32(A, Q), i32(A),
        i32(A), key_sds, i32(A, nb), i32(A, nb),
    )
    prefill_prefixes = (
        "params", "state", "slots", "prompt_ids", "prompt_mask",
        "rows", "turns", "phase_key", "shared_map", "publish_map",
    )
    prefill_shardings = (
        params_sh, state_sh, None, batch_sh, batch_sh, None, None,
        None, None, None,
    )
    decode_args = (params_sds, state_sds)
    refill_args = (state_sds, i32(C))
    release_args = (state_sds, i32(A))
    return [
        TracedProgram(
            subject=f"{kind}.engine_prefill_shared",
            closed_jaxpr=jax.make_jaxpr(serving_engine.prefill_jit)(
                *prefill_args
            ),
            mesh_axes=axes,
            input_paths=flat_input_paths(
                *prefill_args, prefixes=prefill_prefixes
            ),
            mesh_shape=mesh_shape,
            input_divisors=flat_sharding_divisors(
                prefill_args, prefill_shardings
            ),
            input_sharded_dims=flat_sharded_dims(
                prefill_args, prefill_shardings
            ),
            def_site=callable_def_site(serving_engine.prefill_jit),
            jit_fn=serving_engine.prefill_jit,
            example_args=prefill_args,
        ),
        TracedProgram(
            subject=f"{kind}.engine_decode_step_stream",
            closed_jaxpr=jax.make_jaxpr(serving_engine.decode_step_jit)(
                *decode_args
            ),
            mesh_axes=axes,
            input_paths=flat_input_paths(
                *decode_args, prefixes=("params", "state")
            ),
            mesh_shape=mesh_shape,
            input_divisors=flat_sharding_divisors(
                decode_args, (params_sh, state_sh)
            ),
            input_sharded_dims=flat_sharded_dims(
                decode_args, (params_sh, state_sh)
            ),
            def_site=callable_def_site(serving_engine.decode_step_jit),
            jit_fn=serving_engine.decode_step_jit,
            example_args=decode_args,
        ),
        TracedProgram(
            subject=f"{kind}.engine_refill_shared",
            closed_jaxpr=jax.make_jaxpr(serving_engine.refill_jit)(
                *refill_args
            ),
            mesh_axes=axes,
            n_donated_state_leaves=n_state,
            input_paths=flat_input_paths(
                *refill_args, prefixes=("state", "slots")
            ),
            mesh_shape=mesh_shape,
            input_divisors=flat_sharding_divisors(
                refill_args, (state_sh, None)
            ),
            input_sharded_dims=flat_sharded_dims(
                refill_args, (state_sh, None)
            ),
            def_site=callable_def_site(serving_engine.refill_jit),
            jit_fn=serving_engine.refill_jit,
            example_args=refill_args,
        ),
        TracedProgram(
            subject=f"{kind}.engine_release",
            closed_jaxpr=jax.make_jaxpr(serving_engine.release_jit)(
                *release_args
            ),
            mesh_axes=axes,
            n_donated_state_leaves=n_state,
            input_paths=flat_input_paths(
                *release_args, prefixes=("state", "slots")
            ),
            mesh_shape=mesh_shape,
            input_divisors=flat_sharding_divisors(
                release_args, (state_sh, None)
            ),
            input_sharded_dims=flat_sharded_dims(
                release_args, (state_sh, None)
            ),
            def_site=callable_def_site(serving_engine.release_jit),
            jit_fn=serving_engine.release_jit,
            example_args=release_args,
        ),
    ] + _trace_chunked_prefill_programs(
        trainer, serving_engine, kind, mesh_shape, shared=True
    )


def _trace_spec_engine_programs(
    trainer, engine, kind: str, mesh_shape
) -> List[TracedProgram]:
    """Trace the speculative-decoding ``verify_step`` program
    (docs/inference.md "Speculative decoding"): the multi-token
    drafted verify pass that replaces ``decode_step`` when the
    host-side drafter proposed tokens. Neither the trainer collect
    path nor the default serving build compiles it unless
    ``rollout.spec_decode.enabled`` — so like the serving tier above,
    spec engines are constructed separately here and the default
    engines' subjects stay byte-identical. Two variants:

    - ``engine_verify_step`` — trainer-shaped build (no prefix pool),
      the program behind tier-1 spec-on/spec-off bitwise parity;
    - ``engine_verify_step_shared`` — serving-shaped build (shared
      pool + streaming taps), whose cache state additionally carries
      the replicated shared-block pool the verify gather reads
      through.
    """
    import jax
    import jax.numpy as jnp

    from trlx_tpu.inference.engine import ContinuousBatchingEngine
    from trlx_tpu.parallel.mesh import batch_sharding

    axes = set(trainer.mesh.axis_names)
    params_sds = _sds(trainer.state.params)
    params_sh = trainer.state_shardings.params
    batch_sh = batch_sharding(trainer.mesh)
    i32 = lambda *s: jax.ShapeDtypeStruct(s, jnp.int32)

    common = dict(
        apply_fn=engine._apply_fn,
        init_cache_fn=engine._init_cache_fn,
        gen_config=engine.gen_config,
        query_length=engine.Q,
        vocab_size=engine.vocab_size,
        num_slots=engine.num_slots,
        admit_width=engine.admit_width,
        harvest_width=engine.harvest_width,
        block_size=engine.block_size,
        mesh=engine.mesh,
        param_shardings=engine._param_shardings,
        cache_sharding=engine._cache_sharding,
        with_values=engine.with_values,
        spec_max_draft=4,
    )
    out: List[TracedProgram] = []
    for suffix, extra in (
        ("", {}),
        (
            "_shared",
            dict(
                prefix_pool_blocks=max(2, engine.Q // engine.block_size),
                stream_taps=True,
            ),
        ),
    ):
        spec_engine = ContinuousBatchingEngine(**common, **extra)
        if spec_engine.verify_step_jit is None:
            continue  # spec_max_draft clamped to 0 (R == 1)
        state_sds = jax.eval_shape(spec_engine._make_state)
        state_sh = spec_engine.state_sharding()
        B, D = spec_engine.num_slots, spec_engine.spec_max_draft
        verify_args = (params_sds, state_sds, i32(B, D), i32(B))
        verify_prefixes = ("params", "state", "draft", "draft_len")
        verify_shardings = (params_sh, state_sh, batch_sh, batch_sh)
        out.append(
            TracedProgram(
                subject=f"{kind}.engine_verify_step{suffix}",
                closed_jaxpr=jax.make_jaxpr(spec_engine.verify_step_jit)(
                    *verify_args
                ),
                mesh_axes=axes,
                input_paths=flat_input_paths(
                    *verify_args, prefixes=verify_prefixes
                ),
                mesh_shape=mesh_shape,
                input_divisors=flat_sharding_divisors(
                    verify_args, verify_shardings
                ),
                input_sharded_dims=flat_sharded_dims(
                    verify_args, verify_shardings
                ),
                def_site=callable_def_site(spec_engine.verify_step_jit),
                jit_fn=spec_engine.verify_step_jit,
                example_args=verify_args,
            )
        )
    return out


def trace_all(
    kinds: Optional[Sequence[str]] = None,
    mesh: Optional[Dict[str, int]] = None,
) -> Iterator[TracedProgram]:
    for kind in kinds or TRAINER_KINDS:
        yield from trace_trainer(kind, mesh)
