"""Donation-safety checker: use-after-donate, wasted and leaking aliases.

Engine 7 of ``trlx_tpu.analysis``. Buffer donation is the TPU port's
memory contract (the ``donation`` rule already *requires* it for train
steps) — but donation done wrong fails silently, off-device, or only on
real hardware. Three rules close the gap, riding the PR-1/PR-2 traced
programs plus an AST pass over the untraced trainer/orchestrator loops:

- ``use-after-donate`` (AST, host code): a pytree read after being passed
  to a donating jitted callable without rebinding the result first. The
  donating callables are *discovered per module* from
  ``jax.jit(..., donate_argnums=...)`` assignments, so the rule tracks
  the repo's own step functions without a hand-kept list. The walk is
  linear per function (loop-carried flows are not modeled); false
  positives silence with ``# tpu-lint: disable=use-after-donate``.
- ``donation-ignored`` (jaxpr): a donated input with no shape/dtype-
  matching output — XLA cannot reuse the buffer and only warns at
  runtime; the donation promise silently buys nothing.
- ``alias-escape`` (jaxpr): a program output that IS a non-donated input
  (pjit input-forwarding) — the caller receives an alias of a buffer it
  does not own, the exact PR-3 behavior-snapshot hazard: a later
  donating step invalidates every holder of the forwarded output.

Jaxpr findings anchor to the traced callable's ``def`` line (the
harness's ``def_site``), so inline suppression works there too.
"""

from __future__ import annotations

import ast
import os
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple

from trlx_tpu.analysis.findings import Finding, filter_suppressed
from trlx_tpu.analysis.registry import get_rule

# jit spellings whose donate_argnums mark an assigned callable as donating
_JIT_SUFFIXES = ("jit", "pjit")


# ----------------------------- jaxpr rules ------------------------------- #

def _donating_pjit(closed_jaxpr):
    """(inner jaxpr, donated mask) of a traced jitted callable, or
    (outer jaxpr, all-False) when no pjit wrapper is present."""
    outer = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    pjit_eqns = [e for e in outer.eqns if e.primitive.name == "pjit"]
    if len(outer.eqns) == 1 and pjit_eqns:
        eqn = pjit_eqns[0]
        inner = eqn.params["jaxpr"].jaxpr
        donated = list(eqn.params.get("donated_invars", ()))
        donated += [False] * (len(inner.invars) - len(donated))
        return inner, donated
    return outer, [False] * len(outer.invars)


def _path_label(input_paths: Optional[Sequence[str]], i: int) -> str:
    if input_paths and i < len(input_paths):
        return input_paths[i]
    return f"input[{i}]"


def check_donation_ignored(
    closed_jaxpr,
    subject: str,
    input_paths: Optional[Sequence[str]] = None,
    def_site: Optional[Tuple[str, int]] = None,
) -> List[Finding]:
    """Donated inputs XLA cannot reuse: no output shares their
    shape+dtype (aliasing requires an exact buffer match)."""
    rule = get_rule("donation-ignored")
    inner, donated = _donating_pjit(closed_jaxpr)
    if not any(donated):
        return []
    out_pool: Dict[Tuple, int] = {}
    for v in inner.outvars:
        if hasattr(v, "val"):
            continue
        key = (tuple(v.aval.shape), str(v.aval.dtype))
        out_pool[key] = out_pool.get(key, 0) + 1
    findings: List[Finding] = []
    file, line = def_site or (None, None)
    for i, (v, don) in enumerate(zip(inner.invars, donated)):
        if not don:
            continue
        key = (tuple(v.aval.shape), str(v.aval.dtype))
        if out_pool.get(key, 0) > 0:
            out_pool[key] -= 1
            continue
        findings.append(
            Finding(
                rule=rule.id,
                message=(
                    f"donated buffer `{_path_label(input_paths, i)}` "
                    f"(shape {tuple(v.aval.shape)}, {v.aval.dtype}) has no "
                    "same-shape/dtype output to reuse it — XLA ignores the "
                    "donation (silent HBM waste it only warns about at "
                    "runtime); stop donating this argument or return an "
                    "updated value for it"
                ),
                severity=rule.severity,
                file=file,
                line=line,
                subject=subject,
                engine="donation",
            )
        )
    return findings


def check_alias_escape(
    closed_jaxpr,
    subject: str,
    input_paths: Optional[Sequence[str]] = None,
    def_site: Optional[Tuple[str, int]] = None,
) -> List[Finding]:
    """Outputs that ARE non-donated inputs: pjit forwards the caller's
    buffer instead of materializing a fresh one (forwarding a *donated*
    input is intended aliasing and allowed). jax hoists pass-through
    outputs OUT of the pjit body, so the check runs on the outer jaxpr:
    an outer outvar that is an outer invar never went through the
    program at all — it is the caller's buffer, returned."""
    rule = get_rule("alias-escape")
    outer = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    donated_by_var: Dict[int, bool] = {}
    for eqn in outer.eqns:
        if eqn.primitive.name != "pjit":
            continue
        for v, don in zip(eqn.invars, eqn.params.get("donated_invars", ())):
            if not hasattr(v, "val"):
                donated_by_var[id(v)] = donated_by_var.get(id(v), False) or don
    in_index = {id(v): i for i, v in enumerate(outer.invars)}
    findings: List[Finding] = []
    file, line = def_site or (None, None)
    for o, v in enumerate(outer.outvars):
        if hasattr(v, "val"):
            continue
        i = in_index.get(id(v))
        if i is None or donated_by_var.get(id(v), False):
            continue
        findings.append(
            Finding(
                rule=rule.id,
                message=(
                    f"output {o} of `{subject}` is input "
                    f"`{_path_label(input_paths, i)}` forwarded unchanged — "
                    "the caller receives an ALIAS of a buffer it does not "
                    "own; a later donating step invalidates every holder "
                    "(the PR-3 snapshot hazard). Copy the leaf "
                    "(e.g. `x + 0`/`jnp.copy`) or donate the argument"
                ),
                severity=rule.severity,
                file=file,
                line=line,
                subject=subject,
                engine="donation",
            )
        )
    return findings


def audit_traced_programs(programs: Iterable[Any]):
    """Jaxpr-side donation rules over harness TracedPrograms; returns a
    :class:`~trlx_tpu.analysis.findings.Report`."""
    from trlx_tpu.analysis.findings import Report

    report = Report()
    findings: List[Finding] = []
    for traced in programs:
        report.covered.append(f"donation:{traced.subject}")
        findings += check_donation_ignored(
            traced.closed_jaxpr,
            traced.subject,
            traced.input_paths,
            traced.def_site,
        )
        findings += check_alias_escape(
            traced.closed_jaxpr,
            traced.subject,
            traced.input_paths,
            traced.def_site,
        )
    kept, suppressed = filter_suppressed(findings)
    report.extend(kept)
    report.suppressed += suppressed
    return report


# --------------------------- use-after-donate ---------------------------- #

def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _donate_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """donate_argnums of a jax.jit/pjit call, or None when absent."""
    func = _dotted(call.func)
    if func is None or func.split(".")[-1] not in _JIT_SUFFIXES:
        return None
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        value = kw.value
        if isinstance(value, ast.Constant) and isinstance(value.value, int):
            return (value.value,)
        if isinstance(value, (ast.Tuple, ast.List)):
            out = []
            for elt in value.elts:
                if isinstance(elt, ast.Constant) and isinstance(
                    elt.value, int
                ):
                    out.append(elt.value)
            return tuple(out)
    return None


class _DonatingCallables(ast.NodeVisitor):
    """Discover `<name> = jax.jit(fn, donate_argnums=...)` bindings; the
    bound name (attribute or local) is a donating callable."""

    def __init__(self) -> None:
        self.callables: Dict[str, Tuple[int, ...]] = {}

    def visit_Assign(self, node: ast.Assign) -> None:
        if isinstance(node.value, ast.Call):
            positions = _donate_positions(node.value)
            if positions:
                for target in node.targets:
                    name = None
                    if isinstance(target, ast.Attribute):
                        name = target.attr
                    elif isinstance(target, ast.Name):
                        name = target.id
                    if name:
                        self.callables[name] = positions
        self.generic_visit(node)


def _maximal_reads(node: ast.AST) -> List[Tuple[str, ast.AST]]:
    """Maximal dotted-name reads in an expression: `self.state.params`
    yields once, not its sub-chains."""
    reads: List[Tuple[str, ast.AST]] = []

    def walk(n: ast.AST) -> None:
        if isinstance(n, (ast.Attribute, ast.Name)):
            name = _dotted(n)
            if name is not None:
                reads.append((name, n))
                return  # do not descend into the chain's own .value
        for child in ast.iter_child_nodes(n):
            walk(child)

    walk(node)
    return reads


class _UseAfterDonateLinter:
    """Linear, per-function scan: a donating call kills its donated arg
    expressions; a read of a killed expression (or a field of it) before
    a rebinding assignment is a finding."""

    def __init__(
        self, path: str, subject: str, donating: Dict[str, Tuple[int, ...]]
    ) -> None:
        self.path = path
        self.subject = subject
        self.donating = donating
        self.dead: Dict[str, Tuple[int, str]] = {}  # expr -> (line, callee)
        self.findings: List[Finding] = []

    def _flag(self, expr: str, node: ast.AST) -> None:
        line, callee = self.dead[expr if expr in self.dead else next(
            d for d in self.dead
            if expr.startswith(d + ".") or d.startswith(expr + ".")
        )]
        rule = get_rule("use-after-donate")
        self.findings.append(
            Finding(
                rule=rule.id,
                message=(
                    f"`{_dotted(node) or expr}` is read after being donated "
                    f"to `{callee}` (line {line}) — the buffer was freed/"
                    "reused by XLA; rebind the call's result (e.g. "
                    f"`{expr}, ... = self.{callee}({expr}, ...)`) before "
                    "reading it"
                ),
                severity=rule.severity,
                file=self.path,
                line=getattr(node, "lineno", None),
                subject=self.subject,
                engine="donation",
            )
        )

    def _is_dead(self, name: str) -> bool:
        return any(
            name == d or name.startswith(d + ".") or d.startswith(name + ".")
            for d in self.dead
        )

    def _donations_in(self, node: ast.AST):
        """(donated expr, callee, arg node) triples for donating calls
        anywhere inside ``node``."""
        out = []
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            func = _dotted(sub.func)
            if func is None:
                continue
            callee = func.split(".")[-1]
            positions = self.donating.get(callee)
            if not positions:
                continue
            for pos in positions:
                if pos < len(sub.args):
                    expr = _dotted(sub.args[pos])
                    if expr:
                        out.append((expr, callee, sub.args[pos]))
        return out

    def _check_reads(self, node: ast.AST, exclude: Set[int]) -> None:
        for name, read_node in _maximal_reads(node):
            if id(read_node) in exclude:
                continue
            if isinstance(getattr(read_node, "ctx", None), ast.Store):
                continue
            if self._is_dead(name):
                self._flag(name, read_node)

    def _apply_targets(self, targets: Iterable[ast.AST]) -> None:
        for target in targets:
            elts = (
                target.elts
                if isinstance(target, (ast.Tuple, ast.List))
                else [target]
            )
            for elt in elts:
                name = _dotted(elt)
                if name:
                    for d in list(self.dead):
                        if d == name or d.startswith(name + "."):
                            del self.dead[d]

    def _header(self, stmt) -> List[ast.AST]:
        """The expressions a compound statement evaluates BEFORE its body
        — only donations here may kill state ahead of the body scan (a
        donation inside the body applies at its own statement; applying
        it early would flag body reads that precede it)."""
        if isinstance(stmt, (ast.If, ast.While)):
            return [stmt.test]
        if isinstance(stmt, ast.For):
            return [stmt.iter]
        if isinstance(stmt, ast.With):
            return [item.context_expr for item in stmt.items]
        return []

    def scan_block(self, stmts: Sequence[ast.stmt]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested defs have their own donation lifetimes
            # compound statements: handle only the header expressions
            # here, then scan each body in order (shared kill-state — a
            # branch that donates poisons the fall-through, conservatively)
            if isinstance(stmt, (ast.If, ast.While, ast.For, ast.With)):
                donations = []
                for header in self._header(stmt):
                    donations += self._donations_in(header)
                exclude = {id(n) for _, _, n in donations}
                for header in self._header(stmt):
                    self._check_reads(header, exclude)
                self._apply_donations(donations)
                if isinstance(stmt, ast.For):
                    self._apply_targets([stmt.target])
                self.scan_block(stmt.body)
                self.scan_block(getattr(stmt, "orelse", []))
            elif isinstance(stmt, ast.Try):
                self.scan_block(stmt.body)
                for handler in stmt.handlers:
                    self.scan_block(handler.body)
                self.scan_block(stmt.orelse)
                self.scan_block(stmt.finalbody)
            else:
                donations = self._donations_in(stmt)
                exclude = {id(n) for _, _, n in donations}
                self._check_reads(stmt, exclude)
                self._apply_donations(donations)
                if isinstance(stmt, ast.Assign):
                    self._apply_targets(stmt.targets)
                elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                    self._apply_targets([stmt.target])

    def _apply_donations(self, donations) -> None:
        for expr, callee, node in donations:
            self.dead[expr] = (getattr(node, "lineno", 0), callee)


def check_use_after_donate_source(
    source: str, path: str
) -> Tuple[List[Finding], int]:
    """Lint one module; returns (kept findings, suppressed count)."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return [], 0  # ast_lint already reports unparseable files
    discovery = _DonatingCallables()
    discovery.visit(tree)
    if not discovery.callables:
        return [], 0
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            linter = _UseAfterDonateLinter(
                path, f"{node.name}()", discovery.callables
            )
            linter.scan_block(node.body)
            findings.extend(linter.findings)
    return filter_suppressed(findings, {path: source.splitlines()})


def lint_paths(paths: Iterable[str]):
    """use-after-donate over Python files / trees; returns a Report."""
    from trlx_tpu.analysis.ast_lint import collect_py_files
    from trlx_tpu.analysis.findings import Report

    files = collect_py_files(paths)
    report = Report()
    for f in files:
        try:
            with open(f, encoding="utf-8") as fh:
                source = fh.read()
        except OSError:
            continue
        found, suppressed = check_use_after_donate_source(source, f)
        report.extend(found)
        report.suppressed += suppressed
    report.covered.append(f"donation:host[{len(files)} files]")
    return report


def audit_all(
    kinds: Optional[Sequence[str]] = None,
    paths: Optional[Sequence[str]] = None,
    programs=None,
):
    """Full donation engine: jaxpr rules over traced programs + the AST
    use-after-donate pass; returns a merged Report."""
    from trlx_tpu.analysis import harness
    from trlx_tpu.analysis.findings import Report

    report = Report()
    sub = audit_traced_programs(
        programs if programs is not None else harness.trace_all(kinds)
    )
    report.extend(sub.findings)
    report.covered += sub.covered
    report.suppressed += sub.suppressed
    default_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    host = lint_paths(paths or [default_root])
    report.extend(host.findings)
    report.covered += host.covered
    report.suppressed += host.suppressed
    return report
