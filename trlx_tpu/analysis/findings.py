"""Finding model + suppression + rendering for the static-analysis pass.

A ``Finding`` is one rule violation at (optionally) a source location.
Findings are machine-readable (``to_dict`` -> JSON) and human-readable
(``format_text``). Suppression is source-inline:

    x = stats["loss"].item()  # tpu-lint: disable=host-item

A directive names one or more comma-separated rule ids (or ``all``) and
silences findings of those rules **on that line only** — both engines
funnel through :func:`filter_suppressed`, so jaxpr-audit findings that
carry a source location honor the same syntax as AST-lint findings.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

SEVERITY_ERROR = "error"
SEVERITY_WARNING = "warning"

# version of the JSON findings artifact (`--json` / CI uploads). Bump when
# the shape changes: 2 added the field itself, deterministic finding/
# coverage ordering (dict-iteration order used to reorder the artifact
# between runs, defeating artifact diffs), and the optional `resources`
# payload of the --resources report.
JSON_SCHEMA_VERSION = 2

_DIRECTIVE_RE = re.compile(r"#\s*tpu-lint:\s*disable=([\w\-,\s]+)")


@dataclass
class Finding:
    """One rule violation.

    :param rule: registry id of the violated rule (e.g. ``host-item``).
    :param message: human sentence describing the violation.
    :param severity: ``error`` (fails the run) or ``warning`` (fails only
        under ``--strict``).
    :param file: repo-relative path when the finding anchors to source.
    :param line: 1-indexed line within ``file``.
    :param subject: what was analyzed — a traced program name
        (``ppo.train_step``), a param path, or a module path.
    :param engine: ``jaxpr`` or ``ast``.
    """

    rule: str
    message: str
    severity: str = SEVERITY_ERROR
    file: Optional[str] = None
    line: Optional[int] = None
    subject: Optional[str] = None
    engine: str = "ast"

    def to_dict(self) -> Dict:
        return {
            "rule": self.rule,
            "severity": self.severity,
            "message": self.message,
            "file": self.file,
            "line": self.line,
            "subject": self.subject,
            "engine": self.engine,
        }

    def format_text(self) -> str:
        loc = ""
        if self.file:
            loc = f"{self.file}:{self.line or '?'}: "
        subj = f" [{self.subject}]" if self.subject else ""
        return f"{loc}{self.severity}: {self.message} ({self.rule}){subj}"


@dataclass
class Report:
    """All findings of one analysis run, plus what was covered."""

    findings: List[Finding] = field(default_factory=list)
    covered: List[str] = field(default_factory=list)  # traced programs / files
    suppressed: int = 0
    # --resources payload: per-program ProgramResources.to_dict() rows
    resources: Optional[List[Dict]] = None

    def extend(self, findings: Sequence[Finding]) -> None:
        self.findings.extend(findings)

    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == SEVERITY_ERROR]

    def exit_code(self, strict: bool = False) -> int:
        if strict:
            return 1 if self.findings else 0
        return 1 if self.errors() else 0

    def sorted_findings(self) -> List[Finding]:
        """Deterministic rule-major ordering for the JSON artifact — dict
        iteration inside the engines reorders findings run-to-run, which
        breaks artifact diffs in CI."""
        return sorted(
            self.findings,
            key=lambda f: (
                f.rule,
                f.file or "",
                f.line or 0,
                f.subject or "",
                f.message,
            ),
        )

    def to_json(self) -> str:
        payload = {
            "schema_version": JSON_SCHEMA_VERSION,
            "findings": [f.to_dict() for f in self.sorted_findings()],
            "covered": sorted(self.covered),
            "suppressed": self.suppressed,
        }
        if self.resources is not None:
            payload["resources"] = sorted(
                self.resources, key=lambda r: r.get("subject", "")
            )
        return json.dumps(payload, indent=2)

    def format_text(self) -> str:
        lines = [f.format_text() for f in self.findings]
        lines.append(
            f"tpu-lint: {len(self.findings)} finding(s) "
            f"({len(self.errors())} error(s), {self.suppressed} suppressed) "
            f"across {len(self.covered)} subject(s)"
        )
        return "\n".join(lines)


def suppressed_rules_on_line(source_line: str) -> Optional[set]:
    """Rule ids disabled by an inline directive on ``source_line``;
    ``None`` when the line has no directive."""
    m = _DIRECTIVE_RE.search(source_line)
    if not m:
        return None
    return {r.strip() for r in m.group(1).split(",") if r.strip()}


def _read_lines(path: str) -> List[str]:
    """Source lines of ``path``, also trying the package root for the
    package-relative paths jaxpr-engine findings carry (their files are
    relativized against ``trlx_tpu/``, not the process CWD — without
    this, inline directives on jaxpr findings only worked when the
    analysis ran from inside the package)."""
    import os

    candidates = [path]
    if not os.path.isabs(path):
        candidates.append(
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), path)
        )
    for cand in candidates:
        try:
            with open(cand, encoding="utf-8") as fh:
                return fh.read().splitlines()
        except OSError:
            continue
    return []


def filter_suppressed(
    findings: Sequence[Finding],
    source_lines: Optional[Dict[str, List[str]]] = None,
) -> tuple:
    """Split findings into (kept, n_suppressed) honoring inline directives.

    ``source_lines`` maps file path -> list of lines; files not present are
    read lazily from disk (and skipped when unreadable, keeping the finding).
    """
    cache: Dict[str, List[str]] = dict(source_lines or {})
    kept: List[Finding] = []
    n_suppressed = 0
    for f in findings:
        if f.file is None or f.line is None:
            kept.append(f)
            continue
        if f.file not in cache:
            cache[f.file] = _read_lines(f.file)
        lines = cache[f.file]
        if 1 <= f.line <= len(lines):
            rules = suppressed_rules_on_line(lines[f.line - 1])
            if rules is not None and (f.rule in rules or "all" in rules):
                n_suppressed += 1
                continue
        kept.append(f)
    return kept, n_suppressed
