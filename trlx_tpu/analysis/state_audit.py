"""Checkpoint/resume state-coverage auditor (engine 15).

Proves the kill/resume parity contract (docs/resilience.md) over the
WHOLE mutable host-state surface, not just the params pytree the PR-9
canaries pin. Two halves, same shape as engines 11/13/14:

**Static half** — reuse engine 14's attribute-level class collector to
inventory every attribute written outside ``__init__`` on the classes
reachable from a trainer (trainer, orchestrator, rollout buffer,
continuous engine, QoS scheduler, prefix pool, drafters, health
monitor), then require each one to be exactly one of:

- **carried** — referenced inside a checkpoint-carry method
  (``state_dict``/``save``/``host_state_dict``/…) of the class or a
  base class, so it rides the checkpoint;
- **carried-via** — serialized field-by-field by ANOTHER class's carry
  method (declared in :data:`CARRIED_VIA`, e.g. ``_SeriesState`` inside
  ``HealthMonitor.state_dict``);
- **phase-reset** — reassigned wholesale by the class's declared
  phase-boundary reset method (:data:`PHASE_RESET_METHODS`), so it is
  dead at every checkpointable boundary;
- **reconstructed** — written only by ``_build_*``/``_setup_*``/
  ``_rebuild_*`` derivation methods that recompute it from config on
  restore;
- **ephemeral** — allowlisted in :data:`EPHEMERAL_CONTRACTS` with a
  written justification (telemetry counters, caches whose loss is
  parity-inert).

Anything else is a ``resume-state-gap`` error at its first write site.
A contract entry naming a dead attribute is ``stale-state-contract``.

**Dynamic half** — a generalized kill/resume differ: run each trainer's
canonical harness pass to a phase boundary, ``save()``, rebuild the
trainer from scratch, ``load()``, then run BOTH the resumed trainer and
the uninterrupted twin one more identically-seeded pass and deep-compare
the full live attribute trees (arrays by content hash). Any diverging
path is a ``resume-divergence`` error naming the owning attribute path
and both values. The same run fingerprints the checkpoint schema (state
pytree leaf shapes/dtypes + host-metadata key paths) and locks it into
the ``state_manifest`` section of ``analysis/budgets.json``
(``ckpt-schema-drift``; relock via ``--update-budgets`` with the usual
foreign-section-preserving merge).

``--plant-gap`` is the self-test: a planted uncheckpointed counter
threaded into the sampling schedule must be named by BOTH halves.
"""

from __future__ import annotations

import ast
import hashlib
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from trlx_tpu.analysis.ast_lint import collect_py_files
from trlx_tpu.analysis.concurrency import _ClassInfo, _collect_class
from trlx_tpu.analysis.findings import (
    Finding,
    Report,
    filter_suppressed,
)
from trlx_tpu.analysis.registry import ENGINE_STATE, get_rule

__all__ = [
    "audit_resume_state",
    "classify_surface",
    "lint_resume_state",
    "run_resume_differ",
    "format_state_text",
    "RESUME_SURFACE",
    "EPHEMERAL_CONTRACTS",
]

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO_ROOT = os.path.dirname(_PKG_ROOT)

#: the modules that together hold every object reachable from a live
#: trainer whose mutable host state the resume contract covers
RESUME_SURFACE = [
    "trlx_tpu/trainer/__init__.py",
    "trlx_tpu/trainer/ppo_trainer.py",
    "trlx_tpu/trainer/ilql_trainer.py",
    "trlx_tpu/trainer/grpo_trainer.py",
    "trlx_tpu/trainer/seq2seq_ppo_trainer.py",
    "trlx_tpu/orchestrator/__init__.py",
    "trlx_tpu/orchestrator/ppo_orchestrator.py",
    "trlx_tpu/orchestrator/offline_orchestrator.py",
    "trlx_tpu/inference/engine.py",
    "trlx_tpu/pipeline/ppo_buffer.py",
    "trlx_tpu/serving/scheduler.py",
    "trlx_tpu/serving/prefix_cache.py",
    "trlx_tpu/serving/spec_drafter.py",
    "trlx_tpu/telemetry/health.py",
]

#: methods whose body participates in the checkpoint-carry contract: a
#: ``self.X`` reference inside any of them (on the class or a base)
#: classifies X as carried
CARRY_METHODS = frozenset({
    "state_dict",
    "load_state_dict",
    "host_state_dict",
    "load_host_state_dict",
    "_save_metadata",
    "save",
    "load",
})

_INIT_METHODS = frozenset({"__init__", "__post_init__"})

#: method-name prefixes that mark deterministic reconstruction: these
#: derive their writes from config/static model structure, and restore
#: reruns them (jitted programs, shardings, parsed configs)
_REBUILD_PREFIXES = ("_build", "_setup", "_rebuild")

#: per-class phase-boundary reset methods: state written there is
#: reinitialized from the method's arguments at every phase start, so it
#: is dead at the inter-phase boundaries where checkpoints happen
PHASE_RESET_METHODS: Dict[str, Set[str]] = {
    # start_phase() reassigns the whole slot/queue/draft state from the
    # pushed params + phase key (docs/inference.md "phase lifecycle")
    "ContinuousBatchingEngine": {"start_phase"},
    # begin_stream() re-creates the landing store for the next phase;
    # clear_history() is the on-policy refresh that empties the staged
    # chunks before each re-collect — experience is re-gathered from
    # the carried rng/prompt-stream position, never restored (the PR-9
    # parity canary pins exactly this flow)
    "PPORolloutBuffer": {"begin_stream", "clear_history"},
    # reset() drops row histories at each phase boundary (EWMAs are
    # deliberately NOT written there — they must be carried instead)
    "NGramDrafter": {"reset"},
    "TrieDrafter": {"reset"},
    # reset_rollout_phase() re-arms the per-phase RNG cursor pair
    "PPOTrainer": {"reset_rollout_phase"},
}

#: attrs serialized field-by-field by another class's carry method —
#: the owning class has no state_dict of its own, but the state rides
#: the checkpoint anyway
CARRIED_VIA: Dict[Tuple[str, str], str] = {
    ("_SeriesState", attr): (
        "HealthMonitor.state_dict serializes every series "
        "field-by-field ({count, mean, var, window, flat_run})"
    )
    for attr in ("count", "mean", "var", "window", "flat_run")
}
CARRIED_VIA[("TokenBucket", "level")] = (
    "QoSScheduler.state_dict carries every bucket's level"
)

#: the ephemeral allowlist: (class, attr) -> written justification.
#: Every entry asserts that LOSING the attribute across kill/resume
#: cannot change any token, update, or schedule decision.
EPHEMERAL_CONTRACTS: Dict[Tuple[str, str], str] = {
    # ---- BaseRLTrainer ------------------------------------------------ #
    ("BaseRLTrainer", "_last_samples"): (
        "eval-time decoded sample cache for the logger; re-filled by "
        "the next evaluate() and never read by the train schedule"
    ),
    ("BaseRLTrainer", "eval_pipeline"): (
        "wiring performed by the driver (add_eval_pipeline) before "
        "learn(); a resumed run re-wires it the same way it was first "
        "wired — it is an input, not evolving state"
    ),
    ("BaseRLTrainer", "_phase_log"): (
        "run_dir --watch JSONL writer handle (run_ledger.py); an "
        "append-only sink whose rows are already on disk — reopened "
        "in append mode on rebuild"
    ),
    # ---- PPOTrainer --------------------------------------------------- #
    ("PPOTrainer", "_behavior_params"): (
        "phase-scoped behavior-policy snapshot: begin_streamed_phase "
        "re-captures it from the (checkpoint-carried) params at every "
        "phase start; dead at phase boundaries"
    ),
    ("PPOTrainer", "_stream"): (
        "phase-scoped streaming handle created by begin_streamed_phase "
        "and closed by finish_streamed_phase; the preemption contract "
        "drains it before any checkpoint"
    ),
    ("PPOTrainer", "_health_phase"): (
        "phase-scoped health-row accumulator, re-armed by "
        "begin_streamed_phase; observations it fed the monitor are "
        "carried inside health_monitor's state_dict"
    ),
    ("PPOTrainer", "_last_stream_seed"): (
        "debug echo of the last begin_streamed_phase seed; never read "
        "by the schedule"
    ),
    ("PPOTrainer", "_last_overlap_stats"): (
        "telemetry: overlap timing of the finished phase, logger-only"
    ),
    ("PPOTrainer", "_last_phase_mean_kl"): (
        "telemetry echo of the phase KL already carried as mean_kl; "
        "logger/monitor display only"
    ),
    ("PPOTrainer", "_phase_index"): (
        "display counter for flight records; learn() renumbers from "
        "the carried state.step on resume, and no seed or schedule "
        "derives from it"
    ),
    ("PPOTrainer", "_epoch0"): (
        "derived at learn() entry from the carried state.step "
        "(resume fast-forward); recomputed identically on restore"
    ),
    ("PPOTrainer", "_final_stats"): (
        "logger summary of the finished run; never read by training"
    ),
    ("PPOTrainer", "_phase_profiler"): (
        "wall-clock phase profiler (host timing only — timings are "
        "not reproducible across runs by definition)"
    ),
    ("PPOTrainer", "_profiling"): (
        "bool latch for the profiler session; tied to _phase_profiler"
    ),
    ("PPOTrainer", "logger"): (
        "run-scoped logger handle re-opened by learn(); sink, not state"
    ),
    ("PPOTrainer", "_rollout_params_cache"): (
        "memoized rollout-dtype cast keyed by the CARRIED "
        "state.params' identity; a cold cache recomputes the identical "
        "cast on first use after restore"
    ),
    ("PPOTrainer", "_bound_min_prompts"): (
        "prompt-budget binding performed by the driver before learn() "
        "(bind_prompt_budget); re-performed identically on rebuild"
    ),
    ("PPOTrainer", "gen_config"): (
        "rebound by bind_prompt_budget from config + tokenizer "
        "defaults; config-derived, not evolving"
    ),
    # ---- ILQLTrainer -------------------------------------------------- #
    ("ILQLTrainer", "_rollout_bundle_cache"): (
        "memoized rollout-dtype cast keyed by the CARRIED state "
        "params/target identity; recomputed identically on first use "
        "after restore"
    ),
    ("ILQLTrainer", "_chunk_index"): (
        "display counter for flight records; renumbered from the "
        "carried state.step on resume, feeds no seed"
    ),
    ("ILQLTrainer", "_final_stats"): (
        "logger summary of the finished run; never read by training"
    ),
    ("ILQLTrainer", "logger"): (
        "run-scoped logger handle re-opened by learn(); sink, not state"
    ),
    # ---- orchestrators ------------------------------------------------ #
    ("PPOOrchestrator", "_engine_error"): (
        "transient engine-failure capture consumed (re-raised) by the "
        "same collect phase that set it; never outlives a phase"
    ),
    ("PPOOrchestrator", "_rollout_writer"): (
        "background JSONL writer handle; close() is lifecycle, the "
        "rows already written are on disk"
    ),
    ("OfflineOrchestrator", "trainer"): (
        "back-reference wired once by the driver at construction time"
    ),
    # ---- continuous engine (non-phase-reset attrs) -------------------- #
    ("ContinuousBatchingEngine", "_chunk_flops"): (
        "memoized FLOP cost per chunk shape (pure function of config); "
        "refilled on first use after rebuild"
    ),
    # ---- QoS scheduler ------------------------------------------------ #
    ("QoSScheduler", "_queues"): (
        "in-flight request queues: the preemption contract drains the "
        "serving tier at phase boundaries, so queues are empty at "
        "every checkpointable point (clients re-submit after a kill)"
    ),
    ("QoSScheduler", "tenants"): (
        "default-tenant auto-registration cache; an unknown tenant "
        "re-registers with identical defaults on first touch"
    ),
    # ---- prefix pool -------------------------------------------------- #
    ("PrefixBlockPool", "_free"): (
        "device KV block freelist: the KV pool itself is not "
        "checkpointed, so block ids cannot meaningfully survive a "
        "restart; a cold pool only costs recomputed prefixes "
        "(performance), never changes a sampled token — sharing is "
        "parity-exact by construction (docs/inference.md)"
    ),
    ("PrefixBlockPool", "_nodes"): (
        "radix-trie node index over the uncheckpointed KV pool; see "
        "_free — cold-start cost only"
    ),
    ("PrefixBlockPool", "_root"): (
        "radix-trie root over the uncheckpointed KV pool; see _free"
    ),
    ("PrefixBlockPool", "_tick"): (
        "LRU recency clock for eviction order inside one process "
        "lifetime; eviction changes which prefixes are RECOMPUTED, "
        "never their values — parity-inert by the verify-exact "
        "sharing contract"
    ),
    ("PrefixBlockPool", "hits"): "telemetry counter (stats() row only)",
    ("PrefixBlockPool", "misses"): "telemetry counter (stats() row only)",
    ("PrefixBlockPool", "evictions"): (
        "telemetry counter (stats() row only)"
    ),
    # ---- drafters (telemetry only — EWMAs/probes are carried) --------- #
    ("NGramDrafter", "drafts"): "telemetry counter (stats() row only)",
    ("NGramDrafter", "draft_hits"): (
        "telemetry counter (stats() row only)"
    ),
    ("NGramDrafter", "degraded_draws"): (
        "telemetry counter (stats() row only)"
    ),
    ("TrieDrafter", "drafts"): "telemetry counter (stats() row only)",
    ("TrieDrafter", "draft_hits"): (
        "telemetry counter (stats() row only)"
    ),
    ("TrieDrafter", "trie_hits"): "telemetry counter (stats() row only)",
}

# attrs the DIFFER skips on top of the ephemeral contracts: identity /
# handle objects that can never compare equal across two processes yet
# carry no schedule state (the static half still classifies them)
_DIFFER_SKIP_ATTRS: Set[str] = {
    "logger",
    "flight_recorder",
    "_phase_log",
    "_phase_profiler",
    "_stream",
    "pool",  # TrieDrafter's pool back-reference (pool itself visited)
    # per-request wall-clock stamps for the latency histograms: real
    # time can never compare across two processes (statically they are
    # phase-reset — start_phase reassigns them every phase)
    "_req_times",
}


# ------------------------------ static half ------------------------------ #

@dataclass
class AttrClassification:
    """Where one mutable attribute landed in the resume taxonomy."""

    cls: str
    attr: str
    file: str
    line: int
    category: str  # carried|carried-via|phase-reset|reconstructed|ephemeral
    detail: str = ""


@dataclass
class _SurfaceClass:
    info: _ClassInfo
    bases: List[str]
    #: attrs referenced as ``self.X`` inside carry-method bodies
    carried_refs: Set[str]
    #: every attr the class assigns anywhere (incl. __init__) — the
    #: liveness set for stale-contract checks
    all_attrs: Set[str]


def _self_attr_refs(fn: ast.AST) -> Set[str]:
    """Every ``self.X`` referenced (read or written) inside ``fn``."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            out.add(node.attr)
    return out


def _collect_surface(
    paths: Sequence[str],
) -> Dict[str, _SurfaceClass]:
    """Parse ``paths`` into the per-class write/carry maps."""
    classes: Dict[str, _SurfaceClass] = {}
    for path in collect_py_files(paths):
        try:
            with open(path, encoding="utf-8") as fh:
                tree = ast.parse(fh.read())
        except (OSError, SyntaxError):
            continue
        rel = os.path.relpath(os.path.abspath(path), _REPO_ROOT)
        if not rel.startswith(".."):
            report_path = rel
        else:
            report_path = path
        for node in tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            info = _collect_class(node, report_path)
            bases = []
            for b in node.bases:
                try:
                    bases.append(ast.unparse(b).split("[")[0])
                except Exception:  # pragma: no cover - malformed base
                    continue
            carried: Set[str] = set()
            for name, fn in info.methods.items():
                if name in CARRY_METHODS:
                    carried |= _self_attr_refs(fn)
            all_attrs = {w.attr for w in info.writes}
            classes[node.name] = _SurfaceClass(
                info=info,
                bases=bases,
                carried_refs=carried,
                all_attrs=all_attrs,
            )
    return classes


def _base_chain(
    name: str, classes: Dict[str, _SurfaceClass]
) -> List[str]:
    """``name`` plus every (transitively) resolvable base class, MRO-ish
    order, restricted to classes found on the surface."""
    out: List[str] = []
    stack = [name]
    while stack:
        cur = stack.pop(0)
        if cur in out or cur not in classes:
            continue
        out.append(cur)
        stack.extend(classes[cur].bases)
    return out


def classify_surface(
    paths: Optional[Sequence[str]] = None,
    extra_contracts: Optional[Dict[Tuple[str, str], str]] = None,
) -> Tuple[List[AttrClassification], List[Finding]]:
    """The static half: classify every post-init mutated attribute on
    the surface; unclassifiable attrs become ``resume-state-gap``
    findings, contract entries naming dead attrs become
    ``stale-state-contract``."""
    if paths is None:
        paths = [os.path.join(_REPO_ROOT, p) for p in RESUME_SURFACE]
    contracts = dict(EPHEMERAL_CONTRACTS)
    contracts.update(extra_contracts or {})
    gap_rule = get_rule("resume-state-gap")
    stale_rule = get_rule("stale-state-contract")
    classes = _collect_surface(paths)
    classified: List[AttrClassification] = []
    findings: List[Finding] = []

    for name in sorted(classes):
        sc = classes[name]
        chain = _base_chain(name, classes)
        carried: Set[str] = set()
        phase_reset_methods: Set[str] = set()
        for cname in chain:
            carried |= classes[cname].carried_refs
            phase_reset_methods |= PHASE_RESET_METHODS.get(cname, set())
        # attr -> ordered write sites outside init/carry methods
        post_writes: Dict[str, List] = {}
        for w in sc.info.writes:
            if w.method in _INIT_METHODS or w.method in CARRY_METHODS:
                continue
            post_writes.setdefault(w.attr, []).append(w)
        for attr in sorted(post_writes):
            writes = post_writes[attr]
            first = min(writes, key=lambda w: w.line)
            site = AttrClassification(
                cls=name,
                attr=attr,
                file=sc.info.file,
                line=first.line,
                category="",
            )
            contract_key = next(
                (
                    (cname, attr)
                    for cname in chain
                    if (cname, attr) in contracts
                ),
                None,
            )
            carried_via = next(
                (
                    (cname, attr)
                    for cname in chain
                    if (cname, attr) in CARRIED_VIA
                ),
                None,
            )
            if attr in carried:
                site.category = "carried"
            elif carried_via is not None:
                site.category = "carried-via"
                site.detail = CARRIED_VIA[carried_via]
            elif any(w.method in phase_reset_methods for w in writes):
                site.category = "phase-reset"
                site.detail = ",".join(
                    sorted(phase_reset_methods & {w.method for w in writes})
                )
            elif all(
                w.method.startswith(_REBUILD_PREFIXES) for w in writes
            ):
                site.category = "reconstructed"
                site.detail = ",".join(sorted({w.method for w in writes}))
            elif contract_key is not None:
                site.category = "ephemeral"
                site.detail = contracts[contract_key]
            else:
                methods = sorted({w.method for w in writes})
                findings.append(
                    Finding(
                        rule=gap_rule.id,
                        message=(
                            f"`{name}.{attr}` is mutated inside the "
                            f"phase loop (in {', '.join(methods)}) but "
                            "is neither checkpoint-carried, "
                            "reconstructed from config, nor "
                            "allowlisted ephemeral — a resumed run "
                            "silently resets it. Carry it via "
                            "state_dict()/host_state_dict(), or add "
                            "an EPHEMERAL_CONTRACTS entry in "
                            "trlx_tpu/analysis/state_audit.py with a "
                            "written justification that losing it "
                            "cannot change any token or update"
                        ),
                        severity=gap_rule.severity,
                        file=sc.info.file,
                        line=first.line,
                        subject=f"{name}.{attr}",
                        engine=ENGINE_STATE,
                    )
                )
                continue
            classified.append(site)

    # stale contracts: entries naming classes/attrs that no longer exist
    shipped = {
        key
        for key in contracts
        if key in EPHEMERAL_CONTRACTS or (extra_contracts or {}).get(key)
    }
    for (cname, attr) in sorted(shipped):
        sc = classes.get(cname)
        if sc is None:
            # the class lives outside the scanned paths (tests scan tmp
            # trees): only flag when the default surface was scanned
            if paths is not None and any(
                os.path.abspath(p).startswith(_PKG_ROOT)
                for p in paths
            ):
                findings.append(
                    Finding(
                        rule=stale_rule.id,
                        message=(
                            f"ephemeral allowlist names class `{cname}` "
                            "which no longer exists on the resume "
                            "surface — prune or rename the entry"
                        ),
                        severity=stale_rule.severity,
                        subject=f"{cname}.{attr}",
                        engine=ENGINE_STATE,
                    )
                )
            continue
        if attr not in sc.all_attrs:
            findings.append(
                Finding(
                    rule=stale_rule.id,
                    message=(
                        f"ephemeral allowlist entry `{cname}.{attr}` "
                        "names an attribute the class never writes — "
                        "the justification covers nothing; prune or "
                        "rename the entry"
                    ),
                    severity=stale_rule.severity,
                    file=sc.info.file,
                    line=sc.info.line,
                    subject=f"{cname}.{attr}",
                    engine=ENGINE_STATE,
                )
            )
    return classified, findings


def lint_resume_state(
    paths: Optional[Sequence[str]] = None,
    extra_contracts: Optional[Dict[Tuple[str, str], str]] = None,
) -> List[Finding]:
    """Findings-only wrapper over :func:`classify_surface` (test entry)."""
    _, findings = classify_surface(paths, extra_contracts)
    return findings


# ------------------------------ dynamic half ----------------------------- #

_OPAQUE_MODULE_PREFIXES = (
    "jaxlib",
    "orbax",
    "threading",
    "logging",
    "concurrent",
)


def _value_digest(value: Any) -> Optional[str]:
    """A comparable scalar rendering of ``value``, or None when the
    value is opaque (callables, meshes, shardings, jitted programs) and
    must not participate in the diff."""
    import numpy as np

    if value is None or isinstance(value, (bool, int, str, bytes)):
        return repr(value)
    if isinstance(value, float):
        # repr round-trips doubles exactly — bitwise parity, readable
        return repr(value)
    if hasattr(value, "shape") and hasattr(value, "dtype"):
        # arrays first: jax.Array's type lives in jaxlib, which the
        # opaque filter below would otherwise swallow
        try:
            import jax

            host = np.asarray(jax.device_get(value))
        except Exception:
            return None
        digest = hashlib.sha1(host.tobytes()).hexdigest()[:16]
        return f"{host.dtype}{list(host.shape)}:{digest}"
    if callable(value):
        return None
    mod = type(value).__module__ or ""
    if mod.startswith(_OPAQUE_MODULE_PREFIXES):
        return None
    return None


def _snapshot_into(
    value: Any,
    path: str,
    out: Dict[str, str],
    seen: Set[int],
    depth: int = 0,
) -> None:
    """Flatten the live attribute tree under ``value`` into
    ``out[path] = digest`` rows, recursing into containers and
    trlx_tpu-owned objects only."""
    if depth > 12:
        return
    digest = _value_digest(value)
    if digest is not None:
        out[path] = digest
        return
    if id(value) in seen:
        return
    seen.add(id(value))
    if isinstance(value, dict):
        for k in sorted(value, key=repr):
            _snapshot_into(
                value[k], f"{path}[{k!r}]", out, seen, depth + 1
            )
        return
    if isinstance(value, (list, tuple)) or type(value).__name__ == "deque":
        for i, item in enumerate(value):
            _snapshot_into(item, f"{path}[{i}]", out, seen, depth + 1)
        return
    if isinstance(value, (set, frozenset)):
        out[path] = repr(sorted(repr(v) for v in value))
        return
    mod = type(value).__module__ or ""
    if mod.startswith("trlx_tpu") or type(value).__name__ in (
        "_SeriesState",
    ):
        cls = type(value).__name__
        attrs: Dict[str, Any] = {}
        if hasattr(value, "__dict__"):
            attrs.update(vars(value))
        for slot in getattr(type(value), "__slots__", ()) or ():
            if hasattr(value, slot):
                attrs[slot] = getattr(value, slot)
        for attr in sorted(attrs):
            if attr in _DIFFER_SKIP_ATTRS:
                continue
            if _is_contracted(cls, attr):
                continue
            _snapshot_into(
                attrs[attr], f"{path}.{attr}", out, seen, depth + 1
            )
    # anything else (foreign objects, modules, locks) is opaque: skip


def _is_contracted(cls: str, attr: str) -> bool:
    """True when (cls-or-base, attr) carries an ephemeral contract —
    resolved by name only (the differ has no AST at hand), so every
    class in the contract table matches itself and its subclasses via
    the live MRO."""
    probe = _CONTRACT_CLASS_INDEX.get(attr)
    if not probe:
        return False
    return cls in probe or any(
        base in probe for base in _LIVE_BASES.get(cls, ())
    )


#: attr -> {classes allowlisting it} (derived once from the contracts)
_CONTRACT_CLASS_INDEX: Dict[str, Set[str]] = {}
for (_cls, _attr), _ in EPHEMERAL_CONTRACTS.items():
    _CONTRACT_CLASS_INDEX.setdefault(_attr, set()).add(_cls)

#: live base-name map filled lazily by the differ (subclass -> bases)
_LIVE_BASES: Dict[str, Tuple[str, ...]] = {}


def _register_live_bases(obj: Any) -> None:
    for klass in type(obj).__mro__:
        _LIVE_BASES.setdefault(
            klass.__name__,
            tuple(b.__name__ for b in klass.__mro__[1:]),
        )


def snapshot_host_state(trainer: Any) -> Dict[str, str]:
    """The full flattened live attribute tree of ``trainer`` (and every
    reachable trlx_tpu object), arrays digested by content."""
    _register_live_bases(trainer)
    out: Dict[str, str] = {}
    _snapshot_into(trainer, "trainer", out, set())
    return out


class PlantedScheduleState:
    """The ``--plant-gap`` payload: an uncheckpointed draw counter that
    the planted canonical pass folds into its sampling seed — exactly
    the bug class the auditor exists to catch."""

    def __init__(self) -> None:
        self.draws = 0


def _one_pass(trainer: Any, kind: str, step_seed: int) -> None:
    """One canonical phase at the harness shapes — mirrors the loop the
    compile/lockstep engines drive (rollout -> stepwise update -> fused
    phase -> behavior snapshot -> engine mini-phase) so all engines gate
    the same dispatch order."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from trlx_tpu.analysis import harness
    from trlx_tpu.parallel.mesh import batch_sharding

    planted = getattr(trainer, "_planted_schedule", None)
    if planted is not None:
        # the planted gap: an uncheckpointed counter feeding the seed
        planted.draws += 1
        step_seed = step_seed + planted.draws

    batch_sh = getattr(trainer, "_batch_sh", None) or batch_sharding(
        trainer.mesh
    )
    B = trainer.config.train.batch_size
    Q = trainer.query_length
    prompt_ids = jnp.ones((B, Q), jnp.int32)
    prompt_mask = jnp.ones((B, Q), jnp.int32)
    trainer.sample(prompt_ids, prompt_mask)
    mb = harness.concrete_minibatch(trainer, kind, seed=step_seed)
    mb = jax.device_put(mb, batch_sh)
    trainer.state, _ = trainer._train_step_jit(trainer.state, mb)
    if kind == "ilql":
        return
    stacked = jax.tree_util.tree_map(
        lambda a, b: jnp.stack([a, b]),
        harness.concrete_minibatch(trainer, kind, seed=step_seed),
        harness.concrete_minibatch(trainer, kind, seed=step_seed + 17),
    )
    stacked = jax.device_put(stacked, trainer._stacked_batch_sh)
    trainer.state, _ = trainer._train_phase_jit(trainer.state, stacked)
    trainer._behavior_snapshot_jit(trainer.state.params)
    if kind == "ppo":
        engine = trainer.rollout_engine_obj
        rng = np.random.default_rng(step_seed)
        n = engine.harvest_width
        eng_ids = rng.integers(1, 30, (n, Q)).astype(np.int32)
        engine.start_phase(
            trainer.rollout_params(),
            jax.random.fold_in(jax.random.PRNGKey(0), step_seed),
        )
        engine.submit(eng_ids, np.ones((n, Q), np.int32))
        for _group in engine.drive(n):
            pass


@dataclass
class DifferRun:
    """One trainer kind's kill/resume differ outcome."""

    kind: str
    compared_paths: int = 0
    divergences: List[Tuple[str, str, str]] = field(
        default_factory=list
    )  # (path, resumed, twin)
    manifest: Dict[str, Any] = field(default_factory=dict)
    mesh: Dict[str, int] = field(default_factory=dict)


def trainer_manifest(trainer: Any) -> Dict[str, Any]:
    """Checkpoint schema fingerprint: every state-pytree leaf's
    shape/dtype plus the host-metadata key paths."""
    import jax

    leaves: Dict[str, str] = {}
    flat, _ = jax.tree_util.tree_flatten_with_path(trainer.state)
    for keypath, leaf in flat:
        key = jax.tree_util.keystr(keypath)
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            leaves[key] = f"{leaf.dtype}{list(leaf.shape)}"
        else:
            leaves[key] = type(leaf).__name__
    meta_keys: List[str] = []

    def _walk_meta(value: Any, prefix: str) -> None:
        if isinstance(value, dict):
            if not value:
                meta_keys.append(f"{prefix}{{}}")
            for k in sorted(value):
                _walk_meta(value[k], f"{prefix}.{k}" if prefix else str(k))
        else:
            meta_keys.append(prefix)

    _walk_meta(trainer._save_metadata(), "")
    return {"state": leaves, "metadata": sorted(meta_keys)}


def run_resume_differ(
    kind: str,
    mesh: Optional[Dict[str, int]] = None,
    plant_gap: bool = False,
    workdir: Optional[str] = None,
) -> DifferRun:
    """Kill/resume differ for one trainer kind.

    Phase 0 runs on trainer A, which then checkpoints. Trainer B is
    built from scratch (a new process's rebuild) and restores. Both run
    an identically-seeded phase 1; any surviving state A carries that B
    lost shows up as a diverging attribute path.
    """
    import shutil
    import tempfile

    from trlx_tpu.analysis import harness

    own_dir = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix=f"resume_audit_{kind}_")
    ckpt_dir = os.path.join(workdir, "ckpt")
    run = DifferRun(kind=kind)
    try:
        overrides = {
            "checkpoint_dir": ckpt_dir,
            "async_checkpoint": False,
        }
        twin = harness.build_trainer(
            kind, mesh, train_overrides=overrides
        )
        run.mesh = {k: int(v) for k, v in twin.mesh.shape.items()}
        if plant_gap:
            twin._planted_schedule = PlantedScheduleState()
        _one_pass(twin, kind, 0)
        twin.save(ckpt_dir)

        resumed = harness.build_trainer(
            kind, mesh, train_overrides=overrides
        )
        if plant_gap:
            resumed._planted_schedule = PlantedScheduleState()
        resumed.load(ckpt_dir)

        _one_pass(twin, kind, 1)
        _one_pass(resumed, kind, 1)

        run.manifest = trainer_manifest(twin)
        snap_twin = snapshot_host_state(twin)
        snap_resumed = snapshot_host_state(resumed)
        run.compared_paths = len(set(snap_twin) | set(snap_resumed))
        for path in sorted(set(snap_twin) | set(snap_resumed)):
            a = snap_twin.get(path, "<absent>")
            b = snap_resumed.get(path, "<absent>")
            if a != b:
                run.divergences.append((path, b, a))
    finally:
        if own_dir:
            shutil.rmtree(workdir, ignore_errors=True)
    return run


def divergence_findings(run: DifferRun) -> List[Finding]:
    rule = get_rule("resume-divergence")
    findings: List[Finding] = []
    for path, resumed, twin in run.divergences:
        findings.append(
            Finding(
                rule=rule.id,
                message=(
                    f"[{run.kind}] `{path}` diverged after "
                    f"checkpoint/rebuild/restore + one phase: resumed="
                    f"{resumed} vs uninterrupted={twin} — the state at "
                    "this path did not survive kill/resume. Carry it "
                    "in the owner's state_dict()/host_state_dict(), "
                    "or (only if losing it provably cannot change a "
                    "token or update) add an EPHEMERAL_CONTRACTS "
                    "entry in trlx_tpu/analysis/state_audit.py"
                ),
                severity=rule.severity,
                subject=f"{run.kind}:{path}",
                engine=ENGINE_STATE,
            )
        )
    return findings


# ------------------------------- manifest -------------------------------- #

def make_state_manifest(
    runs: Sequence[DifferRun], mesh: Dict[str, int]
) -> Dict[str, Any]:
    return {
        "mesh": {k: int(v) for k, v in sorted(mesh.items())},
        "trainers": {
            run.kind: run.manifest
            for run in sorted(runs, key=lambda r: r.kind)
        },
    }


def check_state_manifest(
    runs: Sequence[DifferRun],
    budgets: Dict,
    mesh: Dict[str, int],
    budgets_path: Optional[str] = None,
) -> List[Finding]:
    """Gate the observed checkpoint schema against the committed lock."""
    rule = get_rule("ckpt-schema-drift")
    stale_rule = get_rule("stale-state-contract")
    where = os.path.basename(budgets_path or "budgets.json")
    section = budgets.get("state_manifest")
    if section is None:
        return [
            Finding(
                rule=rule.id,
                message=(
                    f"{where} has no state_manifest section — lock the "
                    "checkpoint schema with --resume-audit "
                    "--update-budgets and commit the diff"
                ),
                severity=rule.severity,
                subject="state_manifest",
                engine=ENGINE_STATE,
            )
        ]
    findings: List[Finding] = []
    locked_mesh = section.get("mesh")
    current_mesh = {k: int(v) for k, v in sorted(mesh.items())}
    if locked_mesh is not None and locked_mesh != current_mesh:
        return [
            Finding(
                rule=rule.id,
                message=(
                    f"state manifest in {where} was locked for mesh "
                    f"{locked_mesh} but the audit ran on {current_mesh} "
                    "— schemas are not comparable; rerun on the locked "
                    "mesh or --update-budgets"
                ),
                severity=rule.severity,
                subject="state_manifest",
                engine=ENGINE_STATE,
            )
        ]
    locked_trainers = section.get("trainers", {})
    for run in runs:
        locked = locked_trainers.get(run.kind)
        if locked is None:
            findings.append(
                Finding(
                    rule=rule.id,
                    message=(
                        f"no committed state manifest for trainer "
                        f"`{run.kind}` — lock it with --resume-audit "
                        "--update-budgets and review the diff"
                    ),
                    severity=rule.severity,
                    subject=f"state_manifest:{run.kind}",
                    engine=ENGINE_STATE,
                )
            )
            continue
        locked_state = locked.get("state", {})
        current_state = run.manifest.get("state", {})
        for key in sorted(set(locked_state) - set(current_state)):
            findings.append(
                Finding(
                    rule=rule.id,
                    message=(
                        f"[{run.kind}] checkpoint leaf `{key}` vanished "
                        f"from the save pytree (locked "
                        f"{locked_state[key]}) — existing checkpoints "
                        "would restore without it; if the removal is "
                        "intended, relock with --update-budgets and "
                        "explain the diff"
                    ),
                    severity=rule.severity,
                    subject=f"{run.kind}:{key}",
                    engine=ENGINE_STATE,
                )
            )
        for key in sorted(set(current_state) - set(locked_state)):
            findings.append(
                Finding(
                    rule=rule.id,
                    message=(
                        f"[{run.kind}] new checkpoint leaf `{key}` "
                        f"({current_state[key]}) is not in the locked "
                        "manifest — relock additively with "
                        "--resume-audit --update-budgets"
                    ),
                    severity=rule.severity,
                    subject=f"{run.kind}:{key}",
                    engine=ENGINE_STATE,
                )
            )
        for key in sorted(set(current_state) & set(locked_state)):
            if current_state[key] != locked_state[key]:
                findings.append(
                    Finding(
                        rule=rule.id,
                        message=(
                            f"[{run.kind}] checkpoint leaf `{key}` "
                            f"changed {locked_state[key]} -> "
                            f"{current_state[key]} — every checkpoint "
                            "on disk restores with the old "
                            "shape/dtype; relock with --update-budgets "
                            "only alongside a migration story"
                        ),
                        severity=rule.severity,
                        subject=f"{run.kind}:{key}",
                        engine=ENGINE_STATE,
                    )
                )
        locked_meta = set(locked.get("metadata", []))
        current_meta = set(run.manifest.get("metadata", []))
        for key in sorted(locked_meta - current_meta):
            findings.append(
                Finding(
                    rule=rule.id,
                    message=(
                        f"[{run.kind}] host-metadata key `{key}` "
                        "vanished from _save_metadata() — resume "
                        "silently loses it; relock with "
                        "--update-budgets if intended"
                    ),
                    severity=rule.severity,
                    subject=f"{run.kind}:{key}",
                    engine=ENGINE_STATE,
                )
            )
        for key in sorted(current_meta - locked_meta):
            findings.append(
                Finding(
                    rule=rule.id,
                    message=(
                        f"[{run.kind}] new host-metadata key `{key}` "
                        "is not in the locked manifest — relock "
                        "additively with --update-budgets"
                    ),
                    severity=rule.severity,
                    subject=f"{run.kind}:{key}",
                    engine=ENGINE_STATE,
                )
            )
    # stale manifest entries: locked trainer kinds that no longer exist
    from trlx_tpu.analysis import harness

    for stale in sorted(set(locked_trainers) - set(harness.TRAINER_KINDS)):
        findings.append(
            Finding(
                rule=stale_rule.id,
                message=(
                    f"state manifest names trainer kind `{stale}` which "
                    "is not a registered harness kind — prune it with "
                    "--resume-audit --update-budgets"
                ),
                severity=stale_rule.severity,
                subject=f"state_manifest:{stale}",
                engine=ENGINE_STATE,
            )
        )
    return findings


# ------------------------------ planted gap ------------------------------ #

# NOTE: test_analysis_state.py and the CI planted-gap step grep for the
# exact localization "planted_resume_gap.py:18" — the line of the first
# uncarried mutation below (`self.draws += 1`). Keep the layout stable.
_PLANT_SOURCE = '''\
"""Planted resume gap (generated by --plant-gap; never shipped)."""


class PlantedSampler:
    """A sampler whose schedule depends on an uncheckpointed counter."""

    def __init__(self, seed):
        self.seed = seed
        self.draws = 0

    def state_dict(self):
        return {"seed": self.seed}

    def load_state_dict(self, state):
        self.seed = state["seed"]

    def next_seed(self):
        self.draws += 1
        return self.seed + self.draws
'''

_PLANT_FILE = "planted_resume_gap.py"
_PLANT_LINE = 18


def plant_gap_paths(workdir: str) -> List[str]:
    """Write the planted source into ``workdir`` and return the scan
    paths (planted file only — the shipped surface is audited by the
    normal run; the plant proves detection, not the tree)."""
    path = os.path.join(workdir, _PLANT_FILE)
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(_PLANT_SOURCE)
    return [path]


# ------------------------------ entry point ------------------------------ #

@dataclass
class StateAuditResult:
    """The ``--resume-audit`` payload next to the findings report."""

    mesh: Dict[str, int] = field(default_factory=dict)
    classified: List[AttrClassification] = field(default_factory=list)
    runs: List[DifferRun] = field(default_factory=list)

    def to_json(self) -> Dict[str, Any]:
        by_category: Dict[str, int] = {}
        for c in self.classified:
            by_category[c.category] = by_category.get(c.category, 0) + 1
        return {
            "mesh": self.mesh,
            "classified_attrs": len(self.classified),
            "by_category": dict(sorted(by_category.items())),
            "differ": [
                {
                    "kind": r.kind,
                    "compared_paths": r.compared_paths,
                    "divergences": len(r.divergences),
                }
                for r in self.runs
            ],
        }


def audit_resume_state(
    kinds: Optional[Sequence[str]] = None,
    mesh: Optional[Dict[str, int]] = None,
    budgets_path: Optional[str] = None,
    update: bool = False,
    plant_gap: bool = False,
    static_paths: Optional[Sequence[str]] = None,
) -> Tuple[Report, StateAuditResult]:
    """The ``--resume-audit`` entry point.

    Static classification first (no jax), then the per-kind kill/resume
    differ, then the schema gate against (or with ``update=True`` a
    relock of) the ``state_manifest`` section of analysis/budgets.json.
    """
    import tempfile

    from trlx_tpu.analysis import harness
    from trlx_tpu.analysis.resource_audit import (
        default_budgets_path,
        load_budgets,
        write_budgets,
    )

    path = budgets_path or default_budgets_path()
    report = Report()
    result = StateAuditResult()

    # ---- static half ---- #
    classified, static_findings = classify_surface(paths=static_paths)
    result.classified = classified
    if plant_gap:
        with tempfile.TemporaryDirectory(
            prefix="resume_plant_"
        ) as plantdir:
            _, plant_findings = classify_surface(
                paths=plant_gap_paths(plantdir)
            )
            static_findings += plant_findings
    report.covered += [
        f"state:{c.cls}.{c.attr}[{c.category}]" for c in classified
    ]

    # ---- dynamic half ---- #
    dyn_findings: List[Finding] = []
    for kind in kinds or harness.TRAINER_KINDS:
        # plant only on the cheapest trainer: one planted divergence
        # proves the differ end-to-end; planting everywhere just
        # multiplies identical findings
        plant_here = plant_gap and kind == (kinds or ("ilql",))[0]
        run = run_resume_differ(kind, mesh, plant_gap=plant_here)
        result.runs.append(run)
        dyn_findings += divergence_findings(run)
        report.covered += [
            f"differ:{kind}:{run.compared_paths} paths"
        ]
        for key in run.manifest.get("state", {}):
            report.covered.append(f"manifest:{kind}:{key}")
        for key in run.manifest.get("metadata", []):
            report.covered.append(f"manifest-meta:{kind}:{key}")
        result.mesh = run.mesh or result.mesh

    # ---- schema lock ---- #
    if update:
        try:
            budgets = load_budgets(path)
        except (OSError, ValueError):
            budgets = {}
        partial = kinds is not None
        section = make_state_manifest(result.runs, result.mesh)
        old_section = budgets.get("state_manifest") or {}
        if partial and old_section.get("mesh") not in (
            None,
            section["mesh"],
        ):
            rule = get_rule("ckpt-schema-drift")
            report.extend([
                Finding(
                    rule=rule.id,
                    message=(
                        "refusing --update-budgets: the state manifest "
                        f"is locked for mesh {old_section.get('mesh')} "
                        f"but this --trainers subset ran on "
                        f"{section['mesh']} — rerun without --trainers "
                        "or on the locked mesh"
                    ),
                    severity=rule.severity,
                    subject="state_manifest",
                    engine=ENGINE_STATE,
                )
            ])
            return report, result
        # unsuppressed gaps/divergences refuse the relock BEFORE any
        # write: a manifest locked over a broken tree would certify
        # the breakage
        kept_f, suppressed = filter_suppressed(
            static_findings + dyn_findings
        )
        report.extend(kept_f)
        report.suppressed += suppressed
        if report.findings:
            return report, result
        if partial:
            kept = {
                k: dict(v)
                for k, v in old_section.get("trainers", {}).items()
                if k not in set(kinds or ())
            }
            kept.update(section["trainers"])
            section["trainers"] = {k: kept[k] for k in sorted(kept)}
        budgets["state_manifest"] = section
        write_budgets(budgets, path)
        return report, result

    try:
        budgets = load_budgets(path)
    except (OSError, ValueError) as e:
        rule = get_rule("ckpt-schema-drift")
        static_findings.append(
            Finding(
                rule=rule.id,
                message=(
                    f"cannot load budget contract {path}: {e} — "
                    "generate it with --resume-audit --update-budgets"
                ),
                severity=rule.severity,
                subject="state_manifest",
                engine=ENGINE_STATE,
            )
        )
        budgets = {}
    manifest_findings: List[Finding] = []
    if budgets:
        manifest_findings = check_state_manifest(
            result.runs, budgets, result.mesh, path
        )
    kept, suppressed = filter_suppressed(
        static_findings + dyn_findings + manifest_findings
    )
    report.extend(kept)
    report.suppressed += suppressed
    return report, result


def format_state_text(result: StateAuditResult) -> str:
    by_category: Dict[str, int] = {}
    for c in result.classified:
        by_category[c.category] = by_category.get(c.category, 0) + 1
    lines = [
        f"resume surface: {len(result.classified)} classified "
        "mutable attrs "
        + " ".join(
            f"{k}={v}" for k, v in sorted(by_category.items())
        )
    ]
    for run in result.runs:
        lines.append(
            f"{run.kind:8} differ: {run.compared_paths} live paths "
            f"compared, {len(run.divergences)} divergence(s); "
            f"{len(run.manifest.get('state', {}))} state leaves + "
            f"{len(run.manifest.get('metadata', []))} metadata keys "
            "fingerprinted"
        )
    return "\n".join(lines)
