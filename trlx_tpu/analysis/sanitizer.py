"""Sanitizer replay: execute a captured step jaxpr eqn-by-eqn, checking
every intermediate for NaN/Inf.

Engine 5 of ``trlx_tpu.analysis`` — the dynamic complement of the
NaN-flow dataflow. ``python -m trlx_tpu.analysis --sanitize ppo`` builds
the tiny harness trainer (optionally on an explicit ``--mesh``, e.g. the
diverging ``dp=2,fsdp=2,tp=2`` repro), captures its jitted train step as
a jaxpr over the *concrete* trainer state and a plausible rollout batch,
and replays it equation by equation:

- call-like eqns (pjit / remat / custom_vjp / scan / cond) are entered
  recursively, so the first offending equation is an actual primitive
  with source provenance, not "the pjit";
- ``scan`` is re-executed as a Python loop over its body jaxpr, so a NaN
  minted at iteration k of the fused PPO phase is attributed to the body
  equation (and the report says which iteration);
- every output is checked with ``isfinite``; the first non-finite
  equation stops the replay and is reported with its primitive, shapes,
  repo source frame, the parameter paths of any top-level inputs it
  consumed, and the trainer's mesh spec.

Integer/bool outputs are exempt (masks legitimately hold sentinel
values), as are inputs that were already non-finite before the eqn ran —
the report names the *minting* equation, not the propagation.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from trlx_tpu.analysis.findings import Finding, Report
from trlx_tpu.analysis.registry import get_rule

# Call-like primitives entered recursively (params key holding the jaxpr).
_CALL_PRIMS = {
    "pjit": "jaxpr",
    "closed_call": "call_jaxpr",
    "core_call": "call_jaxpr",
    "remat": "jaxpr",
    "remat2": "jaxpr",
    "checkpoint": "jaxpr",
    "custom_jvp_call": "call_jaxpr",
    "custom_vjp_call": "call_jaxpr",
    "custom_vjp_call_jaxpr": "fun_jaxpr",
}


@dataclass
class Offence:
    """The first equation whose output went non-finite."""

    primitive: str
    kind: str  # "nan" | "inf"
    subject: str
    file: Optional[str] = None
    line: Optional[int] = None
    out_shape: str = ""
    iteration: Optional[int] = None  # scan iteration, when inside one
    input_paths: List[str] = field(default_factory=list)
    eqn_str: str = ""

    def describe(self) -> str:
        loc = f"{self.file}:{self.line}" if self.file else "<no repo frame>"
        it = f" (scan iteration {self.iteration})" if self.iteration is not None else ""
        paths = (
            f"; consumes program inputs: {', '.join(self.input_paths)}"
            if self.input_paths
            else ""
        )
        return (
            f"first non-finite intermediate ({self.kind}) minted by "
            f"`{self.primitive}` -> {self.out_shape} at {loc}{it}{paths}"
        )


class _Replayer:
    def __init__(self, repo_root: str, subject: str):
        self.repo_root = repo_root
        self.subject = subject
        self.offence: Optional[Offence] = None
        self._scan_iter: Optional[int] = None

    # --------------------------- value checks --------------------------- #

    def _nonfinite_kind(self, val) -> Optional[str]:
        import numpy as np

        dtype = getattr(val, "dtype", None)
        if dtype is None:
            # plain Python scalars (jaxpr Literals like -inf mask fills)
            if isinstance(val, float):
                import math

                if math.isnan(val):
                    return "nan"
                if math.isinf(val):
                    return "inf"
            return None
        try:
            np_dtype = np.dtype(dtype)
        except TypeError:
            # extended dtypes (typed PRNG keys, `key<fry>`) have no
            # numpy interpretation and no finiteness to check — the
            # engine decode replay's per-row fold_in mints these
            return None
        if np_dtype.kind != "f" and np_dtype.name not in (
            "bfloat16", "float16"  # ml_dtypes report numpy kind 'V'
        ):
            return None
        arr = np.asarray(val)
        if np_dtype.kind != "f":
            arr = arr.astype(np.float32)
        if np.isnan(arr).any():
            return "nan"
        if np.isinf(arr).any():
            return "inf"
        return None

    def _record(self, eqn, invals, outvals, input_names: Dict[int, str]) -> None:
        import numpy as np

        kinds = [self._nonfinite_kind(v) for v in outvals]
        bad = next((k for k in kinds if k), None)
        if bad is None:
            return
        # A NaN is never intentional: record it wherever it first appears
        # (for a poisoned program input, that is its first consumer — the
        # localization the operator wants). An inf *can* be intentional
        # (-inf mask fills, -1e9 biases), so only an inf minted from
        # all-finite inputs counts — genuine overflow, not propagation.
        if bad == "inf" and any(self._nonfinite_kind(v) for v in invals):
            return
        from trlx_tpu.analysis.jaxpr_audit import _repo_frame

        frame = _repo_frame(eqn, self.repo_root)
        shapes = ", ".join(
            str(getattr(v, "shape", "?")) for v in outvals[:3]
        )
        paths = [
            input_names[id(v)]
            for v in eqn.invars
            if id(v) in input_names
        ]
        self.offence = Offence(
            primitive=eqn.primitive.name,
            kind=bad,
            subject=self.subject,
            file=frame.file_name if frame else None,
            line=frame.start_line if frame else None,
            out_shape=shapes,
            iteration=self._scan_iter,
            input_paths=paths,
            eqn_str=str(eqn)[:200],
        )

    # ----------------------------- replay ------------------------------- #

    def replay(
        self,
        jaxpr,
        consts: Sequence[Any],
        args: Sequence[Any],
        input_names: Optional[Dict[int, str]] = None,
        arg_names: Optional[Sequence[Optional[str]]] = None,
    ) -> List[Any]:
        """Evaluate ``jaxpr`` eqn-by-eqn; stops recording at the first
        offence but keeps evaluating (outputs still needed upstream).

        ``arg_names`` labels this jaxpr's invars (parameter paths for the
        top-level call; propagated through call-like eqns)."""
        from jax._src.core import Literal

        env: Dict = {}
        names: Dict[int, str] = dict(input_names or {})

        def read(v):
            return v.val if isinstance(v, Literal) else env[v]

        for var, val in zip(jaxpr.constvars, consts):
            env[var] = val
        for i, (var, val) in enumerate(zip(jaxpr.invars, args)):
            env[var] = val
            if arg_names and i < len(arg_names) and arg_names[i]:
                names[id(var)] = arg_names[i]

        for eqn in jaxpr.eqns:
            invals = [read(v) for v in eqn.invars]
            outvals = self._eval_eqn(eqn, invals, names)
            if not isinstance(outvals, (list, tuple)):
                outvals = [outvals]
            if self.offence is None:
                self._record(eqn, invals, outvals, names)
            for var, val in zip(eqn.outvars, outvals):
                env[var] = val
        return [read(v) for v in jaxpr.outvars]

    def _eval_eqn(self, eqn, invals, names: Dict[int, str]):
        name = eqn.primitive.name
        if name in _CALL_PRIMS:
            closed = eqn.params.get(_CALL_PRIMS[name])
            if closed is not None:
                inner = getattr(closed, "jaxpr", closed)
                consts = getattr(closed, "consts", ())
                inner_names = [
                    names.get(id(v)) for v in eqn.invars
                ]
                return self.replay(inner, consts, invals, arg_names=inner_names)
        if name == "scan":
            return self._eval_scan(eqn, invals, names)
        if name == "cond":
            import numpy as np

            branches = eqn.params.get("branches")
            if branches is not None:
                index = int(np.asarray(invals[0]))
                closed = branches[index]
                inner = getattr(closed, "jaxpr", closed)
                return self.replay(
                    inner, getattr(closed, "consts", ()), invals[1:]
                )
        # everything else: execute the primitive whole (impl rules run
        # eagerly outside any trace)
        subfuns, bind_params = eqn.primitive.get_bind_params(eqn.params)
        out = eqn.primitive.bind(*subfuns, *invals, **bind_params)
        return out

    def _eval_scan(self, eqn, invals, names: Dict[int, str]):
        """Python-loop a scan so each iteration replays the body jaxpr."""
        import jax.numpy as jnp

        params = eqn.params
        closed = params["jaxpr"]
        inner = getattr(closed, "jaxpr", closed)
        consts_vals = getattr(closed, "consts", ())
        n_consts = params.get("num_consts", 0)
        n_carry = params.get("num_carry", 0)
        length = params.get("length")
        reverse = params.get("reverse", False)

        consts = list(invals[:n_consts])
        carry = list(invals[n_consts:n_consts + n_carry])
        xs = list(invals[n_consts + n_carry:])
        if length is None:
            length = xs[0].shape[0] if xs else 0

        const_names = [names.get(id(v)) for v in eqn.invars[:n_consts]]
        ys_acc: List[List[Any]] = []
        order = range(length - 1, -1, -1) if reverse else range(length)
        outer_iter = self._scan_iter
        for i in order:
            slices = [x[i] for x in xs]
            self._scan_iter = i
            outs = self.replay(
                inner,
                consts_vals,
                consts + carry + slices,
                arg_names=const_names + [None] * (n_carry + len(slices)),
            )
            carry = list(outs[:n_carry])
            ys_acc.append(list(outs[n_carry:]))
        self._scan_iter = outer_iter
        if reverse:
            ys_acc.reverse()
        ys = [
            jnp.stack([row[j] for row in ys_acc])
            for j in range(len(ys_acc[0]))
        ] if ys_acc and ys_acc[0] else []
        return carry + ys


@dataclass
class SanitizeResult:
    subject: str
    mesh: Dict[str, int]
    n_eqns_checked: int
    offence: Optional[Offence]

    @property
    def clean(self) -> bool:
        return self.offence is None

    def to_report(self) -> Report:
        report = Report()
        report.covered.append(f"sanitize:{self.subject}")
        if self.offence is not None:
            rule = get_rule("sanitizer-nonfinite")
            report.extend([
                Finding(
                    rule=rule.id,
                    message=self.offence.describe()
                    + f"; mesh={self.mesh}",
                    severity=rule.severity,
                    file=_relpath(self.offence.file),
                    line=self.offence.line,
                    subject=self.subject,
                    engine="sanitizer",
                )
            ])
        return report

    def format_text(self) -> str:
        head = f"sanitize[{self.subject}] mesh={self.mesh}"
        if self.clean:
            return f"{head}: clean — all intermediates finite"
        return f"{head}:\n  {self.offence.describe()}"


def _relpath(path: Optional[str]) -> Optional[str]:
    if path is None:
        return None
    from trlx_tpu.analysis.jaxpr_audit import default_repo_root

    root = default_repo_root()
    if root in path:
        return path.split(root, 1)[1].lstrip("/")
    return path


def _flat_input_names(state, mb) -> List[str]:
    """Flat keypath labels for the (state, minibatch) argument tree, in
    the order make_jaxpr flattens them."""
    from trlx_tpu.analysis.harness import flat_input_paths

    return flat_input_paths(state, mb, prefixes=("state", "batch"))


def sanitize_jaxpr(
    closed_jaxpr,
    args: Sequence[Any],
    subject: str = "program",
    mesh: Optional[Dict[str, int]] = None,
    repo_root: Optional[str] = None,
    arg_names: Optional[Sequence[Optional[str]]] = None,
) -> SanitizeResult:
    """Replay a captured (closed) jaxpr on concrete ``args``."""
    from trlx_tpu.analysis.jaxpr_audit import default_repo_root, iter_eqns

    inner = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    replayer = _Replayer(repo_root or default_repo_root(), subject)
    replayer.replay(
        inner, getattr(closed_jaxpr, "consts", ()), list(args),
        arg_names=list(arg_names or []),
    )
    n = sum(1 for _ in iter_eqns(closed_jaxpr))
    return SanitizeResult(
        subject=subject,
        mesh=dict(mesh or {}),
        n_eqns_checked=n,
        offence=replayer.offence,
    )


def plant_nan(state):
    """Poison one parameter leaf (NaN at flat index 0) so the replay has
    a deterministic first-NaN to localize — the CLI's ``--plant-nan``
    self-check that the sanitizer actually detects and attributes."""
    import jax
    import jax.numpy as jnp

    leaves, treedef = jax.tree_util.tree_flatten(state.params)
    first = leaves[0]
    poisoned = first.at[(0,) * first.ndim].set(jnp.nan)
    params = jax.tree_util.tree_unflatten(treedef, [poisoned] + leaves[1:])
    # .replace keeps every other field (ILQL's state carries
    # target_q_params beyond the common params/opt_state/step)
    return state.replace(params=params)


def sanitize_engine_step(
    kind: str,
    mesh: Optional[Dict[str, int]] = None,
    plant: bool = False,
    seed: int = 0,
) -> SanitizeResult:
    """Replay the continuous-batching engine's ``decode_step``, then
    its speculative ``verify_step`` (``trlx_tpu/inference/engine.py``),
    eqn-by-eqn on a concretely prefilled slot pool.

    The state is produced the way production produces it — a real
    ``start_phase`` + admission prefill over random prompts — so a
    NaN minted anywhere in the decode path (paged-cache gather, per-row
    bias, token selection, value head) is localized to its first
    offending equation exactly like ``--sanitize``'s train-step replay.
    The verify replay runs the multi-token drafted pass
    (docs/inference.md "Speculative decoding") on a separately built
    spec-enabled engine with every slot carrying a full-width random
    draft — acceptance is irrelevant to the replay; rejected columns
    still exercise the OOB-sentinel write and masked-softmax paths
    where a NaN would mint. ``plant`` poisons one param leaf first
    (the CLI self-check; the decode replay finds it and short-circuits).
    """
    import numpy as np

    import jax

    from trlx_tpu.analysis import harness
    from trlx_tpu.analysis.harness import flat_input_paths

    if kind != "ppo":
        raise ValueError(
            "--engine-step replays the causal continuous-batching "
            f"engine via the ppo trainer; got {kind!r}"
        )
    trainer = harness.build_trainer(kind, mesh)
    params = trainer.state.params
    if plant:
        params = plant_nan(trainer.state).params
    engine = trainer.rollout_engine_obj
    rng = np.random.default_rng(seed)
    A, Q = engine.admit_width, engine.Q
    vocab = getattr(trainer.model_config, "vocab_size", 32)
    ids = rng.integers(1, max(2, vocab - 2), (A, Q)).astype(np.int32)
    mask = np.ones((A, Q), np.int32)
    engine.start_phase(params, jax.random.PRNGKey(seed))
    engine.submit(ids, mask)
    engine._admit()  # concrete prefill — the replay's input state
    state = engine._state

    closed = jax.make_jaxpr(engine.decode_step_jit)(params, state)
    args = jax.tree_util.tree_leaves((params, state))
    names = flat_input_paths(params, state, prefixes=("params", "state"))
    mesh_shape = {k: int(v) for k, v in trainer.mesh.shape.items()}
    decode_result = sanitize_jaxpr(
        closed,
        args,
        subject=f"{kind}.engine_decode_step"
        + (".planted" if plant else ""),
        mesh=mesh_shape,
        arg_names=names,
    )
    if decode_result.offence is not None:
        return decode_result

    import jax.numpy as jnp

    from trlx_tpu.inference.engine import ContinuousBatchingEngine

    spec_engine = ContinuousBatchingEngine(
        apply_fn=engine._apply_fn,
        init_cache_fn=engine._init_cache_fn,
        gen_config=engine.gen_config,
        query_length=engine.Q,
        vocab_size=engine.vocab_size,
        num_slots=engine.num_slots,
        admit_width=engine.admit_width,
        harvest_width=engine.harvest_width,
        block_size=engine.block_size,
        mesh=engine.mesh,
        param_shardings=engine._param_shardings,
        cache_sharding=engine._cache_sharding,
        with_values=engine.with_values,
        spec_max_draft=4,
    )
    if spec_engine.verify_step_jit is None:
        return decode_result
    spec_engine.start_phase(params, jax.random.PRNGKey(seed))
    spec_engine.submit(ids, mask)
    spec_engine._admit()
    B, D = spec_engine.num_slots, spec_engine.spec_max_draft
    draft = jnp.asarray(
        rng.integers(1, max(2, vocab - 2), (B, D)).astype(np.int32)
    )
    lens = jnp.full((B,), D, jnp.int32)
    verify_args_tree = (params, spec_engine._state, draft, lens)
    closed_v = jax.make_jaxpr(spec_engine.verify_step_jit)(
        *verify_args_tree
    )
    verify_result = sanitize_jaxpr(
        closed_v,
        jax.tree_util.tree_leaves(verify_args_tree),
        subject=f"{kind}.engine_verify_step",
        mesh=mesh_shape,
        arg_names=flat_input_paths(
            *verify_args_tree,
            prefixes=("params", "state", "draft", "draft_len"),
        ),
    )
    if verify_result.offence is not None:
        return verify_result
    return SanitizeResult(
        subject=f"{kind}.engine_decode_step+engine_verify_step",
        mesh=mesh_shape,
        n_eqns_checked=(
            decode_result.n_eqns_checked + verify_result.n_eqns_checked
        ),
        offence=None,
    )


def sanitize_trainer(
    kind: str,
    mesh: Optional[Dict[str, int]] = None,
    plant: bool = False,
    seed: int = 0,
    streamed: bool = False,
) -> SanitizeResult:
    """Build the tiny harness trainer, capture its train-step jaxpr over
    concrete (state, batch), and replay eqn-by-eqn.

    ``streamed=True`` replays the *streamed* epoch-1 step of the
    overlapped collect→train phase (docs/async_pipeline.md): the
    minibatch is produced the way the streamed dispatcher produces it —
    rollout rows land chunk-by-chunk in the streaming buffer
    (``dynamic_update_slice`` writes, the SPMD-safe path) and the
    replayed step consumes the first plan minibatch gathered from the
    partially-identical store — so sharded-store corruption of the class
    the PR-2 concat bug belonged to shows up as the replay's first
    non-finite equation."""
    import jax

    from trlx_tpu.analysis import harness

    trainer = harness.build_trainer(kind, mesh)
    state = trainer.state
    if plant:
        state = plant_nan(state)
    mb = harness.concrete_minibatch(trainer, kind, seed=seed)
    subject = f"{kind}.train_step"
    if streamed:
        if kind == "ilql":
            raise ValueError(
                "--streamed replays the PPO-family streamed phase; ILQL "
                "has no streamed collect→train path"
            )
        from trlx_tpu.pipeline.ppo_buffer import make_stream_plan

        B = trainer.config.train.batch_size
        plan = make_stream_plan(
            B, B, trainer.config.method.ppo_epochs, seed
        )
        trainer.buffer.clear_history()
        trainer.buffer.begin_stream(plan.total)
        half = max(B // 2, 1)
        trainer.buffer.push(jax.tree_util.tree_map(lambda x: x[:half], mb))
        if half < B:
            trainer.buffer.push(
                jax.tree_util.tree_map(lambda x: x[half:], mb)
            )
        mb = trainer.buffer.gather(
            plan.epoch1[0], sharding=trainer._batch_sh
        )
        subject = f"{kind}.streamed_step"
    closed = jax.make_jaxpr(trainer._train_step_jit)(state, mb)
    args = jax.tree_util.tree_leaves((state, mb))
    names = _flat_input_names(state, mb)
    mesh_shape = {k: int(v) for k, v in trainer.mesh.shape.items()}
    return sanitize_jaxpr(
        closed,
        args,
        subject=subject + (".planted" if plant else ""),
        mesh=mesh_shape,
        arg_names=names,
    )
