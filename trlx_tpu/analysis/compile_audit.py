"""Compile-stability audit: runtime trace counting + jaxpr drift.

Engine 8 of ``trlx_tpu.analysis``. Silent recompilation is the dominant
un-instrumented TPU perf killer in a pjit training loop: one
shape-varying call site (a buffer resized to an arbitrary capacity, a
host scalar rehashing the jit cache key) recompiles the whole train step
mid-run, costs minutes of XLA time at real shapes, and shows up nowhere
— not in loss curves, not in the other engines. Three complementary
checks:

- **trace-count harness** (``python -m trlx_tpu.analysis
  --compile-audit``): runs each trainer's canonical short loop on the
  CPU audit mesh with a compilation hook installed (the
  ``jax_log_compiles`` log stream, which names the jitted callable per
  *actual backend compile* — cache hits are silent), attributes every
  compile to its callable, and gates per-callable counts against the
  ``compile_budgets`` section of ``analysis/budgets.json`` (rule
  ``compile-count-regression``; relock via ``--update-budgets``). Every
  driven callable is invoked again with steady-state inputs after its
  first compile — a compile observed in that window is an
  ``unexpected-retrace``.
- **jaxpr drift**: the same program is traced at step 0 and at step k
  and the canonicalized equation lists are diffed; the first divergent
  equation (shape, dtype/weak_type, or static-arg provenance) ships
  inside the retrace finding, so the report names the *cause* of the
  recompilation, not just the count.
- **AST retrace-risk rules** (rule ``retrace-risk``, also in ``--engine
  all``): untraced trainer/orchestrator loop code feeding a ``*_jit``
  call site values derived from ``len()`` / ``.item()`` / ``int(...)``
  (each distinct value is a fresh cache key), passing non-literal
  expressions in ``static_argnums`` positions, and jit-traced functions
  closing over module globals that other functions mutate (the traced
  value is baked at compile time; mutation silently uses stale data or
  retraces).

The counts are *contracts*: deterministic for a given (config, mesh,
jax version). The harness runs real compiles, so it lives behind its own
CLI flag (and CI job) rather than inside ``--engine all``.
"""

from __future__ import annotations

import hashlib
import logging
import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from trlx_tpu.analysis.findings import Finding, Report, filter_suppressed
from trlx_tpu.analysis.registry import get_rule

# loggers that carry the compile/trace records we count (jax 0.4.x:
# pxla logs "Compiling <name> with global shapes and types [...]" once
# per actual backend compile; dispatch logs the trace/compile timings)
_JAX_COMPILE_LOGGERS = (
    "jax._src.interpreters.pxla",
    "jax._src.dispatch",
)

_COMPILING_RE = re.compile(r"^Compiling ([^\s]+) with global shapes and types (.*)$", re.S)
_TRACING_RE = re.compile(r"^Finished tracing \+ transforming ([^\s]+) for pjit in ([0-9.eE+-]+) sec")
_COMPILED_RE = re.compile(r"^Finished XLA compilation of jit\(([^\s)]+)\) in ([0-9.eE+-]+) sec")


@dataclass
class CompileEvent:
    """One actual backend compilation, as logged by pxla."""

    name: str  # the jitted callable's __name__
    arg_spec: str  # abstract arg shapes/dtypes at the compiling call
    steady: bool  # fired after the harness declared steady state


class CompileMonitor:
    """Context manager counting actual XLA compiles per callable name.

    Uses the ``jax_log_compiles`` record stream at DEBUG level (the
    records are emitted regardless of the config flag; the flag only
    raises their priority), so nothing is printed and no jax internals
    are patched. A compile cache hit emits nothing — counts are *real*
    compiles, exactly what a retrace audit must see.
    """

    def __init__(self) -> None:
        self.events: List[CompileEvent] = []
        self.trace_seconds = 0.0
        self.compile_seconds = 0.0
        self._steady = False
        self._handler: Optional[logging.Handler] = None
        self._saved_levels: Dict[str, int] = {}
        self._saved_propagate: Dict[str, bool] = {}

    # ------------------------------ phases ------------------------------ #

    def mark_steady(self) -> None:
        """Everything after this point is a steady-state repeat: any
        compile recorded from here on is an unexpected retrace."""
        self._steady = True

    def mark_warmup(self) -> None:
        self._steady = False

    def counts(self, steady_only: bool = False) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for e in self.events:
            if steady_only and not e.steady:
                continue
            out[e.name] = out.get(e.name, 0) + 1
        return out

    # ---------------------------- log plumbing --------------------------- #

    def _emit(self, record: logging.LogRecord) -> None:
        try:
            msg = record.getMessage()
        except Exception:
            return
        m = _COMPILING_RE.match(msg)
        if m:
            self.events.append(
                CompileEvent(
                    name=m.group(1),
                    arg_spec=m.group(2).strip(),
                    steady=self._steady,
                )
            )
            return
        m = _TRACING_RE.match(msg)
        if m:
            self.trace_seconds += float(m.group(2))
            return
        m = _COMPILED_RE.match(msg)
        if m:
            self.compile_seconds += float(m.group(2))

    def __enter__(self) -> "CompileMonitor":
        monitor = self

        class _Handler(logging.Handler):
            def emit(self, record: logging.LogRecord) -> None:
                monitor._emit(record)

        self._handler = _Handler(level=logging.DEBUG)
        for name in _JAX_COMPILE_LOGGERS:
            lg = logging.getLogger(name)
            self._saved_levels[name] = lg.level
            # the records are emitted at DEBUG unless jax_log_compiles is
            # set; open the logger without touching global jax config
            if lg.level == 0 or lg.level > logging.DEBUG:
                lg.setLevel(logging.DEBUG)
            # opening the logger at DEBUG would otherwise spray every
            # compile record through the root handler — keep the stream
            # private to this monitor while it is attached
            self._saved_propagate[name] = lg.propagate
            lg.propagate = False
            lg.addHandler(self._handler)
        return self

    def __exit__(self, *exc) -> None:
        for name in _JAX_COMPILE_LOGGERS:
            lg = logging.getLogger(name)
            if self._handler is not None:
                lg.removeHandler(self._handler)
            lg.setLevel(self._saved_levels.get(name, 0))
            lg.propagate = self._saved_propagate.get(name, True)
        self._handler = None


# ------------------------------ jaxpr drift ------------------------------ #

def canonical_eqns(closed_jaxpr, _depth: int = 0) -> List[str]:
    """Canonicalized equation lines of a (closed) jaxpr: variables renamed
    to serial ids, avals printed with weak_type, static params sorted —
    two traces of the same program produce identical lists iff nothing
    that feeds the compile cache key changed.

    Call-like sub-jaxprs (pjit, remat, scan/cond bodies, custom_*) are
    INLINED as indented lines, not summarized: the drift diff must both
    detect an inner-equation change (a same-length summary like
    ``<jaxpr:3eqns>`` would hash identically) and *name* the divergent
    inner equation — a traced ``jax.jit`` wrapper is a single outer pjit
    eqn, so without inlining every real divergence would be reported as
    the whole train step."""
    inner = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    names: Dict[int, str] = {}
    pad = "  " * _depth

    def ref(v) -> str:
        if hasattr(v, "val"):  # Literal
            return f"lit({v.val!r})"
        if id(v) not in names:
            names[id(v)] = f"v{len(names)}"
        return names[id(v)]

    def aval_str(v) -> str:
        aval = getattr(v, "aval", None)
        if aval is None:
            return "?"
        weak = getattr(aval, "weak_type", False)
        return f"{aval.str_short()}{'~w' if weak else ''}"

    def is_jaxpr(val) -> bool:
        return hasattr(val, "jaxpr") or hasattr(val, "eqns")

    def param_str(params: Dict, sub_lines: List[str]) -> str:
        parts = []
        for k in sorted(params):
            val = params[k]
            if is_jaxpr(val):
                parts.append(f"{k}=<jaxpr>")
                sub_lines.extend(canonical_eqns(val, _depth + 1))
            elif isinstance(val, (list, tuple)) and any(
                is_jaxpr(x) for x in val
            ):
                parts.append(f"{k}=<jaxprs:{len(val)}>")
                for x in val:
                    if is_jaxpr(x):
                        sub_lines.extend(canonical_eqns(x, _depth + 1))
            else:
                parts.append(f"{k}={val!r}")
        return ",".join(parts)

    for v in list(inner.constvars) + list(inner.invars):
        ref(v)
    lines = [
        pad
        + "in "
        + " ".join(f"{ref(v)}:{aval_str(v)}" for v in inner.invars)
    ]
    for eqn in inner.eqns:
        ins = " ".join(f"{ref(v)}:{aval_str(v)}" for v in eqn.invars)
        outs = " ".join(f"{ref(v)}:{aval_str(v)}" for v in eqn.outvars)
        sub_lines: List[str] = []
        params = param_str(eqn.params, sub_lines)
        lines.append(f"{pad}{eqn.primitive.name}[{params}] {ins} -> {outs}")
        lines.extend(sub_lines)
    return lines


def jaxpr_fingerprint(closed_jaxpr) -> str:
    digest = hashlib.sha256()
    for line in canonical_eqns(closed_jaxpr):
        digest.update(line.encode())
    return digest.hexdigest()[:16]


@dataclass
class JaxprDrift:
    """First divergence between two traces of one program."""

    eqn_index: int  # -1: different eqn counts with a common prefix
    before: str
    after: str
    cause: str  # "shape" | "dtype" | "weak_type" | "static-args" | "structure"

    def describe(self) -> str:
        where = (
            "program input signature diverged"
            if self.eqn_index < 0
            else f"first divergent eqn #{self.eqn_index}"
        )
        before, after = _focus_divergence(self.before, self.after)
        return (
            f"{where} [{self.cause}]: "
            f"step-0 `{before}` vs step-k `{after}`"
        )


def _focus_divergence(
    before: str, after: str, width: int = 160
) -> Tuple[str, str]:
    """Window both lines around their first differing character — a train
    step's input-signature line holds hundreds of avals, and the finding
    must show the drifting operand, not the whole state tree."""
    if max(len(before), len(after)) <= width:
        return before, after
    i = 0
    for i, (b, a) in enumerate(zip(before, after)):
        if b != a:
            break
    start = max(0, i - width // 4)

    def clip(s: str) -> str:
        end = start + width
        head = "..." if start else ""
        tail = "..." if end < len(s) else ""
        return f"{head}{s[start:end]}{tail}"

    return clip(before), clip(after)


def _classify_drift(before: str, after: str) -> str:
    """Name what changed between two canonical eqn lines."""
    aval_re = re.compile(r"v\d+:([a-z0-9_]+)\[([\d,]*)\](~w)?")
    b, a = aval_re.findall(before), aval_re.findall(after)
    if len(b) == len(a) and b != a:
        for (bd, bs, bw), (ad, as_, aw) in zip(b, a):
            if bs != as_:
                return "shape"
            if bd != ad:
                return "dtype"
            if bw != aw:
                return "weak_type"
    b_head, a_head = before.split(" ", 1)[0], after.split(" ", 1)[0]
    if b_head.split("[")[0] != a_head.split("[")[0]:
        return "structure"
    if b_head != a_head:
        return "static-args"
    return "structure"


def diff_jaxprs(before_jaxpr, after_jaxpr) -> Optional[JaxprDrift]:
    """Diff two traces of the same program; ``None`` when identical."""
    before = canonical_eqns(before_jaxpr)
    after = canonical_eqns(after_jaxpr)
    if before == after:
        return None
    for i, (b, a) in enumerate(zip(before, after)):
        if b != a:
            return JaxprDrift(
                eqn_index=i - 1,  # line 0 is the input signature
                before=b,
                after=a,
                # a line-0 divergence is the program input signature
                # itself changing — classify it like any other aval diff
                cause=_classify_drift(b, a),
            )
    # one trace is a strict prefix of the other
    longer = before if len(before) > len(after) else after
    i = min(len(before), len(after))
    return JaxprDrift(
        eqn_index=i - 1,
        before=before[i] if len(before) > len(after) else "<absent>",
        after="<absent>" if len(before) > len(after) else longer[i],
        cause="structure",
    )


# --------------------------- the canonical loop --------------------------- #

@dataclass
class DrivenProgram:
    """One jitted callable exercised by the canonical loop."""

    subject: str  # "ppo.train_step"
    log_name: str  # the name pxla logs compiles under
    def_site: Optional[Tuple[str, int]]
    compiles: int = 0
    steady_compiles: int = 0
    drift: Optional[JaxprDrift] = None
    trace0_fingerprint: str = ""
    tracek_fingerprint: str = ""


def _log_name(fn) -> str:
    inner = getattr(fn, "__wrapped__", fn)
    return getattr(inner, "__name__", "<unnamed>")


def _sds_args(args) -> Any:
    import jax

    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(
            getattr(x, "shape", ()), getattr(x, "dtype", None),
            weak_type=bool(getattr(x, "weak_type", False)),
        ),
        args,
    )


def drive_trainer(
    kind: str,
    mesh: Optional[Dict[str, int]] = None,
    monitor: Optional[CompileMonitor] = None,
    steps: int = 2,
    instrument=None,
    train_overrides: Optional[Dict] = None,
) -> Tuple[List[DrivenProgram], CompileMonitor, Dict[str, int]]:
    """Run ``kind``'s canonical short loop under a compile monitor.

    The loop mirrors production dispatch order (rollout → stepwise update
    → fused phase → behavior snapshot) at the harness shapes. Every
    jitted callable is invoked at least twice with steady-state inputs;
    after the warmup pass the monitor is flipped to steady, so *any*
    compile in the second pass is an unexpected retrace. The train step's
    inputs are signature-captured at step 0 and step k, and re-traced at
    the end (tracing is compile-free) for the drift diff.

    ``instrument``, when given, is called with the freshly built trainer
    before any program runs — the lockstep simulator (engine 11) uses it
    to wrap every ``*_jit`` attribute with a dispatch recorder, so both
    engines share ONE canonical loop instead of drifting copies.
    ``train_overrides`` forwards to the harness config (same reason).
    """
    import jax

    from trlx_tpu.analysis import harness

    own_monitor = monitor is None
    monitor = monitor or CompileMonitor()
    mesh_shape: Dict[str, int] = {}

    def run_loop() -> List[DrivenProgram]:
        import jax.numpy as jnp

        from trlx_tpu.parallel.mesh import batch_sharding

        nonlocal mesh_shape
        trainer = harness.build_trainer(
            kind, mesh, train_overrides=train_overrides
        )
        if instrument is not None:
            instrument(trainer)
        mesh_shape.update(
            {k: int(v) for k, v in trainer.mesh.shape.items()}
        )
        batch_sh = getattr(trainer, "_batch_sh", None) or batch_sharding(
            trainer.mesh
        )
        B = trainer.config.train.batch_size
        Q = trainer.query_length
        prompt_ids = jnp.ones((B, Q), jnp.int32)
        prompt_mask = jnp.ones((B, Q), jnp.int32)

        driven: List[DrivenProgram] = []

        def register(subject: str, fn) -> DrivenProgram:
            d = DrivenProgram(
                subject=subject,
                log_name=_log_name(fn),
                def_site=harness.callable_def_site(fn),
            )
            driven.append(d)
            return d

        d_rollout = register(f"{kind}.rollout", trainer._sample_jit)
        d_step = register(f"{kind}.train_step", trainer._train_step_jit)
        if kind != "ilql":
            d_phase = register(
                f"{kind}.train_phase", trainer._train_phase_jit
            )
            d_snap = register(
                f"{kind}.behavior_snapshot", trainer._behavior_snapshot_jit
            )
        engine = None
        if kind == "ppo":
            # the continuous-batching engine's programs (docs/inference.md)
            # join the canonical loop: one mini slot-admission phase per
            # pass — a retrace on the second pass means the engine's
            # jitted shapes are not steady (e.g. per-phase state
            # reallocation changed a shape)
            engine = trainer.rollout_engine_obj
            register(f"{kind}.engine_prefill", engine.prefill_jit)
            register(f"{kind}.engine_decode_step", engine.decode_step_jit)
            register(f"{kind}.engine_refill", engine.refill_jit)

        step_args: List[Any] = []  # captured (state, mb) signatures

        def one_pass(step_seed: int) -> None:
            # rollout: the sampler consumes (params, prompts, key); the
            # key changes per call exactly as trainer.sample() does it
            trainer.sample(prompt_ids, prompt_mask)
            # stepwise update: fresh minibatch VALUES, stable shapes
            mb = harness.concrete_minibatch(trainer, kind, seed=step_seed)
            mb = jax.device_put(mb, batch_sh)
            step_args.append(_sds_args((trainer.state, mb)))
            trainer.state, _stats = trainer._train_step_jit(
                trainer.state, mb
            )
            if kind == "ilql":
                return
            # fused phase over 2 stacked minibatches + phase snapshot
            stacked = jax.tree_util.tree_map(
                lambda a, b: jnp.stack([a, b]),
                harness.concrete_minibatch(trainer, kind, seed=step_seed),
                harness.concrete_minibatch(
                    trainer, kind, seed=step_seed + 17
                ),
            )
            stacked = jax.device_put(stacked, trainer._stacked_batch_sh)
            trainer.state, _ = trainer._train_phase_jit(
                trainer.state, stacked
            )
            trainer._behavior_snapshot_jit(trainer.state.params)
            if engine is not None:
                # one harvest group through the slot-admission loop:
                # fresh prompt VALUES per pass, stable shapes
                import numpy as _np

                rng = _np.random.default_rng(step_seed)
                n = engine.harvest_width
                eng_ids = rng.integers(1, 30, (n, Q)).astype(_np.int32)
                engine.start_phase(
                    trainer.rollout_params(),
                    jax.random.fold_in(
                        jax.random.PRNGKey(0), step_seed
                    ),
                )
                engine.submit(eng_ids, _np.ones((n, Q), _np.int32))
                for _group in engine.drive(n):
                    pass

        one_pass(0)
        monitor.mark_steady()
        for s in range(1, max(2, steps)):
            one_pass(s)

        # attribute counts; drift-trace the step program at step 0 vs k
        warm = monitor.counts(steady_only=False)
        steady = monitor.counts(steady_only=True)
        for d in driven:
            d.compiles = warm.get(d.log_name, 0)
            d.steady_compiles = steady.get(d.log_name, 0)
        state0, mb0 = step_args[0]
        statek, mbk = step_args[-1]
        j0 = jax.make_jaxpr(trainer._train_step_jit)(state0, mb0)
        jk = jax.make_jaxpr(trainer._train_step_jit)(statek, mbk)
        d_step.trace0_fingerprint = jaxpr_fingerprint(j0)
        d_step.tracek_fingerprint = jaxpr_fingerprint(jk)
        d_step.drift = diff_jaxprs(j0, jk)
        return driven

    if own_monitor:
        with monitor:
            driven = run_loop()
    else:
        driven = run_loop()
    return driven, monitor, mesh_shape


# ------------------------------- budgets --------------------------------- #

def make_compile_budgets(
    driven: Sequence[DrivenProgram], mesh: Dict[str, int]
) -> Dict:
    return {
        "mesh": {k: int(v) for k, v in sorted(mesh.items())},
        "programs": {
            d.subject: {"compiles": d.compiles}
            for d in sorted(driven, key=lambda d: d.subject)
        },
    }


def check_compile_budgets(
    driven: Sequence[DrivenProgram],
    budgets: Dict,
    mesh: Optional[Dict[str, int]] = None,
    budgets_path: Optional[str] = None,
) -> List[Finding]:
    """Gate observed compile counts against the committed contract."""
    rule = get_rule("compile-count-regression")
    findings: List[Finding] = []
    where = os.path.basename(budgets_path or "budgets.json")
    section = budgets.get("compile_budgets")
    if section is None:
        return [
            Finding(
                rule=rule.id,
                message=(
                    f"{where} has no compile_budgets section — lock the "
                    "compile counts with --compile-audit --update-budgets "
                    "and commit the diff"
                ),
                severity=rule.severity,
                subject="compile_budgets",
                engine="compile",
            )
        ]
    locked_mesh = section.get("mesh")
    if mesh is not None and locked_mesh is not None:
        current = {k: int(v) for k, v in sorted(mesh.items())}
        locked = {k: int(v) for k, v in sorted(locked_mesh.items())}
        if locked != current:
            return [
                Finding(
                    rule=rule.id,
                    message=(
                        f"compile budgets in {where} were locked for mesh "
                        f"{locked_mesh} but the audit ran on {current} — "
                        "counts are not comparable; rerun on the locked "
                        "mesh or --update-budgets"
                    ),
                    severity=rule.severity,
                    subject="compile_budgets",
                    engine="compile",
                )
            ]
    programs = section.get("programs", {})
    for d in driven:
        file, line = d.def_site or (None, None)
        entry = programs.get(d.subject)
        if entry is None:
            findings.append(
                Finding(
                    rule=rule.id,
                    message=(
                        f"no committed compile budget for driven program "
                        f"`{d.subject}` ({d.compiles} compile(s) observed) "
                        "— run --compile-audit --update-budgets and review "
                        "the lockfile diff"
                    ),
                    severity=rule.severity,
                    file=file,
                    line=line,
                    subject=d.subject,
                    engine="compile",
                )
            )
            continue
        locked_n = int(entry.get("compiles", 0))
        if d.compiles > locked_n:
            findings.append(
                Finding(
                    rule=rule.id,
                    message=(
                        f"`{d.subject}` compiled {d.compiles}× over the "
                        f"canonical loop, past the committed {locked_n}× — "
                        "each extra compile is minutes of XLA time at real "
                        "shapes; if intended, relock with --compile-audit "
                        "--update-budgets and explain the diff"
                    ),
                    severity=rule.severity,
                    file=file,
                    line=line,
                    subject=d.subject,
                    engine="compile",
                )
            )
    driven_kinds = {d.subject.split(".")[0] for d in driven}
    current_subjects = {d.subject for d in driven}
    for stale in sorted(set(programs) - current_subjects):
        if stale.split(".")[0] in driven_kinds:
            findings.append(
                Finding(
                    rule=rule.id,
                    message=(
                        f"compile budget entry `{stale}` no longer matches "
                        "any driven program — prune it with "
                        "--compile-audit --update-budgets"
                    ),
                    severity="warning",
                    subject=stale,
                    engine="compile",
                )
            )
    return findings


def retrace_findings(driven: Sequence[DrivenProgram]) -> List[Finding]:
    """unexpected-retrace findings for steady-window compiles, with the
    jaxpr drift attached when the step-0/step-k traces disagree."""
    rule = get_rule("unexpected-retrace")
    findings: List[Finding] = []
    for d in driven:
        if not d.steady_compiles:
            continue
        if d.drift is not None:
            cause = f"; jaxpr drift: {d.drift.describe()}"
        elif d.trace0_fingerprint and (
            d.trace0_fingerprint == d.tracek_fingerprint
        ):
            cause = (
                "; traced program is IDENTICAL at step 0 and step k — the "
                "retrace came from cache-key churn outside the jaxpr "
                "(rebuilt callable identity, non-hashable static args)"
            )
        else:
            cause = ""
        file, line = d.def_site or (None, None)
        findings.append(
            Finding(
                rule=rule.id,
                message=(
                    f"`{d.subject}` recompiled {d.steady_compiles}× during "
                    "the steady-state repeat of the canonical loop — a "
                    "shape-/dtype-varying call site retraces this program "
                    f"every step at real shapes{cause}"
                ),
                severity=rule.severity,
                file=file,
                line=line,
                subject=d.subject,
                engine="compile",
            )
        )
    return findings


# --------------------------- AST retrace risks ---------------------------- #

_HOST_VARYING_CALLS = ("len", "int")


def _expr_retrace_risk(node) -> Optional[str]:
    """Why an argument expression fed to a jitted call risks retraces;
    ``None`` when it looks safe."""
    import ast

    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            func = sub.func
            if (
                isinstance(func, ast.Name)
                and func.id in _HOST_VARYING_CALLS
                and sub.args
                and not isinstance(sub.args[0], ast.Constant)
            ):
                return (
                    f"derives a Python scalar via {func.id}() — every "
                    "distinct value is a fresh jit cache key (weak-typed "
                    "scalar), so the callable recompiles per value"
                )
            if isinstance(func, ast.Attribute) and func.attr == "item":
                return (
                    "derives a Python scalar via .item() — a per-step "
                    "device value becomes a fresh jit cache key each step"
                )
    return None


def lint_retrace_risk(paths: Sequence[str]) -> Tuple[List[Finding], List[str], int]:
    """AST pass over untraced (host-loop) code: per-step-varying host
    scalars fed to ``*_jit`` call sites, non-literal static args, and
    traced closures over mutated module globals."""
    import ast

    from trlx_tpu.analysis.ast_lint import (
        _FunctionIndex,
        _ImportAliases,
        _is_trace_entry,
        _transitively_traced,
        collect_py_files,
    )

    rule = get_rule("retrace-risk")
    files = collect_py_files(paths)

    findings: List[Finding] = []
    n_suppressed = 0
    for path in files:
        try:
            with open(path, encoding="utf-8") as fh:
                source = fh.read()
            tree = ast.parse(source, filename=path)
        except (OSError, SyntaxError):
            continue
        aliases = _ImportAliases()
        aliases.visit(tree)
        index = _FunctionIndex(aliases)
        index.visit(tree)
        traced = _transitively_traced(index)

        # names bound by `g = jax.jit(f, static_argnums=...)` and the
        # positions of their static args
        static_positions: Dict[str, Set[int]] = {}
        mutated_globals: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Global):
                mutated_globals.update(node.names)
            if not isinstance(node, ast.Assign):
                continue
            value = node.value
            if not (
                isinstance(value, ast.Call)
                and _is_trace_entry(value.func, aliases)
            ):
                continue
            positions: Set[int] = set()
            for kw in value.keywords:
                if kw.arg == "static_argnums" and isinstance(
                    kw.value, (ast.Tuple, ast.Constant)
                ):
                    elts = (
                        kw.value.elts
                        if isinstance(kw.value, ast.Tuple)
                        else [kw.value]
                    )
                    positions = {
                        e.value
                        for e in elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, int)
                    }
            if positions:
                for target in node.targets:
                    name = None
                    if isinstance(target, ast.Name):
                        name = target.id
                    elif isinstance(target, ast.Attribute):
                        name = target.attr
                    if name:
                        static_positions[name] = positions

        def add(node, message: str, subject: str) -> None:
            findings.append(
                Finding(
                    rule=rule.id,
                    message=message,
                    severity=rule.severity,
                    file=path,
                    line=getattr(node, "lineno", None),
                    subject=subject,
                    engine="compile",
                )
            )

        # (1)+(2): jitted call sites in untraced functions
        for fname in sorted(set(index.defs) - traced):
            for fnode in index.defs.get(fname, ()):
                # one-hop taint: locals assigned from a host-varying
                # derivation (`n = len(batch)`) carry the risk to the
                # call site that consumes them
                tainted: Dict[str, str] = {}
                for sub in ast.walk(fnode):
                    if not (
                        isinstance(sub, ast.Assign)
                        and len(sub.targets) == 1
                        and isinstance(sub.targets[0], ast.Name)
                    ):
                        continue
                    why = _expr_retrace_risk(sub.value)
                    if why is not None:
                        tainted[sub.targets[0].id] = why
                for node in ast.walk(fnode):
                    if not isinstance(node, ast.Call):
                        continue
                    callee = None
                    if isinstance(node.func, ast.Attribute):
                        callee = node.func.attr
                    elif isinstance(node.func, ast.Name):
                        callee = node.func.id
                    if callee is None:
                        continue
                    is_jit_site = callee.endswith("_jit") or (
                        callee in static_positions
                    )
                    if not is_jit_site:
                        continue
                    for pos, arg in enumerate(node.args):
                        why = _expr_retrace_risk(arg)
                        if why is None and isinstance(arg, ast.Name):
                            why = tainted.get(arg.id)
                        if why is not None:
                            add(
                                arg,
                                f"jitted call site `{callee}(...)` arg "
                                f"{pos} {why}; pass a device array or a "
                                "step-invariant scalar",
                                f"{fname}()",
                            )
                        elif pos in static_positions.get(
                            callee, set()
                        ) and not isinstance(arg, ast.Constant):
                            if not (
                                isinstance(arg, ast.Attribute)
                                and "config" in ast.dump(arg)
                            ):
                                add(
                                    arg,
                                    f"static arg {pos} of `{callee}(...)` "
                                    "is a non-literal expression — every "
                                    "distinct (or unhashable) value "
                                    "recompiles the callable",
                                    f"{fname}()",
                                )

        # (3): traced functions reading module globals that something
        # mutates via `global X`
        if mutated_globals:
            for fname in sorted(traced):
                for fnode in index.defs.get(fname, ()):
                    assigned_here = {
                        t.id
                        for sub in ast.walk(fnode)
                        if isinstance(sub, ast.Assign)
                        for t in sub.targets
                        if isinstance(t, ast.Name)
                    }
                    for node in ast.walk(fnode):
                        if (
                            isinstance(node, ast.Name)
                            and isinstance(node.ctx, ast.Load)
                            and node.id in mutated_globals
                            and node.id not in assigned_here
                        ):
                            add(
                                node,
                                f"traced function closes over module "
                                f"global `{node.id}` that other code "
                                "mutates — the traced value is baked at "
                                "compile time; mutations are silently "
                                "ignored (or force retraces via static "
                                "hashing)",
                                f"{fname}()",
                            )
                            break

    kept, n_suppressed = filter_suppressed(findings)
    return kept, files, n_suppressed


# ----------------------------- orchestration ------------------------------ #

@dataclass
class CompileAuditResult:
    driven: List[DrivenProgram] = field(default_factory=list)
    mesh: Dict[str, int] = field(default_factory=dict)
    trace_seconds: float = 0.0
    compile_seconds: float = 0.0
    unattributed: Dict[str, int] = field(default_factory=dict)

    def to_rows(self) -> List[Dict]:
        return [
            {
                "subject": d.subject,
                "compiles": d.compiles,
                "steady_compiles": d.steady_compiles,
                "trace_fingerprint_step0": d.trace0_fingerprint,
                "trace_fingerprint_stepk": d.tracek_fingerprint,
                "drift": d.drift.describe() if d.drift else None,
            }
            for d in sorted(self.driven, key=lambda d: d.subject)
        ]


def audit_compiles(
    kinds: Optional[Sequence[str]] = None,
    mesh: Optional[Dict[str, int]] = None,
    budgets_path: Optional[str] = None,
    update: bool = False,
    steps: int = 2,
) -> Tuple[Report, CompileAuditResult]:
    """The ``--compile-audit`` entry point: drive every trainer's
    canonical loop under one monitor, then gate counts against (or with
    ``update=True`` relock) the ``compile_budgets`` section of
    ``analysis/budgets.json``. Also runs the AST retrace-risk rules so
    the CI job covers the static half of the engine."""
    from trlx_tpu.analysis import harness
    from trlx_tpu.analysis.resource_audit import (
        default_budgets_path,
        load_budgets,
        write_budgets,
    )

    path = budgets_path or default_budgets_path()
    result = CompileAuditResult()
    report = Report()
    all_driven: List[DrivenProgram] = []
    for kind in kinds or harness.TRAINER_KINDS:
        with CompileMonitor() as monitor:
            driven, _, mesh_shape = drive_trainer(
                kind, mesh, monitor=monitor, steps=steps
            )
        all_driven.extend(driven)
        result.mesh = mesh_shape or result.mesh
        result.trace_seconds += monitor.trace_seconds
        result.compile_seconds += monitor.compile_seconds
        named = {d.log_name for d in driven}
        for name, n in monitor.counts().items():
            if name not in named:
                result.unattributed[name] = (
                    result.unattributed.get(name, 0) + n
                )
    result.driven = all_driven
    report.covered += [f"compile:{d.subject}" for d in all_driven]

    findings = retrace_findings(all_driven)
    if update:
        try:
            budgets = load_budgets(path)
        except (OSError, ValueError):
            budgets = {}
        partial = kinds is not None
        section = make_compile_budgets(all_driven, result.mesh)
        old_section = budgets.get("compile_budgets") or {}
        if partial and old_section.get("mesh") not in (
            None, section["mesh"]
        ):
            rule = get_rule("compile-count-regression")
            report.extend([
                Finding(
                    rule=rule.id,
                    message=(
                        "refusing --update-budgets: the compile lockfile "
                        f"is for mesh {old_section.get('mesh')} but this "
                        f"--trainers subset ran on {section['mesh']} — "
                        "rerun without --trainers or on the locked mesh"
                    ),
                    severity=rule.severity,
                    subject="compile_budgets",
                    engine="compile",
                )
            ])
            return report, result
        if partial:
            kept = {
                s: dict(e)
                for s, e in old_section.get("programs", {}).items()
                if s.split(".")[0] not in {k for k in (kinds or ())}
            }
            kept.update(section["programs"])
            section["programs"] = {s: kept[s] for s in sorted(kept)}
        budgets["compile_budgets"] = section
        write_budgets(budgets, path)
        return report, result

    ast_findings, ast_covered, ast_suppressed = lint_retrace_risk(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
    )
    report.covered += [f"retrace-risk:{len(ast_covered)} files"]
    try:
        budgets = load_budgets(path)
    except (OSError, ValueError) as e:
        rule = get_rule("compile-count-regression")
        findings.append(
            Finding(
                rule=rule.id,
                message=(
                    f"cannot load budget contract {path}: {e} — generate "
                    "it with --compile-audit --update-budgets"
                ),
                severity=rule.severity,
                subject="compile_budgets",
                engine="compile",
            )
        )
        budgets = {}
    if budgets:
        findings += check_compile_budgets(
            all_driven, budgets, result.mesh, path
        )
    kept, suppressed = filter_suppressed(findings)
    report.extend(kept + ast_findings)
    report.suppressed += suppressed + ast_suppressed
    return report, result


def format_compile_text(result: CompileAuditResult) -> str:
    lines = [
        f"{'program':28} {'compiles':>9} {'steady':>7}  fingerprint(step0->k)"
    ]
    for row in result.to_rows():
        fp = row["trace_fingerprint_step0"]
        fpk = row["trace_fingerprint_stepk"]
        fps = f"{fp}->{fpk}" if fp or fpk else "-"
        lines.append(
            f"{row['subject']:28} {row['compiles']:>9} "
            f"{row['steady_compiles']:>7}  {fps}"
        )
        if row["drift"]:
            lines.append(f"  drift: {row['drift']}")
    lines.append(
        f"total: {result.compile_seconds:.1f}s XLA compile, "
        f"{result.trace_seconds:.1f}s trace"
        + (
            f"; unattributed compiles: {result.unattributed}"
            if result.unattributed
            else ""
        )
    )
    return "\n".join(lines)
