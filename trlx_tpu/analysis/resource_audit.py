"""Resource audit: static HBM / collective / FLOP budgets per program.

Engine 6 of ``trlx_tpu.analysis``. Nothing else in the stack says
*statically* how much memory, interconnect traffic, or compute a jitted
program needs — regressions surface as OOMs or slow benches on real
hardware. This engine derives three numbers from every traced jaxpr
(recursing pjit / scan / cond / remat sub-jaxprs) and gates them against
a committed contract file, ``analysis/budgets.json``:

- **peak live HBM bytes** (per device): a liveness walk over the program.
  Non-donated inputs are pinned for the whole program (the caller owns
  them); donated inputs die at their last use — donation IS in-place
  reuse, so a donating step's peak excludes the double-buffer. Input
  bytes divide by their sharding divisor (total / per-device shard
  elements, from the trainer's declared ``in_shardings``); divisors
  propagate through shape-preserving eqns, everything else is counted
  replicated (a deterministic upper bound).
- **collective cost model**: per-(primitive, mesh axes) counts and bytes
  moved per device, with standard ring factors over the operand bytes —
  psum ``2(n-1)/n``, all_gather ``(n-1)×`` (its operand is the
  pre-gather shard), reduce_scatter/all_to_all ``(n-1)/n``, ppermute
  ``1`` hop — where ``n`` is the product of the named axes' sizes.
  Collectives inside ``scan`` bodies multiply by the trip count.
- **FLOP estimate**: ``dot_general`` / ``conv_general_dilated`` exact
  MAC counting (2 FLOPs/MAC), scan bodies multiplied by length, cond
  branches at the max.

The numbers are *contracts, not measurements*: deterministic for a given
(config, mesh, jax version), monotone under buffer growth, and cheap
(tracing only — no compilation). ``--update-budgets`` regenerates the
lockfile; CI fails on unexplained growth (rules ``hbm-over-budget``,
``collective-bytes-regression``), turning perf/memory regressions into
reviewable diffs of ``budgets.json``.
"""

from __future__ import annotations

import json
import math
import os
from dataclasses import dataclass, field
from typing import (
    Any, Dict, Iterator, List, Optional, Sequence, Set, Tuple,
)

from trlx_tpu.analysis.findings import Finding, Report
from trlx_tpu.analysis.registry import get_rule

BUDGETS_SCHEMA_VERSION = 1
DEFAULT_TOLERANCE_PCT = 5.0

# collectives the cost model prices; axis_index moves no payload
COSTED_COLLECTIVES = {
    "psum", "psum2", "pmax", "pmin", "psum_invariant", "pbroadcast",
    "all_gather", "all_to_all", "reduce_scatter", "ppermute",
}


def default_budgets_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "budgets.json")


# ------------------------------- bytes ---------------------------------- #

def _aval_bytes(aval, divisor: int = 1) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = math.prod(int(s) for s in shape) if shape else 1
    return (n * dtype.itemsize) // max(1, divisor)


def _is_literal(v) -> bool:
    return hasattr(v, "val")  # jax.core.Literal


def _is_drop(v) -> bool:
    return type(v).__name__ == "DropVar"


def _sub_jaxprs_of(eqn) -> Iterator[Any]:
    from trlx_tpu.analysis.jaxpr_audit import _sub_jaxprs

    for sub in _sub_jaxprs(eqn):
        yield getattr(sub, "jaxpr", sub)  # open a ClosedJaxpr


# --------------------------- peak-HBM liveness --------------------------- #

def peak_live_bytes(
    jaxpr,
    input_divisors: Optional[Sequence[int]] = None,
    donated: Optional[Sequence[bool]] = None,
) -> int:
    """Peak simultaneously-live bytes of one (open) jaxpr.

    Liveness: a value is born when its eqn executes and dies after its
    last consumer. Non-donated inputs and program outputs are pinned for
    the whole program (caller-owned / escaping buffers); donated inputs
    die at their last use, which is exactly XLA's in-place reuse. Each
    sub-jaxpr contributes its internal overhead (its own peak beyond its
    boundary values) as a transient at its eqn — parent-level lifetimes
    already cover the boundary.
    """
    eqns = list(jaxpr.eqns)
    div: Dict[Any, int] = {}
    if input_divisors:
        for v, d in zip(jaxpr.invars, input_divisors):
            if d and d > 1:
                div[v] = int(d)

    def vb(v) -> int:
        if _is_literal(v) or _is_drop(v):
            return 0
        return _aval_bytes(v.aval, div.get(v, 1))

    last_use: Dict[Any, int] = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.invars:
            if not _is_literal(v):
                last_use[v] = i

    end = len(eqns)
    outset = {v for v in jaxpr.outvars if not _is_literal(v)}
    for v in outset:
        last_use[v] = end

    inputs = list(jaxpr.constvars) + list(jaxpr.invars)
    donated_mask = [False] * len(jaxpr.constvars) + list(
        donated if donated is not None else [False] * len(jaxpr.invars)
    )
    donated_mask += [False] * (len(inputs) - len(donated_mask))
    current = 0
    for v, don in zip(inputs, donated_mask):
        current += vb(v)
        if v in outset:
            continue
        if not don:
            last_use[v] = end  # caller keeps the buffer alive throughout
        elif v not in last_use:
            last_use[v] = -1  # unused donated input: reusable immediately
    peak = current
    for v, don in zip(inputs, donated_mask):
        if last_use.get(v) == -1:
            current -= vb(v)

    for i, eqn in enumerate(eqns):
        # propagate sharding divisors through shape-preserving eqns so a
        # cast/elementwise image of a sharded input stays per-device
        if len(eqn.outvars) == 1 and not _is_drop(eqn.outvars[0]):
            out_shape = getattr(eqn.outvars[0].aval, "shape", None)
            best = 1
            for v in eqn.invars:
                if (
                    not _is_literal(v)
                    and v in div
                    and getattr(v.aval, "shape", None) == out_shape
                ):
                    best = max(best, div[v])
            if best > 1:
                div[eqn.outvars[0]] = best

        inner_extra = 0
        for sub in _sub_jaxprs_of(eqn):
            sub_div = None
            if len(sub.invars) == len(eqn.invars):
                sub_div = [
                    1 if _is_literal(v) else div.get(v, 1)
                    for v in eqn.invars
                ]
            sub_peak = peak_live_bytes(
                sub, sub_div, [True] * len(sub.invars)
            )
            boundary = sum(
                _aval_bytes(v.aval, (sub_div or [1] * len(sub.invars))[k])
                for k, v in enumerate(sub.invars)
            ) + sum(
                0 if _is_literal(v) else _aval_bytes(v.aval)
                for v in sub.outvars
            )
            inner_extra = max(inner_extra, max(0, sub_peak - boundary))

        outs = [v for v in eqn.outvars if not _is_drop(v)]
        for v in outs:
            if v not in last_use and v not in outset:
                last_use[v] = i  # produced and never consumed
        current += sum(vb(v) for v in outs)
        peak = max(peak, current + inner_extra)
        released = set()
        for v in list(eqn.invars) + outs:
            if _is_literal(v) or v in released:
                continue
            if last_use.get(v, end) == i:
                current -= vb(v)
                released.add(v)
    return peak


# ----------------------------- FLOP counting ----------------------------- #

def count_flops(jaxpr) -> int:
    """Matmul/conv FLOPs of a jaxpr (2 FLOPs per MAC), scan bodies
    multiplied by trip count, cond branches at the max, while bodies
    counted once (trip count is data-dependent)."""
    total = 0
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "dot_general":
            (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
            lhs = eqn.invars[0].aval.shape
            rhs = eqn.invars[1].aval.shape
            batch = math.prod(int(lhs[i]) for i in lb) if lb else 1
            contract = math.prod(int(lhs[i]) for i in lc) if lc else 1
            m = math.prod(
                int(s) for i, s in enumerate(lhs) if i not in set(lb) | set(lc)
            )
            n = math.prod(
                int(s) for i, s in enumerate(rhs) if i not in set(rb) | set(rc)
            )
            total += 2 * batch * m * n * contract
        elif name == "conv_general_dilated":
            out = eqn.outvars[0].aval.shape
            rhs = eqn.invars[1].aval.shape
            groups = int(eqn.params.get("feature_group_count", 1))
            # per output element: one MAC per kernel element of its group
            kernel_macs = math.prod(int(s) for s in rhs) // max(
                1, int(out[1]) if len(out) > 1 else 1
            )
            total += 2 * math.prod(int(s) for s in out) * max(
                1, kernel_macs // max(1, groups)
            )
        elif name == "scan":
            body = eqn.params["jaxpr"]
            total += int(eqn.params.get("length", 1)) * count_flops(
                getattr(body, "jaxpr", body)
            )
        elif name == "cond":
            total += max(
                (
                    count_flops(getattr(b, "jaxpr", b))
                    for b in eqn.params["branches"]
                ),
                default=0,
            )
        else:
            for sub in _sub_jaxprs_of(eqn):
                total += count_flops(sub)
    return total


# --------------------------- collective model ---------------------------- #

def _moved_bytes(prim: str, payload: int, n: int) -> int:
    """Bytes one device moves for a collective over ``n`` participants,
    where ``payload`` is the operand (invar) bytes — standard ring
    algorithms; n == 1 moves nothing. Note the operand-size asymmetry:
    psum/reduce_scatter/all_to_all operate on full-size inputs, so the
    ring factor is fractional, while all_gather's operand is the
    PRE-gather shard — each device moves (n-1) shards to assemble the
    n-shard output."""
    if n <= 1:
        return 0
    if prim in ("psum", "psum2", "pmax", "pmin", "psum_invariant"):
        return int(2 * (n - 1) / n * payload)
    if prim == "all_gather":
        return (n - 1) * payload
    if prim in ("reduce_scatter", "all_to_all"):
        return int((n - 1) / n * payload)
    # ppermute / pbroadcast: one payload hop
    return payload


def collective_costs(
    jaxpr, axis_sizes: Dict[str, int], _mult: int = 1
) -> Dict[str, Dict[str, int]]:
    """Per-(primitive, axes) collective counts and modeled bytes moved,
    recursing sub-jaxprs; scan bodies multiply by trip count."""
    from trlx_tpu.analysis.jaxpr_audit import _axis_names_of

    costs: Dict[str, Dict[str, int]] = {}
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in COSTED_COLLECTIVES:
            axes = tuple(_axis_names_of(eqn))
            n = math.prod(int(axis_sizes.get(a, 1)) for a in axes) if axes else 1
            payload = sum(
                _aval_bytes(v.aval)
                for v in eqn.invars
                if not _is_literal(v)
            )
            key = f"{name}[{','.join(axes)}]"
            entry = costs.setdefault(key, {"count": 0, "bytes": 0})
            entry["count"] += _mult
            entry["bytes"] += _mult * _moved_bytes(name, payload, n)
            continue
        mult = _mult
        if name == "scan":
            mult = _mult * int(eqn.params.get("length", 1))
        for sub in _sub_jaxprs_of(eqn):
            for key, sub_entry in collective_costs(
                sub, axis_sizes, mult
            ).items():
                entry = costs.setdefault(key, {"count": 0, "bytes": 0})
                entry["count"] += sub_entry["count"]
                entry["bytes"] += sub_entry["bytes"]
    return costs


# ------------------------------ per program ------------------------------ #

@dataclass
class ProgramResources:
    subject: str
    peak_hbm_bytes: int
    input_bytes: int
    donated_bytes: int
    output_bytes: int
    flops: int
    collectives: Dict[str, Dict[str, int]] = field(default_factory=dict)
    # (file, line) of the traced callable's def — budget findings anchor
    # here so `# tpu-lint: disable=hbm-over-budget` on the def line
    # works; not serialized (machine-local paths would churn the report)
    def_site: Optional[Tuple[str, int]] = None

    @property
    def collective_bytes(self) -> int:
        return sum(e["bytes"] for e in self.collectives.values())

    @property
    def collective_count(self) -> int:
        return sum(e["count"] for e in self.collectives.values())

    def to_dict(self) -> Dict:
        return {
            "subject": self.subject,
            "peak_hbm_bytes": self.peak_hbm_bytes,
            "input_bytes": self.input_bytes,
            "donated_bytes": self.donated_bytes,
            "output_bytes": self.output_bytes,
            "flops": self.flops,
            "collective_bytes": self.collective_bytes,
            "collective_count": self.collective_count,
            "collectives": {
                k: dict(self.collectives[k]) for k in sorted(self.collectives)
            },
        }


def analyze_closed_jaxpr(
    closed_jaxpr,
    subject: str,
    axis_sizes: Optional[Dict[str, int]] = None,
    input_divisors: Optional[Sequence[int]] = None,
) -> ProgramResources:
    """Resources of one traced program (``jax.make_jaxpr`` output).

    When the program is a jitted callable, the outer jaxpr holds a single
    pjit eqn: the analysis uses its ``donated_invars`` and recurses its
    body; a bare (un-jitted) jaxpr is analyzed directly, undonated.
    """
    outer = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    axis_sizes = axis_sizes or {}
    target, donated, divisors = outer, None, input_divisors
    pjit_eqns = [e for e in outer.eqns if e.primitive.name == "pjit"]
    if len(outer.eqns) == 1 and pjit_eqns:
        eqn = pjit_eqns[0]
        target = eqn.params["jaxpr"].jaxpr
        donated = list(eqn.params.get("donated_invars", ()))
        # the outer jaxpr forwards its invars to the pjit 1:1; on an
        # arity mismatch (e.g. hoisted closure consts becoming extra
        # inner invars) the outer divisors do not align — fall back to
        # replicated rather than zip them against the wrong values
        if input_divisors and len(target.invars) == len(input_divisors):
            divisors = list(input_divisors)
        else:
            divisors = None

    divisors = list(divisors or [1] * len(target.invars))
    donated_list = list(donated or [False] * len(target.invars))
    donated_list += [False] * (len(target.invars) - len(donated_list))
    input_bytes = sum(
        _aval_bytes(v.aval, d) for v, d in zip(target.invars, divisors)
    )
    donated_bytes = sum(
        _aval_bytes(v.aval, d)
        for v, d, don in zip(target.invars, divisors, donated_list)
        if don
    )
    output_bytes = sum(
        0 if _is_literal(v) else _aval_bytes(v.aval) for v in target.outvars
    )
    return ProgramResources(
        subject=subject,
        peak_hbm_bytes=peak_live_bytes(target, divisors, donated_list),
        input_bytes=input_bytes,
        donated_bytes=donated_bytes,
        output_bytes=output_bytes,
        flops=count_flops(target),
        collectives=collective_costs(target, axis_sizes),
    )


def analyze_traced_program(traced) -> ProgramResources:
    """Resources of a harness :class:`TracedProgram`."""
    res = analyze_closed_jaxpr(
        traced.closed_jaxpr,
        traced.subject,
        axis_sizes=traced.mesh_shape or {},
        input_divisors=traced.input_divisors,
    )
    res.def_site = traced.def_site
    return res


def trainer_step_resources(trainer, kind: str = "ppo") -> ProgramResources:
    """Static resources of a LIVE trainer's jitted train step — tracing
    only (no compilation), so bench.py can print the budget numbers next
    to measured stats at the real workload shape."""
    import jax

    from trlx_tpu.analysis import harness
    from trlx_tpu.parallel.mesh import batch_sharding

    state_sds = harness._sds(trainer.state)
    mb = (
        harness._ilql_minibatch_sds(trainer)
        if kind == "ilql"
        else harness._ppo_minibatch_sds(trainer)
    )
    closed = jax.make_jaxpr(trainer._train_step_jit)(state_sds, mb)
    divisors = harness.flat_sharding_divisors(
        (state_sds, mb),
        (trainer.state_shardings, batch_sharding(trainer.mesh)),
    )
    return analyze_closed_jaxpr(
        closed,
        f"{kind}.train_step",
        axis_sizes={k: int(v) for k, v in trainer.mesh.shape.items()},
        input_divisors=divisors,
    )


# ------------------------------- budgets --------------------------------- #

def make_budgets(
    resources: Sequence[ProgramResources],
    mesh: Dict[str, int],
    tolerance_pct: float = DEFAULT_TOLERANCE_PCT,
) -> Dict:
    return {
        "schema_version": BUDGETS_SCHEMA_VERSION,
        "mesh": {k: int(v) for k, v in sorted(mesh.items())},
        "tolerance_pct": tolerance_pct,
        "programs": {
            r.subject: {
                "peak_hbm_bytes": r.peak_hbm_bytes,
                "collective_bytes": r.collective_bytes,
                "collective_count": r.collective_count,
                "flops": r.flops,
            }
            for r in sorted(resources, key=lambda r: r.subject)
        },
    }


def merge_budgets(
    budgets: Dict,
    existing: Dict,
    partial: bool,
    traced_kinds: Set[str],
) -> Dict:
    """Fold a freshly-generated ``budgets`` dict into the ``existing``
    lockfile: the file-level and per-entry ``tolerance_pct`` overrides a
    reviewer committed survive regeneration, a *partial* update (a
    ``--trainers`` subset trace) keeps the untraced kinds' entries
    instead of silently dropping them from the contract, and foreign
    top-level sections owned by OTHER engines (``compile_budgets``,
    engine 8; ``perf_budgets``, engine 10; anything future) pass through
    untouched — a resource relock must never wipe another engine's
    contract out of the shared lockfile."""
    own_keys = {"schema_version", "mesh", "tolerance_pct", "programs"}
    for key, val in existing.items():
        if key not in own_keys:
            budgets[key] = val
    if "tolerance_pct" in existing:
        budgets["tolerance_pct"] = existing["tolerance_pct"]
    old_programs = existing.get("programs", {})
    if partial:
        kept = {
            s: dict(e)
            for s, e in old_programs.items()
            if s.split(".")[0] not in traced_kinds
        }
        kept.update(budgets["programs"])
        budgets["programs"] = {s: kept[s] for s in sorted(kept)}
    for s, entry in budgets["programs"].items():
        old = old_programs.get(s)
        if old and "tolerance_pct" in old and "tolerance_pct" not in entry:
            entry["tolerance_pct"] = old["tolerance_pct"]
    return budgets


def load_budgets(path: str) -> Dict:
    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


def write_budgets(budgets: Dict, path: str) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(budgets, fh, indent=2, sort_keys=True)
        fh.write("\n")


def check_budgets(
    resources: Sequence[ProgramResources],
    budgets: Dict,
    mesh: Optional[Dict[str, int]] = None,
    budgets_path: Optional[str] = None,
) -> List[Finding]:
    """Gate current resources against the committed contract.

    Growth past a program's tolerance (entry-level ``tolerance_pct``
    override, else the file-level default) is a finding; so is a traced
    program with no committed entry, a stale entry for a kind that was
    traced, and a mesh mismatch (the numbers are only comparable on the
    mesh they were locked for).
    """
    hbm_rule = get_rule("hbm-over-budget")
    coll_rule = get_rule("collective-bytes-regression")
    findings: List[Finding] = []
    where = budgets_path or default_budgets_path()

    locked_mesh = budgets.get("mesh")
    if mesh is not None and locked_mesh is not None:
        current = {k: int(v) for k, v in sorted(mesh.items())}
        locked = {k: int(v) for k, v in sorted(locked_mesh.items())}
        if locked != current:
            return [
                Finding(
                    rule=hbm_rule.id,
                    message=(
                        f"budgets in {os.path.basename(where)} were locked "
                        f"for mesh {locked_mesh}, but the audit ran on "
                        f"{current} — the numbers are not comparable; rerun "
                        "with the locked mesh or --update-budgets"
                    ),
                    severity=hbm_rule.severity,
                    subject="budgets",
                    engine="resource",
                )
            ]

    default_tol = float(budgets.get("tolerance_pct", DEFAULT_TOLERANCE_PCT))
    programs = budgets.get("programs", {})
    for r in resources:
        # anchor at the traced callable's def line so inline
        # `# tpu-lint: disable=` directives apply to budget findings too
        file, line = r.def_site or (None, None)
        entry = programs.get(r.subject)
        if entry is None:
            findings.append(
                Finding(
                    rule=hbm_rule.id,
                    message=(
                        f"no committed budget for traced program "
                        f"`{r.subject}` (peak {r.peak_hbm_bytes} B, "
                        f"{r.collective_bytes} collective B) — run "
                        "--update-budgets and review the lockfile diff"
                    ),
                    severity=hbm_rule.severity,
                    file=file,
                    line=line,
                    subject=r.subject,
                    engine="resource",
                )
            )
            continue
        tol = 1.0 + float(entry.get("tolerance_pct", default_tol)) / 100.0
        locked_hbm = int(entry.get("peak_hbm_bytes", 0))
        if r.peak_hbm_bytes > locked_hbm * tol:
            growth = (
                100.0 * (r.peak_hbm_bytes - locked_hbm) / locked_hbm
                if locked_hbm
                else float("inf")
            )
            findings.append(
                Finding(
                    rule=hbm_rule.id,
                    message=(
                        f"static peak HBM of `{r.subject}` grew to "
                        f"{r.peak_hbm_bytes} B per device, "
                        f"{growth:+.1f}% over the committed "
                        f"{locked_hbm} B (tolerance "
                        f"{entry.get('tolerance_pct', default_tol)}%) — if "
                        "intended, regenerate with --update-budgets and "
                        "explain the growth in the lockfile diff"
                    ),
                    severity=hbm_rule.severity,
                    file=file,
                    line=line,
                    subject=r.subject,
                    engine="resource",
                )
            )
        locked_coll = int(entry.get("collective_bytes", 0))
        cur_coll = r.collective_bytes
        over = cur_coll > locked_coll * tol
        if locked_coll == 0:
            over = cur_coll > 0
        if over:
            findings.append(
                Finding(
                    rule=coll_rule.id,
                    message=(
                        f"modeled collective traffic of `{r.subject}` grew "
                        f"to {cur_coll} B/device over "
                        f"{r.collective_count} op(s), past the committed "
                        f"{locked_coll} B — an extra/larger collective is "
                        "a scaling regression on real slices; if intended, "
                        "regenerate with --update-budgets"
                    ),
                    severity=coll_rule.severity,
                    file=file,
                    line=line,
                    subject=r.subject,
                    engine="resource",
                )
            )

    traced_kinds = {r.subject.split(".")[0] for r in resources}
    current_subjects = {r.subject for r in resources}
    for stale in sorted(set(programs) - current_subjects):
        if stale.split(".")[0] in traced_kinds:
            findings.append(
                Finding(
                    rule=hbm_rule.id,
                    message=(
                        f"budget entry `{stale}` no longer matches any "
                        "traced program — prune it with --update-budgets"
                    ),
                    severity="warning",
                    subject=stale,
                    engine="resource",
                )
            )
    return findings


# ----------------------------- orchestration ----------------------------- #

def collect_resources(
    kinds: Optional[Sequence[str]] = None,
    mesh: Optional[Dict[str, int]] = None,
    programs=None,
) -> Tuple[List[ProgramResources], Dict[str, int]]:
    """Trace the trainer programs (or reuse ``programs``) and size them;
    returns (resources, resolved mesh axis sizes)."""
    from trlx_tpu.analysis import harness

    if programs is None:
        programs = list(harness.trace_all(kinds, mesh))
    resources = [analyze_traced_program(t) for t in programs]
    mesh_shape: Dict[str, int] = {}
    for t in programs:
        if t.mesh_shape:
            mesh_shape = dict(t.mesh_shape)
            break
    return resources, mesh_shape


def audit_resources(
    kinds: Optional[Sequence[str]] = None,
    mesh: Optional[Dict[str, int]] = None,
    budgets_path: Optional[str] = None,
    update: bool = False,
    programs=None,
) -> Tuple[Report, List[ProgramResources]]:
    """The ``--resources`` entry point: trace, size, and either regenerate
    the lockfile (``update=True``) or gate against it."""
    from trlx_tpu.analysis.findings import filter_suppressed

    path = budgets_path or default_budgets_path()
    resources, mesh_shape = collect_resources(kinds, mesh, programs)
    report = Report()
    report.covered += [f"resource:{r.subject}" for r in resources]
    report.resources = [r.to_dict() for r in resources]
    if update:
        budgets = make_budgets(resources, mesh_shape)
        try:
            existing = load_budgets(path)
        except (OSError, ValueError):
            existing = None
        if existing is not None:
            partial = kinds is not None
            locked_mesh = existing.get("mesh")
            # lockfile-sourced ints, not device values — normalized
            # OUTSIDE the branch so the host-branch lint can see this
            # condition never reads device state
            locked_norm = (
                {k: int(v) for k, v in sorted(locked_mesh.items())}
                if locked_mesh is not None
                else None
            )
            if (
                partial
                and locked_norm is not None
                and locked_norm != budgets["mesh"]
            ):
                # a subset trace on a different mesh cannot merge: the
                # kept entries would be locked for another topology
                rule = get_rule("hbm-over-budget")
                report.extend([
                    Finding(
                        rule=rule.id,
                        message=(
                            f"refusing --update-budgets: the lockfile is "
                            f"for mesh {locked_mesh} but this --trainers "
                            f"subset traced on {budgets['mesh']} — a "
                            "partial update would mix topologies; rerun "
                            "without --trainers (full relock) or on the "
                            "locked mesh"
                        ),
                        severity=rule.severity,
                        subject="budgets",
                        engine="resource",
                    )
                ])
                return report, resources
            budgets = merge_budgets(
                budgets,
                existing,
                partial,
                {r.subject.split(".")[0] for r in resources},
            )
        write_budgets(budgets, path)
        return report, resources
    try:
        budgets = load_budgets(path)
    except (OSError, ValueError) as e:
        rule = get_rule("hbm-over-budget")
        report.extend([
            Finding(
                rule=rule.id,
                message=(
                    f"cannot load budget contract {path}: {e} — generate "
                    "it with --update-budgets and commit the file"
                ),
                severity=rule.severity,
                subject="budgets",
                engine="resource",
            )
        ])
        return report, resources
    kept, suppressed = filter_suppressed(
        check_budgets(resources, budgets, mesh_shape, path)
    )
    report.extend(kept)
    report.suppressed += suppressed
    return report, resources


def format_resources_text(resources: Sequence[ProgramResources]) -> str:
    lines = [
        f"{'program':28} {'peak HBM/dev':>14} {'collective B':>13} "
        f"{'colls':>6} {'GFLOP':>10}"
    ]
    for r in sorted(resources, key=lambda r: r.subject):
        lines.append(
            f"{r.subject:28} {r.peak_hbm_bytes:>14,} "
            f"{r.collective_bytes:>13,} {r.collective_count:>6} "
            f"{r.flops / 1e9:>10.3f}"
        )
    return "\n".join(lines)
