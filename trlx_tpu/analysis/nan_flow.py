"""NaN-source dataflow: guard-dominance analysis over traced jaxprs.

Engine 3 of ``trlx_tpu.analysis``. The fsdp/tp PPO divergence (ROADMAP
"Open items") is a *numeric* failure: some equation produced the first
NaN/Inf, and some unguarded op upstream made it possible. This engine
walks every traced program's jaxpr in dataflow order, tracking per-value
facts a guard establishes —

- ``lo``/``hi``: statically known bounds (``clamp``, ``max(x, c)``,
  interval arithmetic through ``add``/``sub``/``mul``/``exp``/...);
- ``pos``/``nonzero``: strict positivity (``x**2 + eps``, softmax
  denominators whose max element is provably included);
- ``neg_inf_mask``: the value may hold ``-inf``/huge-negative fill
  written by a ``where``-style mask (so ``exp`` of it can be exactly 0);

— and flags ops that can mint a NaN/Inf when their operands lack the
matching guard:

- ``nan-unguarded``: ``div`` by a possibly-zero denominator, ``log``/
  ``rsqrt`` of a possibly-nonpositive operand, ``sqrt``/non-integer
  ``pow`` of a possibly-negative operand, ``exp`` of an operand with no
  static upper bound (overflow to inf — the classic unclipped PPO
  ratio).
- ``where-grad-trap``: the same unguarded op, but its output feeds a
  ``select_n`` — the ``where(mask, f(x), 0)`` pattern whose *backward*
  pass evaluates ``f'(x)`` on the masked lane and multiplies the
  inf/NaN by a zero cotangent, producing NaN gradients even though the
  forward value is masked (guard the *input*, not the output).
- ``inf-mask-softmax``: a softmax-style denominator (sum of ``exp``)
  built from a ``-inf``-masked input — a fully-masked row divides 0/0.

Attribution mirrors the precision-leak rule: a finding is reported only
when the op's *innermost* traced frame is repo code (jax/flax/optax own
their internal numerics — ``jax.nn.softmax`` guards itself). Intentional
sites are curated in :data:`NAN_ALLOWLIST`, not inline-suppressed, so
kernel code stays clean and each exemption carries its justification.

Two softmax structural patterns are recognized (interval facts alone
cannot prove them):

- ``x - max(x)`` (same operand, possibly through ``stop_gradient``) is
  bounded above by 0, so its ``exp`` cannot overflow;
- ``sum(exp(x - max(x)))`` includes the max element, so it is >= 1 —
  a valid ``log``/``div`` guard — *unless* the input was -inf-masked.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, replace
from typing import Any, Dict, List, Optional, Sequence, Tuple

from trlx_tpu.analysis.findings import Finding
from trlx_tpu.analysis.registry import get_rule

# (file suffix, function name) pairs allowed to run the flagged op
# unguarded; None matches the whole file. Every entry documents why the
# site cannot actually mint a NaN (a dynamic invariant the dataflow
# cannot see). Extend here rather than suppressing inline in kernels.
NAN_ALLOWLIST: Sequence[Tuple[str, Optional[str]]] = (
    # online-softmax kernels: exp(s - m) where m is the *running* row max
    # carried through the scan — dynamically s - m <= 0, but the carry
    # enters the body jaxpr with no static facts
    ("ops/flash_attention.py", None),
    ("ops/ring_attention.py", None),
    # decode-time top-p/min-length filtering fills logits with -inf by
    # design; the sampler always leaves at least one finite logit (the
    # top-1 survives any top-p threshold, and eos suppression only masks
    # one column)
    ("ops/sampling.py", None),
    # causal self-attention softmax over -1e9/-inf-masked logits: every
    # live query row sees at least its own position (the causal band
    # includes the diagonal), so the denominator keeps one exp(0) term;
    # fully-padded rows produce garbage that response_forward's
    # position slicing and the loss masks never read
    ("ops/attention.py", "dot_product_attention"),
)

_BIG_NEG = -1e8  # mask fills at or below this count as "-inf-like"


@dataclass(frozen=True)
class Fact:
    """Statically-known properties of one jaxpr value (NaN-free unless
    a flagged op mints one — facts describe the *intended* range)."""

    lo: Optional[float] = None  # x >= lo elementwise
    hi: Optional[float] = None  # x <= hi elementwise
    pos: bool = False  # x > 0 strictly
    nonzero: bool = False
    neg_inf_mask: bool = False  # may hold a -inf-like mask fill

    @property
    def nonneg(self) -> bool:
        return self.pos or (self.lo is not None and self.lo >= 0)

    def meet(self, other: "Fact") -> "Fact":
        """Facts that hold for a value that may be either input."""
        lo = None
        if self.lo is not None and other.lo is not None:
            lo = min(self.lo, other.lo)
        hi = None
        if self.hi is not None and other.hi is not None:
            hi = max(self.hi, other.hi)
        return Fact(
            lo=lo,
            hi=hi,
            pos=self.pos and other.pos,
            nonzero=self.nonzero and other.nonzero,
            neg_inf_mask=self.neg_inf_mask or other.neg_inf_mask,
        )


TOP = Fact()


def _const_fact(value) -> Fact:
    import numpy as np

    try:
        arr = np.asarray(value)
    except Exception:
        return TOP
    if arr.dtype.kind not in "fiub" and arr.dtype.name not in (
        "bfloat16", "float16"  # ml_dtypes report numpy kind 'V'
    ):
        return TOP
    if arr.size == 0 or arr.size > 1 << 22:
        return TOP
    arr64 = arr.astype(np.float64)
    if np.isnan(arr64).any():
        return Fact(neg_inf_mask=False)
    lo = float(arr64.min())
    hi = float(arr64.max())
    return Fact(
        lo=lo if math.isfinite(lo) else None,
        hi=hi if math.isfinite(hi) else None,
        pos=lo > 0,
        nonzero=bool((arr64 != 0).all()),
        neg_inf_mask=lo <= _BIG_NEG,
    )


def _add(a: Fact, b: Fact) -> Fact:
    lo = a.lo + b.lo if a.lo is not None and b.lo is not None else None
    hi = a.hi + b.hi if a.hi is not None and b.hi is not None else None
    return Fact(
        lo=lo,
        hi=hi,
        # pos + nonneg stays strictly positive (the classic `x**2 + eps`)
        pos=(a.pos and b.nonneg) or (b.pos and a.nonneg) or bool(lo and lo > 0),
        nonzero=bool(lo is not None and lo > 0) or bool(hi is not None and hi < 0),
        neg_inf_mask=a.neg_inf_mask or b.neg_inf_mask,
    )


def _sub(a: Fact, b: Fact) -> Fact:
    return _add(a, Fact(
        lo=-b.hi if b.hi is not None else None,
        hi=-b.lo if b.lo is not None else None,
        pos=False,
        neg_inf_mask=b.neg_inf_mask,
    ))


def _mul(a: Fact, b: Fact) -> Fact:
    lo = hi = None
    if None not in (a.lo, a.hi, b.lo, b.hi):
        prods = [a.lo * b.lo, a.lo * b.hi, a.hi * b.lo, a.hi * b.hi]
        lo, hi = min(prods), max(prods)
    return Fact(
        lo=0.0 if (a.nonneg and b.nonneg and lo is None) else lo,
        hi=hi,
        pos=a.pos and b.pos,
        nonzero=a.nonzero and b.nonzero,
        neg_inf_mask=a.neg_inf_mask or b.neg_inf_mask,
    )


_IDENTITY_PRIMS = {
    "broadcast_in_dim", "reshape", "transpose", "squeeze", "expand_dims",
    "slice", "dynamic_slice", "rev", "copy", "stop_gradient",
    "reduce_precision", "sharding_constraint", "device_put", "gather",
    "reduce_max", "reduce_min", "cumsum", "sort", "pad",
    "optimization_barrier", "convert_element_type", "real", "tile",
}


def _is_int_const(fact: Fact) -> bool:
    return (
        fact.lo is not None
        and fact.hi is not None
        and fact.lo == fact.hi
        and float(fact.lo).is_integer()
    )


class _Analyzer:
    """One program's dataflow walk; collects findings."""

    def __init__(self, subject: str, repo_root: str,
                 allowlist: Sequence[Tuple[str, Optional[str]]]):
        self.subject = subject
        self.repo_root = repo_root
        self.allowlist = allowlist
        self.findings: List[Finding] = []

    # ----------------------------- helpers ------------------------------ #

    def _read(self, env: Dict, var) -> Fact:
        from jax._src.core import Literal

        if isinstance(var, Literal):
            return _const_fact(var.val)
        return env.get(var, TOP)

    def _source_of(self, producers: Dict, var):
        """The eqn that produced ``var`` at this jaxpr level, or None."""
        return producers.get(id(var))

    def _is_max_shift(self, eqn, env: Dict, producers: Dict) -> bool:
        """``sub(x, reduce_max(x))`` (through stop_gradient/broadcast) —
        bounded above by 0."""
        if eqn.primitive.name != "sub":
            return False
        x, m = eqn.invars
        m_eqn = self._source_of(producers, m)
        # peel broadcast/reshape/stop_gradient wrappers around the max
        seen = 0
        while m_eqn is not None and seen < 6:
            name = m_eqn.primitive.name
            if name == "reduce_max":
                root = m_eqn.invars[0]
                return root is x or self._same_origin(root, x, producers)
            if name in _IDENTITY_PRIMS or name == "custom_jvp_call":
                m_eqn = self._source_of(producers, m_eqn.invars[0])
                seen += 1
                continue
            if name == "max":
                # jax.nn.softmax emits max(-inf, reduce_max(x)) — a no-op
                # floor; peel through the non-literal operand
                from jax._src.core import Literal

                operands = [
                    v for v in m_eqn.invars if not isinstance(v, Literal)
                ]
                if len(operands) == 1:
                    m_eqn = self._source_of(producers, operands[0])
                    seen += 1
                    continue
            return False
        return False

    def _same_origin(self, a, b, producers, depth: int = 4) -> bool:
        """Whether two vars trace to one producer through identity prims."""
        def root(v):
            for _ in range(depth):
                e = self._source_of(producers, v)
                if e is None or e.primitive.name not in _IDENTITY_PRIMS:
                    return v
                v = e.invars[0]
            return v

        return root(a) is root(b)

    def _library_owned(self, eqn) -> bool:
        """Whether the innermost non-jax raw frame is third-party code
        (optax/flax register traceback exclusions, so their internals
        *attribute* to the repo call line — but they still own the
        numerics of ops they wrote, e.g. adamw's eps-guarded div)."""
        source_info = getattr(eqn, "source_info", None)
        tb = getattr(source_info, "traceback", None)
        if tb is None:
            return False
        try:
            for frame in tb.frames:
                fn = frame.file_name
                if "/jax/" in fn or "/jaxlib/" in fn:
                    continue  # jax machinery is transparent
                return self.repo_root not in fn
        except Exception:
            return False
        return False

    def _report(self, eqn, rule_id: str, message: str) -> None:
        from trlx_tpu.analysis.jaxpr_audit import _repo_frame

        frame = _repo_frame(eqn, self.repo_root, innermost_only=True)
        if frame is None:
            return  # library-internal numerics guard themselves
        if self._library_owned(eqn):
            return  # optax/flax wrote the op; they own its guards
        rel = frame.file_name
        if self.repo_root in rel:
            rel = rel.split(self.repo_root, 1)[1].lstrip("/")
        for file_suffix, func in self.allowlist:
            if file_suffix and not rel.endswith(file_suffix):
                continue
            if func is not None and frame.function_name != func:
                continue
            return  # curated: the site's invariant is documented
        rule = get_rule(rule_id)
        self.findings.append(
            Finding(
                rule=rule.id,
                message=message,
                severity=rule.severity,
                file=frame.file_name,
                line=frame.start_line,
                subject=self.subject,
                engine="nanflow",
            )
        )

    # ------------------------------ walk -------------------------------- #

    def walk(self, jaxpr, consts: Sequence[Any],
             in_facts: Sequence[Fact]) -> List[Fact]:
        env: Dict = {}
        producers: Dict[int, Any] = {}
        consumers: Dict[int, List] = {}
        for eqn in jaxpr.eqns:
            for v in eqn.invars:
                consumers.setdefault(id(v), []).append(eqn)

        for var, val in zip(jaxpr.constvars, consts):
            env[var] = _const_fact(val)
        for var, fact in zip(jaxpr.invars, in_facts):
            env[var] = fact

        for eqn in jaxpr.eqns:
            facts = [self._read(env, v) for v in eqn.invars]
            outs = self._transfer(eqn, facts, env, producers)
            self._check(eqn, facts, env, producers, consumers)
            for v, f in zip(eqn.outvars, outs):
                env[v] = f
                producers[id(v)] = eqn
        return [self._read(env, v) for v in jaxpr.outvars]

    def _sub_jaxpr_facts(self, eqn, facts: List[Fact]) -> Optional[List[Fact]]:
        """Recurse into call-like sub-jaxprs with mapped input facts;
        returns the sub-program's output facts where they map 1:1 onto
        the eqn's outputs (pjit-wrapped helpers like ``jnp.clip`` /
        ``jnp.where`` must not erase the guard they establish)."""
        name = eqn.primitive.name
        params = eqn.params
        if name in ("pjit", "closed_call", "core_call", "remat", "remat2",
                    "checkpoint", "custom_vjp_call_jaxpr"):
            closed = params.get("jaxpr") or params.get("fun_jaxpr")
            if closed is None:
                return None
            inner = getattr(closed, "jaxpr", closed)
            consts = getattr(closed, "consts", ())
            return self.walk(inner, consts, facts)
        if name in ("custom_jvp_call", "custom_vjp_call"):
            closed = params.get("call_jaxpr") or params.get("fun_jaxpr")
            if closed is not None:
                inner = getattr(closed, "jaxpr", closed)
                return self.walk(inner, getattr(closed, "consts", ()), facts)
            return None
        if name == "scan":
            closed = params["jaxpr"]
            inner = getattr(closed, "jaxpr", closed)
            n_consts = params.get("num_consts", 0)
            n_carry = params.get("num_carry", 0)
            # consts keep their facts; carry iterates to an unknown fixed
            # point -> TOP; xs facts hold per-slice (bounds are elementwise)
            body_facts = (
                facts[:n_consts]
                + [TOP] * n_carry
                + facts[n_consts + n_carry:]
            )
            self.walk(inner, getattr(closed, "consts", ()), body_facts)
            return None  # outs went through the unknown carry
        if name == "while":
            for key in ("cond_jaxpr", "body_jaxpr"):
                closed = params[key]
                inner = getattr(closed, "jaxpr", closed)
                self.walk(inner, getattr(closed, "consts", ()),
                          [TOP] * len(inner.invars))
            return None
        if name == "cond":
            branch_outs = []
            for closed in params.get("branches", ()):
                inner = getattr(closed, "jaxpr", closed)
                branch_outs.append(
                    self.walk(inner, getattr(closed, "consts", ()), facts[1:])
                )
            if branch_outs and all(
                len(o) == len(branch_outs[0]) for o in branch_outs
            ):
                met = branch_outs[0]
                for outs in branch_outs[1:]:
                    met = [a.meet(b) for a, b in zip(met, outs)]
                return met
            return None
        if name == "shard_map":
            inner = params.get("jaxpr")
            if inner is not None:
                inner = getattr(inner, "jaxpr", inner)
                return self.walk(inner, (), facts)
        return None

    def _transfer(self, eqn, facts: List[Fact], env, producers) -> List[Fact]:
        name = eqn.primitive.name
        n_out = len(eqn.outvars)

        if name in ("pjit", "closed_call", "core_call", "remat", "remat2",
                    "checkpoint", "custom_jvp_call", "custom_vjp_call",
                    "custom_vjp_call_jaxpr", "scan", "while", "cond",
                    "shard_map"):
            sub_out = self._sub_jaxpr_facts(eqn, facts)
            if sub_out is not None and len(sub_out) == n_out:
                return sub_out
            return [TOP] * n_out

        if name in _IDENTITY_PRIMS:
            if name == "convert_element_type":
                # int casts of bool masks etc. keep facts
                return [facts[0]]
            return [facts[0] if facts else TOP] * n_out
        if name == "concatenate":
            out = facts[0]
            for f in facts[1:]:
                out = out.meet(f)
            return [out]
        if name == "add":
            return [_add(facts[0], facts[1])]
        if name == "sub":
            if self._is_max_shift(eqn, env, producers):
                out = replace(_sub(facts[0], facts[1]), hi=0.0)
                return [out]
            return [_sub(facts[0], facts[1])]
        if name == "mul":
            a, b = eqn.invars
            if a is b:  # x * x
                sq = _mul(facts[0], facts[1])
                return [replace(sq, lo=max(0.0, sq.lo or 0.0))]
            return [_mul(facts[0], facts[1])]
        if name == "neg":
            f = facts[0]
            return [Fact(
                lo=-f.hi if f.hi is not None else None,
                hi=-f.lo if f.lo is not None else None,
                nonzero=f.nonzero,
            )]
        if name == "abs":
            f = facts[0]
            hi = None
            if f.lo is not None and f.hi is not None:
                hi = max(abs(f.lo), abs(f.hi))
            return [Fact(lo=0.0, hi=hi, pos=f.nonzero or f.pos,
                         nonzero=f.nonzero)]
        if name in ("max", "pmax"):
            a, b = facts[0], facts[1]
            los = [x for x in (a.lo, b.lo) if x is not None]
            hi = None
            if a.hi is not None and b.hi is not None:
                hi = max(a.hi, b.hi)
            lo = max(los) if los else None
            return [Fact(lo=lo, hi=hi, pos=a.pos or b.pos or bool(lo and lo > 0),
                         nonzero=bool(lo is not None and lo > 0))]
        if name in ("min", "pmin"):
            a, b = facts[0], facts[1]
            his = [x for x in (a.hi, b.hi) if x is not None]
            lo = None
            if a.lo is not None and b.lo is not None:
                lo = min(a.lo, b.lo)
            return [Fact(lo=lo, hi=min(his) if his else None,
                         pos=a.pos and b.pos,
                         neg_inf_mask=a.neg_inf_mask or b.neg_inf_mask)]
        if name == "clamp":  # clamp(lo, x, hi)
            lo_f, x_f, hi_f = facts
            return [Fact(lo=lo_f.lo, hi=hi_f.hi,
                         pos=lo_f.pos, nonzero=lo_f.pos)]
        if name == "exp":
            f = facts[0]
            hi = math.exp(min(f.hi, 700.0)) if f.hi is not None else None
            # exp(x) > 0 unless x can be a -inf-like mask fill (exp -> 0)
            return [Fact(lo=0.0, hi=hi, pos=not f.neg_inf_mask,
                         nonzero=not f.neg_inf_mask,
                         neg_inf_mask=f.neg_inf_mask)]
        if name == "logistic":
            return [Fact(lo=0.0, hi=1.0, pos=not facts[0].neg_inf_mask)]
        if name == "tanh":
            return [Fact(lo=-1.0, hi=1.0)]
        if name == "erf":
            return [Fact(lo=-1.0, hi=1.0)]
        if name == "log":
            f = facts[0]
            lo = math.log(f.lo) if f.lo is not None and f.lo > 0 else None
            return [Fact(
                lo=lo,
                hi=math.log(f.hi) if f.hi and f.hi > 0 else None,
                pos=bool(lo is not None and lo > 0),
                nonzero=bool(lo is not None and lo > 0)
                or bool(f.hi is not None and f.hi < 1),
            )]
        if name == "sqrt":
            f = facts[0]
            return [Fact(lo=0.0, pos=f.pos, nonzero=f.pos,
                         hi=math.sqrt(f.hi) if f.hi and f.hi >= 0 else None)]
        if name == "rsqrt":
            return [Fact(lo=0.0, pos=facts[0].pos, nonzero=facts[0].pos)]
        if name == "integer_pow":
            y = eqn.params.get("y", 1)
            f = facts[0]
            if y < 0:
                # x**-k is a division: inf at 0, and magnitude bounds
                # invert — no sound facts without a nonzero guarantee
                return [Fact(lo=0.0 if y % 2 == 0 else None,
                             pos=f.pos, nonzero=f.nonzero)]
            if y % 2 == 0:
                hi = None
                if f.lo is not None and f.hi is not None:
                    hi = max(abs(f.lo), abs(f.hi)) ** y
                return [Fact(lo=0.0, hi=hi, pos=f.nonzero, nonzero=f.nonzero)]
            return [TOP]
        if name == "div":
            a, b = facts[0], facts[1]
            out_pos = a.pos and b.pos
            hi = None
            if a.hi is not None and b.lo is not None and b.lo > 0:
                if a.hi >= 0:
                    # positive numerators are largest over the smallest
                    # denominator
                    hi = a.hi / b.lo
                elif b.hi is not None:
                    # negative numerators are largest (closest to 0) over
                    # the LARGEST denominator
                    hi = a.hi / b.hi
                else:
                    hi = 0.0  # a.hi < 0, denominator unbounded above
            lo = 0.0 if (a.nonneg and b.pos) else None
            return [Fact(lo=lo, hi=hi, pos=out_pos, nonzero=a.nonzero and b.nonzero)]
        if name == "reduce_sum":
            f = facts[0]
            # sum(exp(x - max(x))) includes the max element -> >= 1;
            # matched here so softmax denominators count as guards
            src = self._source_of(producers, eqn.invars[0])
            if (
                src is not None
                and src.primitive.name == "exp"
                and f.pos
                and self._source_of(producers, src.invars[0]) is not None
                and self._is_max_shift(
                    self._source_of(producers, src.invars[0]), env, producers
                )
            ):
                return [Fact(lo=1.0, pos=True, nonzero=True)]
            return [Fact(
                lo=0.0 if f.nonneg else None,
                pos=f.pos,
                neg_inf_mask=f.neg_inf_mask,
            )]
        if name in ("reduce_prod",):
            f = facts[0]
            return [Fact(pos=f.pos, nonzero=f.nonzero)]
        if name == "select_n":
            # select_n(pred, case0, case1, ...): value is one of the cases
            out = facts[1]
            for f in facts[2:]:
                out = out.meet(f)
            return [out]
        if name == "pow":
            base, expo = facts[0], facts[1]
            if base.pos:
                hi = None
                if (
                    base.hi is not None
                    and 0 < base.hi <= 1
                    and expo.lo is not None
                    and expo.lo >= 0
                ):
                    # c^x for c in (0,1], x >= x_lo: bounded by c^x_lo
                    # (adamw's bias correction 1 - b^count needs this)
                    hi = base.hi ** expo.lo
                return [Fact(lo=0.0, hi=hi, pos=True, nonzero=True)]
            return [TOP]
        if name in ("dot_general",):
            return [TOP]
        if name in ("sign",):
            return [Fact(lo=-1.0, hi=1.0)]
        if name in ("cos", "sin"):
            return [Fact(lo=-1.0, hi=1.0)]
        if name in ("iota",):
            return [Fact(lo=0.0)]
        if name in ("argmax", "argmin"):
            return [Fact(lo=0.0)]
        if name in ("and", "or", "not", "xor", "eq", "ne", "lt", "le",
                    "gt", "ge", "is_finite"):
            return [Fact(lo=0.0, hi=1.0)]
        if name == "one_hot":
            return [Fact(lo=0.0, hi=1.0)]
        if name in ("psum", "psum2", "all_gather", "reduce_scatter",
                    "all_to_all", "ppermute", "pbroadcast"):
            f = facts[0] if facts else TOP
            return [Fact(lo=0.0 if f.nonneg else None, pos=f.pos,
                         neg_inf_mask=f.neg_inf_mask)] * n_out
        return [TOP] * n_out

    # ----------------------------- checks ------------------------------- #

    def _emit(self, eqn, consumers, kind: str, detail: str) -> None:
        """Pick the rule id: the where-grad-trap variant when the risky
        op's output feeds a select_n at this jaxpr level."""
        def _is_select(c) -> bool:
            # jnp.where arrives as a pjit named `_where` wrapping select_n
            return c.primitive.name == "select_n" or (
                c.primitive.name == "pjit"
                and c.params.get("name") == "_where"
            )

        feeds_select = any(
            _is_select(c)
            for v in eqn.outvars
            for c in consumers.get(id(v), ())
        )
        if feeds_select:
            self._report(
                eqn, "where-grad-trap",
                f"{detail} — and its output feeds a `where`/`select`: the "
                "backward pass still evaluates the non-total op on masked "
                "lanes and multiplies inf by a zero cotangent (NaN grads); "
                "guard the op's *input* instead",
            )
        else:
            self._report(eqn, "nan-unguarded", detail)

    def _check(self, eqn, facts: List[Fact], env, producers, consumers) -> None:
        import numpy as np

        name = eqn.primitive.name
        if name == "div":
            aval = getattr(eqn.outvars[0], "aval", None)
            dtype = getattr(aval, "dtype", None)
            if dtype is None or np.dtype(dtype).kind != "f":
                return
            den = facts[1]
            if den.pos or den.nonzero:
                return
            if den.neg_inf_mask or (
                den.nonneg and self._den_is_masked_softmax(eqn, producers)
            ):
                self._report(
                    eqn, "inf-mask-softmax",
                    "softmax denominator built from a -inf-masked input: a "
                    "fully-masked row sums exp() to 0 and divides 0/0; "
                    "re-select the output or keep one unmasked column",
                )
                return
            self._emit(
                eqn, consumers,
                "div",
                "`div` by a denominator not proven nonzero — guard with "
                "`+eps`, `maximum(x, eps)`, or a `where` on the input",
            )
        elif name in ("log", "log1p"):
            f = facts[0]
            floor = -1.0 if name == "log1p" else 0.0
            if f.lo is not None and f.lo > floor:
                return
            if f.pos and name == "log":
                return
            self._emit(
                eqn, consumers, name,
                f"`{name}` of an operand not proven > {floor:g} — NaN on "
                "the masked/zero lane; guard the input with `+eps` or "
                "`maximum`",
            )
        elif name == "rsqrt":
            f = facts[0]
            if f.pos:
                return
            self._emit(
                eqn, consumers, name,
                "`rsqrt` of an operand not proven positive — inf at 0, NaN "
                "below; guard with `+eps` (eps-free rsqrt is the classic "
                "norm/whitening divergence)",
            )
        elif name == "sqrt":
            f = facts[0]
            if f.nonneg:
                return
            self._emit(
                eqn, consumers, name,
                "`sqrt` of an operand not proven >= 0 — NaN on negative "
                "inputs; guard with `maximum(x, 0)` or square the operand",
            )
        elif name in ("exp", "exp2"):
            f = facts[0]
            aval = getattr(eqn.outvars[0], "aval", None)
            dtype = getattr(aval, "dtype", None)
            if dtype is None or np.dtype(dtype).kind != "f":
                return
            # overflow guard: any finite static upper bound below the f32
            # overflow threshold (~88.7; bf16 shares the f32 exponent)
            if f.hi is not None and f.hi <= 80.0:
                return
            self._emit(
                eqn, consumers, name,
                f"`{name}` of an operand with no static upper bound — "
                "overflows to inf (the unclipped-ratio PPO trap); clamp "
                "the exponent (e.g. `clip(log_ratio, -c, c)`) or subtract "
                "a rowwise max first",
            )
        elif name == "pow":
            base, expo = facts[0], facts[1]
            if base.nonneg or _is_int_const(expo):
                return
            self._emit(
                eqn, consumers, name,
                "`pow` with a possibly-negative base and non-integer "
                "exponent — NaN; guard the base or use an integer power",
            )
        elif name == "integer_pow":
            y = eqn.params.get("y", 1)
            if y >= 0:
                return
            f = facts[0]
            aval = getattr(eqn.outvars[0], "aval", None)
            dtype = getattr(aval, "dtype", None)
            if dtype is None or np.dtype(dtype).kind != "f":
                return
            if f.nonzero or f.pos:
                return
            self._emit(
                eqn, consumers, name,
                f"`x**{y}` (a reciprocal power) of an operand not proven "
                "nonzero — inf at 0; guard the base with `+eps` or "
                "`maximum`",
            )

    def _den_is_masked_softmax(self, eqn, producers) -> bool:
        """div denominator = reduce_sum(exp(masked)) where the exp input
        carries a -inf-like fill."""
        src = self._source_of(producers, eqn.invars[1])
        hops = 0
        while src is not None and hops < 4:
            n = src.primitive.name
            if n == "reduce_sum":
                inner = self._source_of(producers, src.invars[0])
                return bool(inner is not None and inner.primitive.name == "exp")
            if n in _IDENTITY_PRIMS or n == "add":
                src = self._source_of(producers, src.invars[0])
                hops += 1
                continue
            return False
        return False


def analyze_program(
    closed_jaxpr,
    subject: str,
    repo_root: Optional[str] = None,
    allowlist: Sequence[Tuple[str, Optional[str]]] = NAN_ALLOWLIST,
    in_facts: Optional[Sequence[Fact]] = None,
) -> List[Finding]:
    """Run the NaN-source dataflow on one traced program."""
    from trlx_tpu.analysis.jaxpr_audit import default_repo_root

    repo_root = repo_root or default_repo_root()
    inner = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    consts = getattr(closed_jaxpr, "consts", ())
    analyzer = _Analyzer(subject, repo_root, allowlist)
    facts = list(in_facts or [])
    facts = facts[:len(inner.invars)]
    facts += [TOP] * (len(inner.invars) - len(facts))
    analyzer.walk(inner, consts, facts)
    # one report per (rule, site): scan bodies and vmapped lanes repeat
    # the same source eqn in several trace contexts
    seen = set()
    unique: List[Finding] = []
    for f in analyzer.findings:
        key = (f.rule, f.file, f.line)
        if key not in seen:
            seen.add(key)
            unique.append(f)
    return unique


def input_facts(paths: Sequence[str]) -> List[Fact]:
    """Data-contract facts per program input, derived from its keypath:
    masks and done flags are 0/1, behavior logprobs are <= 0, token ids /
    step counters / Adam second moments are nonnegative. These are the
    invariants the trainers' input pipelines maintain — seeding them at
    the program boundary is what lets guards like ``sum(mask) >= ...``
    and ``sqrt(nu)`` prove out."""
    facts: List[Fact] = []
    for path in paths:
        p = path.lower()
        if "mask" in p or "dones" in p:
            facts.append(Fact(lo=0.0, hi=1.0))
        elif "logprob" in p:
            facts.append(Fact(hi=0.0))
        elif (
            "tokens" in p or "input_ids" in p or "_ixs" in p
            or p.endswith(".step") or ".count" in p or p.endswith("count")
        ):
            facts.append(Fact(lo=0.0))
        elif ".nu" in p:  # Adam second moment: EMA of squares
            facts.append(Fact(lo=0.0))
        else:
            facts.append(TOP)
    return facts


def analyze_trainers(kinds=None, programs=None):
    """NaN-flow over the harness's traced trainer programs; returns a
    :class:`~trlx_tpu.analysis.findings.Report`."""
    from trlx_tpu.analysis import harness
    from trlx_tpu.analysis.findings import Report, filter_suppressed

    report = Report()
    findings: List[Finding] = []
    for traced in programs if programs is not None else harness.trace_all(kinds):
        report.covered.append(f"nanflow:{traced.subject}")
        facts = (
            input_facts(traced.input_paths)
            if getattr(traced, "input_paths", None)
            else None
        )
        findings += analyze_program(
            traced.closed_jaxpr, traced.subject, in_facts=facts
        )
    kept, suppressed = filter_suppressed(findings)
    report.extend(kept)
    report.suppressed += suppressed
    return report
