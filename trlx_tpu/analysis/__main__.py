"""CLI: ``python -m trlx_tpu.analysis [--strict] [--json] ...``.

Exit status: 0 when clean; 1 when findings remain (``--strict`` counts
warnings too, plain mode only errors). Designed for CI on CPU-only
runners — the jaxpr audit forces an 8-virtual-device CPU platform before
JAX initializes so collective/sharding structure is real.

Besides the rule engines there are report modes: ``--sanitize
<trainer>`` (eqn-level non-finite replay), ``--resources`` (static
peak-HBM / collective / FLOP budgets per traced program), ``--compile-
audit`` (runtime compile counting), ``--perf-audit`` (measured
per-span wall-clock over the instrumented phase loop), and
``--lockstep`` (N simulated controller processes diffing per-host
dispatch logs), ``--hlo-audit`` (AOT-compiled post-SPMD HLO vs
jaxpr intent), and ``--races`` (host-concurrency lockset lint +
deterministic-schedule interleaving engine) — the budgeted modes gated
against the committed
``analysis/budgets.json`` with ``--update-budgets`` relocking each
engine's own section. JSON output
carries a top-level ``schema_version`` and deterministic ordering so CI
artifacts diff cleanly.
"""

from __future__ import annotations

import argparse
import os
import sys


def _force_cpu_platform() -> None:
    """Make the audit runnable on any host, before jax first initializes."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags = (flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ["XLA_FLAGS"] = flags


def _emit_smoke(summary, format_smoke_text, as_json: bool) -> int:
    """Shared tail of every ``--*-smoke`` mode: print the summary (JSON
    or text) and map ``passed`` to the exit code — one place to fix the
    contract instead of one copy per smoke."""
    import json as _json

    if as_json:
        print(_json.dumps(summary, default=str))
    else:
        print(format_smoke_text(summary))
    return 0 if summary["passed"] else 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m trlx_tpu.analysis",
        description="jaxpr + AST static analysis for the TPU port",
    )
    parser.add_argument(
        "--engine",
        choices=(
            "all", "jaxpr", "ast", "nanflow", "collective", "donation",
            "compile", "prng",
        ),
        default="all",
        help="which engine(s) to run (default: all; `compile` here is "
        "the static retrace-risk rules — the runtime trace-count "
        "harness is --compile-audit)",
    )
    parser.add_argument(
        "--compile-audit",
        action="store_true",
        help="instead of the rule engines: run each trainer's canonical "
        "short loop with a compilation hook, attribute every XLA "
        "compile to its jitted callable, gate counts against the "
        "compile_budgets section of analysis/budgets.json, and diff "
        "step-0 vs step-k jaxprs on any steady-state retrace "
        "(--update-budgets relocks the counts)",
    )
    parser.add_argument(
        "--lockstep",
        action="store_true",
        help="instead of the rule engines: simulate each trainer's "
        "canonical loop as N controller processes (threads with "
        "per-thread jax.process_index/process_count and rank-0 gates), "
        "record every jitted/collective-bearing dispatch per host, diff "
        "the logs (any divergence is a future multi-host deadlock, "
        "localized to ordinal + file:line + guarding branch), and gate "
        "host-0 dispatch fingerprints against the lockstep_budgets "
        "section of analysis/budgets.json (--update-budgets relocks)",
    )
    parser.add_argument(
        "--hosts",
        type=int,
        default=2,
        help="with --lockstep: number of simulated controller processes "
        "(default 2)",
    )
    parser.add_argument(
        "--plant-divergence",
        action="store_true",
        help="with --lockstep: plant one rank-0-only dispatch at the end "
        "of the loop — self-check that the simulator localizes exactly "
        "this hazard (budget gating is skipped; exit must be 1)",
    )
    parser.add_argument(
        "--races",
        action="store_true",
        help="instead of the rule engines: host-concurrency race audit — "
        "static thread-entry-point inventory + attribute-level lockset "
        "walk (unguarded-shared-write, lock-order-cycle, "
        "signal-unsafe-handler, atomicity-split), then a deterministic "
        "cooperative scheduler running the real async-writer, engine "
        "drive/weight-push, and TokenStream paths under N seeded "
        "interleavings asserting the repo's cross-thread invariants "
        "(schedule-invariant-violation names the replayable seed)",
    )
    parser.add_argument(
        "--schedules",
        type=int,
        default=6,
        help="with --races: seeded interleavings explored per scenario "
        "(default 6; nightly sweeps pass more)",
    )
    parser.add_argument(
        "--race-seed",
        type=int,
        default=None,
        metavar="SEED",
        help="with --races: replay exactly this one schedule seed per "
        "scenario instead of the 0..N-1 sweep (reproduce a reported "
        "schedule-invariant-violation)",
    )
    parser.add_argument(
        "--race-scenarios",
        metavar="NAMES",
        default=None,
        help="with --races: comma-separated subset of dynamic scenarios "
        "(writer-rows,stream-close,engine-push; default: all)",
    )
    parser.add_argument(
        "--plant-race",
        action="store_true",
        help="with --races: plant a deliberate unguarded counter through "
        "BOTH halves — the lockset walk must name "
        "unguarded-shared-write at the planted file:line and the "
        "scheduler must find a violating schedule; exit must be 1",
    )
    parser.add_argument(
        "--hlo-audit",
        action="store_true",
        help="instead of the rule engines: AOT-compile every traced "
        "program with its real in_shardings, parse the optimized "
        "post-SPMD HLO + buffer-assignment stats, diff the emitted "
        "collectives/dtypes/peak against jaxpr intent and the "
        "hlo_budgets section of analysis/budgets.json, and sweep the "
        "known-miscompile registry (--update-budgets relocks)",
    )
    parser.add_argument(
        "--plant-hazard",
        action="store_true",
        help="with --hlo-audit: compile a seeded eager concat of "
        "committed-sharded arrays — self-check that the audit trips "
        "both spmd-concat-hazard (at the planted line) and "
        "lowering-collective-drift (on the minted replica-axis "
        "all-reduce); budget gating is skipped; exit must be 1",
    )
    parser.add_argument(
        "--no-mesh-matrix",
        action="store_true",
        help="with --hlo-audit: compile only the audit-mesh program set, "
        "skipping the train-step compiles on the rest of the "
        "collective-divergence mesh matrix (faster; less coverage)",
    )
    parser.add_argument(
        "--resume-audit",
        action="store_true",
        help="instead of the rule engines: checkpoint/resume "
        "state-coverage audit — statically classify every mutable "
        "attribute on the trainer-reachable surface as "
        "checkpoint-carried / config-reconstructed / allowlisted "
        "ephemeral (resume-state-gap on anything else), run a "
        "kill/resume differ per trainer (checkpoint at a phase "
        "boundary, rebuild + restore, one more phase vs an "
        "uninterrupted twin, deep-compare the full live attribute "
        "trees: resume-divergence), and gate the checkpoint schema "
        "against the state_manifest section of analysis/budgets.json "
        "(ckpt-schema-drift; --update-budgets relocks)",
    )
    parser.add_argument(
        "--plant-gap",
        action="store_true",
        help="with --resume-audit: plant an uncheckpointed counter "
        "threaded into the sampling schedule — self-check that the "
        "static half names resume-state-gap at the planted file:line "
        "AND the differ names the divergent attribute path; schema "
        "gating is skipped; exit must be 1",
    )
    parser.add_argument(
        "--resources",
        action="store_true",
        help="instead of the rule engines: compute static peak-HBM / "
        "collective-bytes / FLOP budgets per traced program and gate "
        "them against the committed analysis/budgets.json contract",
    )
    parser.add_argument(
        "--perf-audit",
        action="store_true",
        help="instead of the rule engines: run the instrumented streamed "
        "phase loop (telemetry spans, docs/observability.md), measure "
        "per-span p50/p95 wall-clock, and gate the stable phase spans "
        "against the perf_budgets section of analysis/budgets.json "
        "(--update-budgets relocks; --span-log exports the trace)",
    )
    parser.add_argument(
        "--span-log",
        metavar="PATH",
        default=None,
        help="with --perf-audit: write the audited run's span stream to "
        "PATH as Perfetto/chrome-tracing JSONL",
    )
    parser.add_argument(
        "--perf-phases",
        type=int,
        default=5,
        help="with --perf-audit: measured phases per run (default 5; "
        "p50 over these gates the lockfile)",
    )
    parser.add_argument(
        "--plant-slowdown",
        type=float,
        default=0.0,
        metavar="MS",
        help="with --perf-audit: inject MS milliseconds of host-side "
        "sleep into every measured phase — self-check that a planted "
        "regression trips the perf-regression gate",
    )
    parser.add_argument(
        "--health-smoke",
        action="store_true",
        help="instead of the rule engines: planted-anomaly self-check "
        "for the run-health detectors (docs/observability.md) — clean "
        "streamed phases must stay quiet, then a poisoned embedding "
        "table must trip kl-spike + entropy-collapse and write a "
        "flight dump parseable by `python -m trlx_tpu.telemetry "
        "--inspect`; exit 1 when any leg fails",
    )
    parser.add_argument(
        "--chaos-smoke",
        action="store_true",
        help="instead of the rule engines: injected-failure self-check "
        "for the resilience layer (docs/resilience.md) — a clean "
        "supervised run must stay quiet; a transient checkpoint-I/O "
        "error must recover via bounded backoff; a structure mismatch "
        "must refuse fast; a SIGTERM at phase k must drain to an "
        "emergency checkpoint and auto-resume bitwise-identically; an "
        "engine-path failure must degrade to the fixed sampler with a "
        "health event; a disk-full rollout log must degrade to "
        "synchronous writes with zero row loss; exit 1 when any "
        "scenario fails",
    )
    parser.add_argument(
        "--chaos-workdir",
        metavar="DIR",
        default=None,
        help="with --chaos-smoke: scratch/artifact directory for the "
        "scenarios' checkpoints and logs (default: a temp dir)",
    )
    parser.add_argument(
        "--chaos-scenarios",
        metavar="NAMES",
        default=None,
        help="with --chaos-smoke: comma-separated subset of scenarios "
        "to run (default: all)",
    )
    parser.add_argument(
        "--async-smoke",
        action="store_true",
        help="instead of the rule engines: self-check for the "
        "asynchronous actor–learner path (docs/async_pipeline.md) — a "
        "staleness_window=0 async phase must be bitwise-identical to "
        "the serial same-plan phase with zero weight pushes, and a "
        "planted dead actor (engine.admit chaos) must surface an "
        "actor-dead health event and recover via the resilience "
        "supervisor with no hang; exit 1 when any scenario fails",
    )
    parser.add_argument(
        "--async-workdir",
        metavar="DIR",
        default=None,
        help="with --async-smoke: scratch directory for the scenarios' "
        "checkpoints (default: a temp dir)",
    )
    parser.add_argument(
        "--async-scenarios",
        metavar="NAMES",
        default=None,
        help="with --async-smoke: comma-separated subset of scenarios "
        "to run (default: all)",
    )
    parser.add_argument(
        "--health-dump-dir",
        metavar="DIR",
        default=None,
        help="with --health-smoke: directory for the flight-dump "
        "artifact (default: a temp dir; CI passes an upload path)",
    )
    parser.add_argument(
        "--update-budgets",
        action="store_true",
        help="with --resources / --compile-audit / --perf-audit / "
        "--hlo-audit: "
        "regenerate that engine's section of the budget lockfile from "
        "the current run instead of checking against it (review the "
        "diff!); each engine's relock preserves the others' entries",
    )
    parser.add_argument(
        "--budgets",
        metavar="PATH",
        default=None,
        help="budget contract file for --resources "
        "(default: trlx_tpu/analysis/budgets.json)",
    )
    parser.add_argument(
        "--sanitize",
        metavar="TRAINER",
        default=None,
        help="instead of the rule engines: replay TRAINER's train step "
        "eqn-by-eqn on concrete values and report the first non-finite "
        "equation (ppo|ilql|grpo|seq2seq)",
    )
    parser.add_argument(
        "--mesh",
        default=None,
        help="mesh axis sizes for --sanitize / --resources, e.g. "
        "dp=2,fsdp=2,tp=2 (default: the audit mesh)",
    )
    parser.add_argument(
        "--plant-nan",
        action="store_true",
        help="poison one param leaf with NaN before --sanitize — "
        "self-check that the replay detects and attributes it",
    )
    parser.add_argument(
        "--streamed",
        action="store_true",
        help="with --sanitize: replay the overlapped phase's streamed "
        "epoch-1 step — the minibatch is gathered from the streaming "
        "buffer after chunked dynamic_update_slice landings, the way "
        "the streamed dispatcher produces it",
    )
    parser.add_argument(
        "--engine-step",
        action="store_true",
        help="with --sanitize ppo: replay the continuous-batching "
        "engine's decode_step, then the speculative verify_step "
        "(docs/inference.md) on a concretely prefilled slot pool "
        "instead of the train step",
    )
    parser.add_argument(
        "--paths",
        nargs="*",
        default=None,
        help="files/dirs for the AST lint (default: the trlx_tpu package)",
    )
    parser.add_argument(
        "--trainers",
        default=None,
        help="comma-separated trainer kinds for the jaxpr audit "
        "(default: ppo,ilql,grpo,seq2seq)",
    )
    parser.add_argument(
        "--strict",
        action="store_true",
        help="fail on any finding, warnings included",
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalogue"
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        from trlx_tpu.analysis.registry import all_rules

        for rule in all_rules():
            print(f"{rule.id:18} [{rule.engine}/{rule.severity}] "
                  f"{rule.description}")
        return 0

    mesh = None
    if args.mesh:
        mesh = {
            k.strip(): int(v)
            for k, v in (kv.split("=") for kv in args.mesh.split(","))
        }
    trainers = (
        [t.strip() for t in args.trainers.split(",") if t.strip()]
        if args.trainers
        else None
    )

    if args.hlo_audit or args.plant_hazard:
        _force_cpu_platform()
        from trlx_tpu.analysis.hlo_audit import audit_hlo, format_hlo_text

        report, result = audit_hlo(
            kinds=trainers,
            mesh=mesh,
            budgets_path=args.budgets,
            update=args.update_budgets,
            matrix=not args.no_mesh_matrix,
            plant=args.plant_hazard,
        )
        if args.json:
            report.resources = result.to_rows()
            print(report.to_json())
        else:
            print(format_hlo_text(result))
            if args.update_budgets and not report.findings:
                print(
                    "hlo budgets written — review and commit the "
                    "lockfile diff"
                )
            if report.findings:
                print(report.format_text())
        if args.update_budgets:
            # findings here mean the update was REFUSED (rule findings
            # on the tree, or a cross-mesh partial relock) and nothing
            # was written
            return 1 if report.findings else 0
        return report.exit_code(strict=args.strict)

    if args.resume_audit or args.plant_gap:
        _force_cpu_platform()
        from trlx_tpu.analysis.state_audit import (
            audit_resume_state,
            format_state_text,
        )

        report, result = audit_resume_state(
            kinds=trainers,
            mesh=mesh,
            budgets_path=args.budgets,
            update=args.update_budgets,
            plant_gap=args.plant_gap,
        )
        if args.json:
            print(report.to_json())
        else:
            print(format_state_text(result))
            if args.update_budgets and not report.findings:
                print(
                    "state manifest written — review and commit the "
                    "lockfile diff"
                )
            if report.findings:
                print(report.format_text())
        if args.update_budgets:
            # findings here mean the update was REFUSED (gap/divergence
            # findings on the tree, or a cross-mesh partial relock) and
            # nothing trustworthy was written
            return 1 if report.findings else 0
        return report.exit_code(strict=args.strict)

    if args.races or args.plant_race:
        _force_cpu_platform()
        from trlx_tpu.analysis.concurrency import (
            audit_races,
            format_races_text,
        )

        scenarios = (
            [s.strip() for s in args.race_scenarios.split(",") if s.strip()]
            if args.race_scenarios
            else None
        )
        report, result = audit_races(
            paths=args.paths,
            schedules=args.schedules,
            plant=args.plant_race,
            seed=args.race_seed,
            scenarios=scenarios,
        )
        if args.json:
            print(report.to_json())
        else:
            print(format_races_text(result))
            if report.findings:
                print(report.format_text())
        return report.exit_code(strict=args.strict)

    if args.lockstep:
        _force_cpu_platform()
        from trlx_tpu.analysis.lockstep import (
            audit_lockstep,
            format_lockstep_text,
        )

        report, results = audit_lockstep(
            kinds=trainers,
            hosts=args.hosts,
            mesh=mesh,
            budgets_path=args.budgets,
            update=args.update_budgets,
            plant=args.plant_divergence,
        )
        if args.json:
            report.resources = [r.to_row() for r in results]
            print(report.to_json())
        else:
            print(format_lockstep_text(results))
            if args.update_budgets and not report.findings:
                print(
                    "lockstep budgets written — review and commit the "
                    "lockfile diff"
                )
            if report.findings:
                print(report.format_text())
        if args.update_budgets:
            # findings here mean the update was REFUSED (diverging
            # schedule, or cross-mesh/hosts partial relock) and nothing
            # was written
            return 1 if report.findings else 0
        return report.exit_code(strict=args.strict)

    if args.compile_audit:
        _force_cpu_platform()
        from trlx_tpu.analysis.compile_audit import (
            audit_compiles,
            format_compile_text,
        )

        report, result = audit_compiles(
            kinds=trainers,
            mesh=mesh,
            budgets_path=args.budgets,
            update=args.update_budgets,
        )
        if args.json:
            report.resources = result.to_rows()
            print(report.to_json())
        else:
            print(format_compile_text(result))
            if args.update_budgets and not report.findings:
                print(
                    "compile budgets written — review and commit the "
                    "lockfile diff"
                )
            if report.findings:
                print(report.format_text())
        if args.update_budgets:
            # findings here mean the update was REFUSED (cross-mesh
            # partial relock) and nothing was written
            return 1 if report.findings else 0
        return report.exit_code(strict=args.strict)

    if args.chaos_smoke:
        _force_cpu_platform()
        from trlx_tpu.analysis.chaos_smoke import (
            format_smoke_text,
            run_chaos_smoke,
        )

        only = (
            [s.strip() for s in args.chaos_scenarios.split(",") if s.strip()]
            if args.chaos_scenarios
            else None
        )
        summary = run_chaos_smoke(workdir=args.chaos_workdir, only=only)
        return _emit_smoke(summary, format_smoke_text, args.json)

    if args.async_smoke:
        _force_cpu_platform()
        from trlx_tpu.analysis.async_smoke import (
            format_smoke_text,
            run_async_smoke,
        )

        only = (
            [s.strip() for s in args.async_scenarios.split(",") if s.strip()]
            if args.async_scenarios
            else None
        )
        summary = run_async_smoke(workdir=args.async_workdir, only=only)
        return _emit_smoke(summary, format_smoke_text, args.json)

    if args.health_smoke:
        _force_cpu_platform()
        from trlx_tpu.analysis.health_smoke import (
            format_smoke_text,
            run_health_smoke,
        )

        summary = run_health_smoke(dump_dir=args.health_dump_dir)
        return _emit_smoke(summary, format_smoke_text, args.json)

    if args.perf_audit:
        _force_cpu_platform()
        from trlx_tpu.analysis.perf_audit import audit_perf, format_perf_text

        report, rows = audit_perf(
            budgets_path=args.budgets,
            update=args.update_budgets,
            phases=args.perf_phases,
            slowdown_ms=args.plant_slowdown,
            span_log=args.span_log,
        )
        if args.json:
            print(report.to_json())
        else:
            print(format_perf_text(rows))
            if args.update_budgets and not report.findings:
                print(
                    "perf budgets written — review and commit the "
                    "lockfile diff"
                )
            if report.findings:
                print(report.format_text())
        if args.update_budgets:
            return 1 if report.findings else 0
        return report.exit_code(strict=args.strict)

    if args.resources:
        _force_cpu_platform()
        from trlx_tpu.analysis.resource_audit import (
            audit_resources,
            default_budgets_path,
            format_resources_text,
        )

        report, resources = audit_resources(
            kinds=trainers,
            mesh=mesh,
            budgets_path=args.budgets,
            update=args.update_budgets,
        )
        if args.json:
            print(report.to_json())
        else:
            print(format_resources_text(resources))
            if args.update_budgets and not report.findings:
                print(
                    f"budgets written to "
                    f"{args.budgets or default_budgets_path()} — review "
                    "and commit the diff"
                )
            if report.findings:
                print(report.format_text())
        if args.update_budgets:
            # findings here mean the update was REFUSED (mesh-mixing
            # partial relock) and nothing was written
            return 1 if report.findings else 0
        return report.exit_code(strict=args.strict)

    if args.sanitize:
        _force_cpu_platform()
        from trlx_tpu.analysis.sanitizer import (
            sanitize_engine_step,
            sanitize_trainer,
        )

        if args.engine_step:
            result = sanitize_engine_step(
                args.sanitize, mesh=mesh, plant=args.plant_nan
            )
        else:
            result = sanitize_trainer(
                args.sanitize, mesh=mesh, plant=args.plant_nan,
                streamed=args.streamed,
            )
        report = result.to_report()
        print(report.to_json() if args.json else result.format_text())
        return report.exit_code(strict=args.strict)

    if args.engine in (
        "all", "jaxpr", "nanflow", "collective", "donation", "prng",
    ):
        _force_cpu_platform()

    from trlx_tpu.analysis import run

    report = run(engine=args.engine, paths=args.paths, trainers=trainers)
    print(report.to_json() if args.json else report.format_text())
    return report.exit_code(strict=args.strict)


if __name__ == "__main__":
    sys.exit(main())
