"""PRNG key-lineage: dataflow over traced jaxprs + host split-chain walk.

Engine 9 of ``trlx_tpu.analysis``. PPO's statistical correctness rests
on key discipline nothing else checks: key reuse silently *correlates*
rollouts (two draws from one key explore identical trajectories), a
dropped split repeats the "fresh" subkeys on the next call, and a
hard-coded seed pins every run of a sampling path to one trajectory set
— none of which is visible in loss curves. Three rules:

- ``key-reuse`` (jaxpr + host AST): one key consumed by two or more
  random primitives (draw / split / fold_in) without an intervening
  derivation. The jaxpr dataflow tracks key identity through
  ``random_wrap``/``random_unwrap`` (raw uint32[2] chains), call
  boundaries (pjit/remat/custom_*), and ``scan``: a key passed as a
  scan *constant* and consumed in the body is flagged — the body
  reuses it every iteration. ``cond`` branches are exclusive, so
  per-branch consumptions do not add up.
- ``key-discard`` (host AST): a ``jax.random.split`` whose output is
  never consumed, or a split of a persistent chain (``self.rng``)
  that does not rebind the chain — ``_, key = split(self.rng)``
  re-derives the identical key on every call.
- ``fixed-seed`` (host AST): a literal seed at a
  ``PRNGKey``/``jax.random.key``/``default_rng``/``set_seed`` call
  site in training-path code (trainer/pipeline/orchestrator/ops).
  Seeds come from config so runs differ on purpose.

Key-derivation semantics intentionally mirror jax's own: ``split`` and
``fold_in`` outputs are fresh lineages; slicing/indexing a split result
is selection, not reuse.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from trlx_tpu.analysis.findings import Finding, Report, filter_suppressed
from trlx_tpu.analysis.registry import get_rule

# primitives that CONSUME a key's randomness (a second consumption of the
# same lineage is reuse). random_seed mints a key from an int — creation,
# not consumption.
KEY_CONSUMERS = {
    "random_bits",
    "random_split",
    "random_fold_in",
    "random_gamma",
    "threefry2x32",
}

# identity-preserving wrappers: out is the SAME lineage as in
_KEY_IDENTITY = {"random_wrap", "random_unwrap", "convert_element_type"}

# call-like primitives entered with an invar->canonical mapping
_CALL_PRIMS = {
    "pjit": "jaxpr",
    "closed_call": "call_jaxpr",
    "core_call": "call_jaxpr",
    "remat": "jaxpr",
    "remat2": "jaxpr",
    "checkpoint": "jaxpr",
    "custom_jvp_call": "call_jaxpr",
    "custom_vjp_call": "call_jaxpr",
    "custom_vjp_call_jaxpr": "fun_jaxpr",
}


def _is_key_aval(aval) -> bool:
    dtype = getattr(aval, "dtype", None)
    if dtype is None:
        return False
    s = str(dtype)
    return s.startswith("key<") or "prng" in s.lower()


def _is_raw_key_aval(aval) -> bool:
    """uint32[..., 2]: the raw threefry key layout trainers thread."""
    dtype = getattr(aval, "dtype", None)
    shape = getattr(aval, "shape", None)
    return (
        dtype is not None
        and str(dtype) == "uint32"
        and shape is not None
        and len(shape) >= 1
        and shape[-1] == 2
    )


@dataclass
class _Site:
    primitive: str
    canonical: int
    label: str
    file: Optional[str]
    line: Optional[int]
    repeats: bool  # a loop-invariant key consumed inside a scan body:
    # the SAME lineage is consumed once per iteration


class _KeyFlow:
    """One program's key-lineage walk."""

    def __init__(self, subject: str, repo_root: str):
        self.subject = subject
        self.repo_root = repo_root
        self._next = 0
        self.labels: Dict[int, str] = {}
        # canonical id -> consumption sites, in program order
        self.consumers: Dict[int, List[_Site]] = {}

    def fresh(self, label: str = "") -> int:
        self._next += 1
        self.labels[self._next] = label
        return self._next

    # -------------------------- the jaxpr walk -------------------------- #

    def run(self, closed_jaxpr, input_paths: Optional[Sequence[str]] = None):
        inner = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
        env: Dict[Any, int] = {}
        for i, v in enumerate(inner.invars):
            if _is_key_aval(v.aval) or _is_raw_key_aval(v.aval):
                label = (
                    input_paths[i]
                    if input_paths and i < len(input_paths)
                    else f"input[{i}]"
                )
                env[v] = self.fresh(label)
        self._walk(inner, env, repeat_ids=set())
        return self

    def _loc(self, eqn) -> Tuple[Optional[str], Optional[int]]:
        from trlx_tpu.analysis.jaxpr_audit import _repo_frame

        frame = _repo_frame(eqn, self.repo_root)
        if frame is None:
            return None, None
        return frame.file_name, frame.start_line

    def _consume(self, eqn, canonical: int, repeats: bool) -> None:
        file, line = self._loc(eqn)
        self.consumers.setdefault(canonical, []).append(
            _Site(
                primitive=eqn.primitive.name,
                canonical=canonical,
                label=self.labels.get(canonical, ""),
                file=file,
                line=line,
                repeats=repeats,
            )
        )

    def _walk(
        self, jaxpr, env: Dict[Any, int], repeat_ids: Set[int]
    ) -> None:
        def canon(v) -> Optional[int]:
            if hasattr(v, "val"):  # Literal
                return None
            return env.get(v)

        for eqn in jaxpr.eqns:
            name = eqn.primitive.name

            if name in KEY_CONSUMERS:
                for v in eqn.invars:
                    c = canon(v)
                    if c is not None:
                        self._consume(eqn, c, repeats=c in repeat_ids)
                # split/fold_in outputs are FRESH lineages
                for out in eqn.outvars:
                    if _is_key_aval(out.aval) or _is_raw_key_aval(out.aval):
                        env[out] = self.fresh(f"derived@{name}")
                continue

            if name in _KEY_IDENTITY:
                src = canon(eqn.invars[0]) if eqn.invars else None
                if src is not None and eqn.outvars:
                    env[eqn.outvars[0]] = src
                continue

            if name in _CALL_PRIMS:
                closed = eqn.params.get(_CALL_PRIMS[name])
                if closed is not None:
                    sub = getattr(closed, "jaxpr", closed)
                    sub_env: Dict[Any, int] = {}
                    for outer, inner_v in zip(eqn.invars, sub.invars):
                        c = canon(outer)
                        if c is not None:
                            sub_env[inner_v] = c
                    self._walk(sub, sub_env, repeat_ids)
                    for outer_out, inner_out in zip(
                        eqn.outvars, sub.outvars
                    ):
                        if not hasattr(inner_out, "val"):
                            c = sub_env.get(inner_out)
                            if c is not None:
                                env[outer_out] = c
                continue

            if name == "scan":
                closed = eqn.params.get("jaxpr")
                if closed is not None:
                    sub = getattr(closed, "jaxpr", closed)
                    n_consts = eqn.params.get("num_consts", 0)
                    sub_env = {}
                    # consts are loop-invariant: the SAME lineage enters
                    # every iteration — one consumption in the body
                    # repeats per step (marked via repeat_ids and
                    # upgraded to reuse by findings())
                    body_repeats = set(repeat_ids)
                    for outer, inner_v in zip(
                        eqn.invars[:n_consts], sub.invars[:n_consts]
                    ):
                        c = canon(outer)
                        if c is not None:
                            sub_env[inner_v] = c
                            body_repeats.add(c)
                    # carry/xs keys are per-iteration values: fresh, and
                    # NOT repeating (the carry advances each step)
                    for inner_v in sub.invars[n_consts:]:
                        if _is_key_aval(inner_v.aval) or _is_raw_key_aval(
                            inner_v.aval
                        ):
                            sub_env[inner_v] = self.fresh("scan-carry")
                    self._walk(sub, sub_env, body_repeats)
                continue

            if name == "cond":
                branches = eqn.params.get("branches", ())
                # branches are exclusive: consumptions must not add up
                # across them — each runs against a snapshot, and the
                # heaviest branch's counts are kept
                base = {
                    c: list(sites) for c, sites in self.consumers.items()
                }
                best = base
                best_total = sum(len(s) for s in base.values())
                for closed in branches:
                    sub = getattr(closed, "jaxpr", closed)
                    self.consumers = {
                        c: list(sites) for c, sites in base.items()
                    }
                    sub_env = {}
                    for outer, inner_v in zip(eqn.invars[1:], sub.invars):
                        c = canon(outer)
                        if c is not None:
                            sub_env[inner_v] = c
                    self._walk(sub, sub_env, repeat_ids)
                    total = sum(len(s) for s in self.consumers.values())
                    if total > best_total:
                        best, best_total = self.consumers, total
                self.consumers = best
                continue

            # anything else producing a key-typed output (slice/squeeze/
            # gather of a split result, stacking, ...) is SELECTION of a
            # fresh lineage, not reuse
            for out in eqn.outvars:
                if hasattr(out, "val"):
                    continue
                if _is_key_aval(out.aval) or _is_raw_key_aval(out.aval):
                    env[out] = self.fresh(f"selected@{name}")

    # ----------------------------- findings ----------------------------- #

    def findings(self) -> List[Finding]:
        rule = get_rule("key-reuse")
        out: List[Finding] = []
        for canonical, sites in sorted(self.consumers.items()):
            effective = len(sites) + sum(1 for s in sites if s.repeats)
            if effective < 2:
                continue
            label = self.labels.get(canonical, "") or "key"
            offender = sites[1] if len(sites) > 1 else sites[0]
            ops = ", ".join(
                s.primitive + (" (per scan iteration)" if s.repeats else "")
                for s in sites
            )
            out.append(
                Finding(
                    rule=rule.id,
                    message=(
                        f"key `{label}` is consumed by {len(sites)} random "
                        f"primitive(s) [{ops}] without an intervening "
                        "split/fold_in — draws from one key are perfectly "
                        "correlated; split first and consume the subkeys"
                    ),
                    severity=rule.severity,
                    file=_relpath(offender.file),
                    line=offender.line,
                    subject=self.subject,
                    engine="prng",
                )
            )
        return out


def _relpath(path: Optional[str]) -> Optional[str]:
    if path is None:
        return None
    from trlx_tpu.analysis.jaxpr_audit import default_repo_root

    root = default_repo_root()
    if root in path:
        return path.split(root, 1)[1].lstrip("/")
    return path


def analyze_key_flow(
    closed_jaxpr,
    subject: str = "program",
    input_paths: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """key-reuse findings of one traced program."""
    from trlx_tpu.analysis.jaxpr_audit import default_repo_root

    flow = _KeyFlow(subject, default_repo_root())
    flow.run(closed_jaxpr, input_paths)
    return flow.findings()


# ----------------------------- host AST walk ------------------------------ #

# jax.random draw functions whose first argument consumes a key
_DRAW_FNS = {
    "normal", "uniform", "bits", "categorical", "bernoulli", "gumbel",
    "choice", "permutation", "randint", "truncated_normal", "exponential",
    "laplace", "poisson", "gamma", "beta", "dirichlet", "cauchy",
}

# calls whose literal first argument is a seed
_SEED_FNS = {"PRNGKey", "key", "default_rng", "seed", "set_seed"}

# training-path directories for the fixed-seed rule (tests and the
# analysis harness use fixed seeds deliberately)
_TRAINING_PATH_DIRS = ("trainer", "pipeline", "orchestrator", "ops", "models")
_TRAINING_PATH_FILES = ("api.py",)


def _name_of(node: ast.AST) -> Optional[str]:
    """Textual form of a chain-able reference: `x` or `self.x`."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        return f"{node.value.id}.{node.attr}"
    return None


def _is_split_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    dotted = []
    func = node.func
    while isinstance(func, ast.Attribute):
        dotted.append(func.attr)
        func = func.value
    if isinstance(func, ast.Name):
        dotted.append(func.id)
    dotted.reverse()
    return bool(dotted) and dotted[-1] in ("split", "fold_in") and (
        len(dotted) == 1 or dotted[-2] in ("random",)
    )


class _ChainWalker(ast.NodeVisitor):
    """Ordered statement walk of one host function: split-chain discipline
    and key consumption counting."""

    def __init__(self, path: str, subject: str) -> None:
        self.path = path
        self.subject = subject
        self.findings: List[Finding] = []
        # key name -> number of consumptions since last (re)bind
        self.consumed: Dict[str, int] = {}
        # split-result names never read (candidate discards)
        self.unread_splits: Dict[str, ast.AST] = {}

    def _add(self, rule_id: str, node: ast.AST, message: str) -> None:
        rule = get_rule(rule_id)
        self.findings.append(
            Finding(
                rule=rule.id,
                message=message,
                severity=rule.severity,
                file=self.path,
                line=getattr(node, "lineno", None),
                subject=self.subject,
                engine="prng",
            )
        )

    # ----------------------------- binding ----------------------------- #

    def _bind_targets(self, targets: Sequence[ast.AST]) -> List[str]:
        names: List[str] = []
        for t in targets:
            elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
            for e in elts:
                n = _name_of(e)
                if n:
                    names.append(n)
        return names

    def visit_Assign(self, node: ast.Assign) -> None:
        # reads on the RHS happen before the bind
        self.generic_visit(node)
        bound = self._bind_targets(node.targets)
        for n in bound:
            self.consumed.pop(n, None)
            self.unread_splits.pop(n, None)
        if _is_split_call(node.value) and node.value.args:
            src = _name_of(node.value.args[0])
            for n in bound:
                # locals only: attribute targets (self.rng) are the
                # persistent chain advancing — read by the NEXT call —
                # and `_` is the idiomatic spelled-out discard handled
                # by the chain-advance check below
                if "." not in n and n != "_":
                    self.unread_splits[n] = node
            # splitting a persistent chain must advance it: self.rng
            # (or any *.rng/_rng attribute) has to be among the targets
            if (
                src
                and "." in src
                and src.split(".", 1)[1].lstrip("_") in ("rng", "key")
                and src not in bound
            ):
                self._add(
                    "key-discard",
                    node,
                    f"split of persistent chain `{src}` does not rebind "
                    f"it — the next call replays the same subkeys; write "
                    f"`{src}, key = jax.random.split({src})`",
                )

    def visit_Expr(self, node: ast.Expr) -> None:
        if _is_split_call(node.value):
            self._add(
                "key-discard",
                node,
                "jax.random.split result is discarded — the derived "
                "subkeys are lost and the source chain did not advance",
            )
        self.generic_visit(node)

    # --------------------------- consumption ---------------------------- #

    def _consume(self, name: str, node: ast.AST, how: str) -> None:
        self.consumed[name] = self.consumed.get(name, 0) + 1
        if self.consumed[name] == 2:
            self._add(
                "key-reuse",
                node,
                f"host key `{name}` is consumed twice without a fresh "
                f"split ({how}) — the two draws are perfectly correlated",
            )

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        dotted: List[str] = []
        f = func
        while isinstance(f, ast.Attribute):
            dotted.append(f.attr)
            f = f.value
        if isinstance(f, ast.Name):
            dotted.append(f.id)
        dotted.reverse()
        leaf = dotted[-1] if dotted else None

        if leaf in _DRAW_FNS and len(dotted) >= 2 and dotted[-2] == "random":
            if node.args:
                n = _name_of(node.args[0])
                if n:
                    self._consume(n, node, f"jax.random.{leaf}")
        elif leaf and (leaf.endswith("_jit") or leaf in ("sample",)):
            for arg in node.args:
                n = _name_of(arg)
                if n and (
                    n in self.consumed
                    or n.split(".")[-1] in ("key", "rng", "subkey")
                ):
                    self._consume(n, arg, f"passed to {leaf}()")
        self.generic_visit(node)

    # ANY Load-context read of a split result counts as consumption —
    # subscripts (`keys[0]`), returns, tuple packing, f-strings — not
    # just call arguments; key-discard is only the *never read at all*
    # case (the `visit_Assign` re-add happens after its RHS walk, so a
    # fresh split's own statement cannot clear its entry)
    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load):
            self.unread_splits.pop(node.id, None)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if isinstance(node.ctx, ast.Load):
            n = _name_of(node)
            if n:
                self.unread_splits.pop(n, None)
        self.generic_visit(node)

    def visit_FunctionDef(self, node) -> None:
        return  # nested defs walk under their own classification

    visit_AsyncFunctionDef = visit_FunctionDef


def _is_training_path(path: str) -> bool:
    parts = os.path.normpath(path).split(os.sep)
    if parts[-1] in _TRAINING_PATH_FILES:
        return True
    return any(d in parts for d in _TRAINING_PATH_DIRS)


class _SeedLinter(ast.NodeVisitor):
    """fixed-seed: literal seeds at RNG constructor call sites."""

    def __init__(self, path: str, subject: str) -> None:
        self.path = path
        self.subject = subject
        self.findings: List[Finding] = []

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        leaf = None
        if isinstance(func, ast.Attribute):
            leaf = func.attr
        elif isinstance(func, ast.Name):
            leaf = func.id
        if (
            leaf in _SEED_FNS
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, int)
        ):
            rule = get_rule("fixed-seed")
            self.findings.append(
                Finding(
                    rule=rule.id,
                    message=(
                        f"literal seed {node.args[0].value!r} at "
                        f"{leaf}(...) in training-path code — every run "
                        "replays the same randomness; take the seed from "
                        "train.seed/config"
                    ),
                    severity=rule.severity,
                    file=self.path,
                    line=node.lineno,
                    subject=self.subject,
                    engine="prng",
                )
            )
        self.generic_visit(node)


def lint_key_chains(
    paths: Sequence[str],
) -> Tuple[List[Finding], List[str], int]:
    """Host-side walk: split-chain discipline in untraced functions and
    literal seeds in training-path modules."""
    from trlx_tpu.analysis.ast_lint import (
        _FunctionIndex,
        _ImportAliases,
        _transitively_traced,
        collect_py_files,
    )

    files = collect_py_files(paths)

    findings: List[Finding] = []
    n_suppressed = 0
    for path in files:
        try:
            with open(path, encoding="utf-8") as fh:
                source = fh.read()
            tree = ast.parse(source, filename=path)
        except (OSError, SyntaxError):
            continue
        aliases = _ImportAliases()
        aliases.visit(tree)
        index = _FunctionIndex(aliases)
        index.visit(tree)
        traced = _transitively_traced(index)

        file_findings: List[Finding] = []
        for fname in sorted(set(index.defs) - traced):
            for fnode in index.defs.get(fname, ()):
                walker = _ChainWalker(path, f"{fname}()")
                for stmt in fnode.body:
                    walker.visit(stmt)
                for name, node in walker.unread_splits.items():
                    walker._add(
                        "key-discard",
                        node,
                        f"split result `{name}` is never consumed — "
                        "either dead randomness or a chain that was "
                        "meant to advance",
                    )
                file_findings.extend(walker.findings)

        if _is_training_path(path):
            seeds = _SeedLinter(path, os.path.basename(path))
            seeds.visit(tree)
            file_findings.extend(seeds.findings)

        kept, suppressed = filter_suppressed(
            file_findings, {path: source.splitlines()}
        )
        findings.extend(kept)
        n_suppressed += suppressed
    return findings, files, n_suppressed


# ----------------------------- orchestration ------------------------------ #

def analyze_trainers(
    kinds: Optional[Sequence[str]] = None,
    paths: Optional[Sequence[str]] = None,
    programs=None,
) -> Report:
    """The engine entry: key-reuse dataflow over every traced trainer
    program that consumes a key, plus the host chain/seed walk."""
    from trlx_tpu.analysis import harness

    report = Report()
    if programs is None:
        programs = list(harness.trace_all(kinds))
    jaxpr_findings: List[Finding] = []
    for traced in programs:
        flow_findings = analyze_key_flow(
            traced.closed_jaxpr, traced.subject, traced.input_paths
        )
        jaxpr_findings.extend(flow_findings)
        report.covered.append(f"prng:{traced.subject}")
    kept, suppressed = filter_suppressed(jaxpr_findings)
    report.extend(kept)
    report.suppressed += suppressed

    default_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ast_findings, files, ast_suppressed = lint_key_chains(
        paths or [default_root]
    )
    report.extend(ast_findings)
    report.covered.append(f"prng-host:{len(files)} files")
    report.suppressed += ast_suppressed
    return report
