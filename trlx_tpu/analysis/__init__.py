"""Static analysis for the TPU port: jaxpr audit, AST lint, NaN-source
dataflow, collective-sequence divergence, an eqn-level sanitizer, a
resource auditor with CI-gated budgets, and a donation-safety checker.

The engines enforce the invariants the reference kept by convention
(bf16 compute / f32 optimizer, frozen KL reference, declared-collective
parallelism), the host-sync discipline OPPO/HEPPO-GAE (PAPERS.md) show
PPO throughput hinges on, and — since PR 2 — the numerics-and-SPMD
safety properties the fsdp/tp NaN divergence exposed:

- :mod:`trlx_tpu.analysis.jaxpr_audit` — traces the trainers' jitted
  step/rollout programs abstractly on a CPU mesh and walks the jaxprs.
- :mod:`trlx_tpu.analysis.ast_lint` — rule-based source checker for
  host-sync / tracer-safety hazards in traced Python code, plus the
  host-branch SPMD-desync rule for host-loop code.
- :mod:`trlx_tpu.analysis.nan_flow` — guard-dominance dataflow flagging
  ops that can mint NaN/Inf from unguarded operands.
- :mod:`trlx_tpu.analysis.collective_trace` — collective schedules must
  be identical across the dp/fsdp/tp mesh matrix up to axis renaming.
- :mod:`trlx_tpu.analysis.sanitizer` — ``--sanitize <trainer>`` replays
  a captured step jaxpr eqn-by-eqn on concrete values and reports the
  first non-finite equation with source provenance.
- :mod:`trlx_tpu.analysis.resource_audit` — ``--resources`` computes
  static peak-HBM / collective-traffic / FLOP budgets per traced program
  and gates them against the committed ``analysis/budgets.json``
  contract (``--update-budgets`` regenerates it).
- :mod:`trlx_tpu.analysis.donation` — donation-safety: host
  use-after-donate (AST), donated-but-unreusable buffers, and
  input-forwarding alias escapes (jaxpr).
- :mod:`trlx_tpu.analysis.compile_audit` — ``--compile-audit`` runs each
  trainer's canonical loop under a compilation hook, gates per-callable
  compile counts against the ``compile_budgets`` lockfile section, and
  diffs step-0 vs step-k jaxprs so a retrace finding names its cause;
  its AST retrace-risk rules also run in ``--engine all``.
- :mod:`trlx_tpu.analysis.key_lineage` — PRNG discipline: key-reuse
  dataflow over traced jaxprs plus a host-side split-chain walk of
  ``self.rng`` rebinding (rules ``key-reuse``/``key-discard``/
  ``fixed-seed``).
- :mod:`trlx_tpu.analysis.perf_audit` — ``--perf-audit`` runs the
  telemetry-instrumented streamed phase loop and gates measured
  per-span wall-clock (p50) against the ``perf_budgets`` lockfile
  section (rule ``perf-regression``) — the first engine watching a
  *run*, not a trace; see docs/observability.md.
- :mod:`trlx_tpu.analysis.lockstep` — ``--lockstep`` simulates each
  trainer's canonical loop as N controller processes (per-thread
  ``jax.process_index``/rank-0 gates), records every jitted/
  collective-bearing dispatch per host, diffs the logs (rule
  ``lockstep-divergence``) and gates host-0 dispatch fingerprints
  against the ``lockstep_budgets`` lockfile section (rule
  ``dispatch-sequence-drift``); its static half is the engine-12
  host-concurrency rules in ``ast_lint`` (``rank-gated-dispatch``,
  ``nondet-host-order``, ``host-time-in-dispatch``,
  ``unsynced-host-io``), run by ``--engine all``/``ast``.
- :mod:`trlx_tpu.analysis.concurrency` — ``--races`` audits the host
  threads themselves (engine 14): a whole-repo thread-entry-point
  inventory + attribute-level lockset walk (rules
  ``unguarded-shared-write``, ``lock-order-cycle``,
  ``signal-unsafe-handler``, ``atomicity-split``, with a curated
  single-thread-contract allowlist), then a deterministic cooperative
  scheduler running the REAL async-writer / engine weight-push /
  TokenStream paths under N seeded interleavings (rule
  ``schedule-invariant-violation`` reports the first violating
  schedule as a replayable ``--race-seed``).

Run ``python -m trlx_tpu.analysis --help`` or see docs/static_analysis.md.
"""

from trlx_tpu.analysis.findings import (
    Finding,
    Report,
    filter_suppressed,
)
from trlx_tpu.analysis.registry import Rule, all_rules, get_rule, register_rule

__all__ = [
    "Finding",
    "Report",
    "Rule",
    "all_rules",
    "filter_suppressed",
    "get_rule",
    "register_rule",
    "run",
]


def run(
    engine: str = "all",
    paths=None,
    trainers=None,
) -> Report:
    """Run the selected engine(s); returns a merged :class:`Report`.

    :param engine: ``all`` | ``jaxpr`` | ``ast`` | ``nanflow`` |
        ``collective`` | ``donation`` | ``compile`` (AST retrace-risk
        rules only — the runtime trace-count harness is
        ``--compile-audit``) | ``prng``.
    :param paths: files/dirs for the AST lint (default: the trlx_tpu
        package directory).
    :param trainers: trainer kinds for the trainer-tracing engines
        (default: all four).
    """
    import os

    report = Report()
    if engine in ("all", "ast"):
        from trlx_tpu.analysis.ast_lint import lint_paths

        default_root = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
        findings, covered, suppressed = lint_paths(paths or [default_root])
        report.extend(findings)
        report.covered += covered
        report.suppressed += suppressed
    if engine in ("all", "compile"):
        from trlx_tpu.analysis.compile_audit import lint_retrace_risk

        default_root = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
        findings, covered, suppressed = lint_retrace_risk(
            paths or [default_root]
        )
        report.extend(findings)
        report.covered.append(f"retrace-risk:{len(covered)} files")
        report.suppressed += suppressed
    if engine in ("all", "jaxpr", "nanflow", "donation", "prng"):
        # one trace of the trainer programs feeds all jaxpr-walking
        # engines — trainer construction dominates the cost
        from trlx_tpu.analysis import harness

        programs = list(harness.trace_all(trainers))
        if engine in ("all", "jaxpr"):
            from trlx_tpu.analysis.jaxpr_audit import audit_trainers

            sub = audit_trainers(trainers, programs=programs)
            report.extend(sub.findings)
            report.covered += sub.covered
            report.suppressed += sub.suppressed
        if engine in ("all", "nanflow"):
            from trlx_tpu.analysis.nan_flow import analyze_trainers

            sub = analyze_trainers(trainers, programs=programs)
            report.extend(sub.findings)
            report.covered += sub.covered
            report.suppressed += sub.suppressed
        if engine in ("all", "donation"):
            from trlx_tpu.analysis.donation import audit_all

            sub = audit_all(trainers, paths=paths, programs=programs)
            report.extend(sub.findings)
            report.covered += sub.covered
            report.suppressed += sub.suppressed
        if engine in ("all", "prng"):
            from trlx_tpu.analysis.key_lineage import (
                analyze_trainers as analyze_keys,
            )

            sub = analyze_keys(trainers, paths=paths, programs=programs)
            report.extend(sub.findings)
            report.covered += sub.covered
            report.suppressed += sub.suppressed
    if engine in ("all", "collective"):
        from trlx_tpu.analysis.collective_trace import check_all

        sub = check_all(trainers)
        report.extend(sub.findings)
        report.covered += sub.covered
        report.suppressed += sub.suppressed
    return report
