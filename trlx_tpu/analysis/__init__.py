"""Static analysis for the TPU port: jaxpr audit + AST lint.

Two engines enforce the invariants the reference kept by convention
(bf16 compute / f32 optimizer, frozen KL reference, declared-collective
parallelism) and the host-sync discipline OPPO/HEPPO-GAE (PAPERS.md) show
PPO throughput hinges on:

- :mod:`trlx_tpu.analysis.jaxpr_audit` — traces the trainers' jitted
  step/rollout programs abstractly on a CPU mesh and walks the jaxprs.
- :mod:`trlx_tpu.analysis.ast_lint` — rule-based source checker for
  host-sync / tracer-safety hazards in traced Python code.

Run ``python -m trlx_tpu.analysis --help`` or see docs/static_analysis.md.
"""

from trlx_tpu.analysis.findings import (
    Finding,
    Report,
    filter_suppressed,
)
from trlx_tpu.analysis.registry import Rule, all_rules, get_rule, register_rule

__all__ = [
    "Finding",
    "Report",
    "Rule",
    "all_rules",
    "filter_suppressed",
    "get_rule",
    "register_rule",
    "run",
]


def run(
    engine: str = "all",
    paths=None,
    trainers=None,
) -> Report:
    """Run the selected engine(s); returns a merged :class:`Report`.

    :param engine: ``all`` | ``jaxpr`` | ``ast``.
    :param paths: files/dirs for the AST lint (default: the trlx_tpu
        package directory).
    :param trainers: trainer kinds for the jaxpr audit (default: all four).
    """
    import os

    report = Report()
    if engine in ("all", "ast"):
        from trlx_tpu.analysis.ast_lint import lint_paths

        default_root = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
        findings, covered, suppressed = lint_paths(paths or [default_root])
        report.extend(findings)
        report.covered += covered
        report.suppressed += suppressed
    if engine in ("all", "jaxpr"):
        from trlx_tpu.analysis.jaxpr_audit import audit_trainers

        sub = audit_trainers(trainers)
        report.extend(sub.findings)
        report.covered += sub.covered
        report.suppressed += sub.suppressed
    return report
