"""``--async-smoke``: self-check for the asynchronous actor–learner path.

The ``--chaos-smoke`` pattern applied to the async RL subsystem
(trainer/async_rl.py, docs/async_pipeline.md): each scenario runs a
REAL tiny job and asserts the contract — no mocks on the failure path.

1. **staleness0_parity** — the degenerate-mode contract: one full
   async phase at ``staleness_window: 0`` (continuous engine, health
   on) must be **bitwise identical** (final params + KL sequence +
   every per-update stat) to the serial same-plan streamed phase from
   the same initial state, with zero weight pushes and zero health
   events. This is the invariant that lets the whole async machinery
   ship default-off without a parallel maintenance burden: async is a
   dispatch/push *policy*, never a different schedule.
2. **dead_actor_recovery** — a planted dead actor (``engine.admit``
   chaos, the PR-9 injection site): (a) at the orchestrator level the
   failure must surface as an ``actor-dead`` health event and an
   :class:`~trlx_tpu.trainer.async_rl.ActorDeadError` — NOT a silent
   fixed-sampler fallback, which would change the async workload's
   whole schedule mid-run; (b) the same failure under the resilience
   supervisor must recover — the run completes to ``total_steps`` on
   the continuous engine with no hang (the supervisor classifies
   ActorDeadError retriable and rebuilds the actor pool).

PASS requires every scenario. Exercised per-PR by the ``async-smoke``
CI job (`python -m trlx_tpu.analysis --async-smoke --json`).
"""

from __future__ import annotations

import os
import tempfile
from typing import Any, Callable, Dict, List

SCENARIOS = (
    "staleness0_parity",
    "dead_actor_recovery",
)

#: continuous-engine rollout section shared by every scenario
_ROLLOUT = {"engine": "continuous", "slots": 8, "admit_width": 4,
            "harvest_width": 4}


def _phase_config_dict(
    async_rl: Dict[str, Any], dump_dir: str = None
) -> Dict[str, Any]:
    """Tiny 2-minibatch/2-epoch phase shape (the tests/test_async_rl.py
    canary shape) — enough landings for the guard to act on.
    ``dump_dir`` redirects any flight dump into the scenario workdir —
    the planted failures below MUST NOT litter the caller's cwd with a
    repo-root ``health_dumps/`` (the health_smoke discipline)."""
    from trlx_tpu.analysis import harness

    cfg = harness.tiny_config_dict("ppo", mesh={"dp": -1, "fsdp": 1, "tp": 1})
    cfg["method"].update(num_rollouts=16, chunk_size=8, ppo_epochs=2)
    cfg["train"]["batch_size"] = 8
    cfg["train"]["rollout"] = dict(_ROLLOUT)
    cfg["train"]["health"] = {"enabled": True}
    if dump_dir:
        cfg["train"]["health"]["dump_dir"] = dump_dir
    cfg["method"]["gen_kwargs"]["min_new_tokens"] = 1
    if async_rl:
        cfg["train"]["async_rl"] = dict(async_rl)
    return cfg


def _reward(samples, queries, response_gt=None):
    return [float(len(s)) for s in samples]


def _run_phase(trainer, init_state, overlap=None):
    """One streamed/async phase from a pinned initial state (the
    tests/test_phase_overlap.py reset discipline)."""
    import jax
    import numpy as np

    from trlx_tpu.orchestrator.ppo_orchestrator import PPOOrchestrator
    from trlx_tpu.pipeline.prompt_pipeline import PromptPipeline

    trainer.state = jax.device_put(init_state, trainer.state_shardings)
    trainer.rng = jax.random.PRNGKey(123)
    trainer.kl_coef = float(trainer.config.method.init_kl_coef)
    trainer.mean_kl = 0.0
    trainer.buffer.clear_history()
    rng = np.random.default_rng(3)
    prompts = [
        [int(x) for x in rng.integers(1, 30, size=4)] for _ in range(64)
    ]
    pipe = PromptPipeline(prompts, trainer.config.train.seq_length)
    orch = PPOOrchestrator(trainer, pipe, reward_fn=_reward, chunk_size=8)
    trainer.begin_streamed_phase(seed=11, overlap=overlap)
    orch.make_experience(trainer.config.method.num_rollouts, 0)
    n_up, rows, kl_seq = trainer.finish_streamed_phase()
    orch.close()
    return jax.device_get(trainer.state.params), rows, kl_seq, n_up


def scenario_staleness0_parity(workdir: str) -> Dict[str, Any]:
    import jax
    import numpy as np

    from trlx_tpu.data.configs import TRLConfig
    from trlx_tpu.trainer.ppo_trainer import PPOTrainer

    os.environ.setdefault("WANDB_DISABLED", "1")
    dumps = os.path.join(workdir, "health_dumps")
    tr_async = PPOTrainer(
        TRLConfig.from_dict(
            _phase_config_dict(
                {"enabled": True, "staleness_window": 0}, dump_dir=dumps
            )
        ),
        reward_fn=_reward,
    )
    init = jax.device_get(tr_async.state)
    p_a, r_a, kl_a, n_a = _run_phase(tr_async, init)
    pushes = tr_async._last_overlap_stats.get("async/weight_pushes", -1.0)
    events = list(tr_async.health_monitor.events)

    tr_serial = PPOTrainer(
        TRLConfig.from_dict(_phase_config_dict({}, dump_dir=dumps)),
        reward_fn=_reward,
    )
    p_s, r_s, kl_s, n_s = _run_phase(tr_serial, init, overlap=False)

    params_bitwise = all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(
            jax.tree_util.tree_leaves(p_a), jax.tree_util.tree_leaves(p_s)
        )
    )
    stats_bitwise = set(r_a) == set(r_s) and all(
        np.array_equal(np.asarray(r_a[k]), np.asarray(r_s[k])) for k in r_s
    )
    return {
        "n_updates": n_a,
        "params_bitwise_equal": params_bitwise,
        "kl_seq_equal": kl_a == kl_s,
        "stats_bitwise_equal": stats_bitwise,
        "weight_pushes": pushes,
        "health_events": len(events),
        "passed": (
            n_a == n_s
            and params_bitwise
            and kl_a == kl_s
            and stats_bitwise
            and pushes == 0.0
            and not events
        ),
    }


def scenario_dead_actor_recovery(workdir: str) -> Dict[str, Any]:
    import contextlib
    import sys

    import jax
    import numpy as np

    import trlx_tpu
    from trlx_tpu.data.configs import TRLConfig
    from trlx_tpu.resilience import chaos
    from trlx_tpu.trainer.async_rl import ActorDeadError
    from trlx_tpu.trainer.ppo_trainer import PPOTrainer

    os.environ["WANDB_DISABLED"] = "1"

    # (a) event visibility at the orchestrator level: the planted
    # admission failure must raise ActorDeadError AND leave exactly one
    # actor-dead health event — never a silent engine fallback
    dumps = os.path.join(workdir, "health_dumps")
    trainer = PPOTrainer(
        TRLConfig.from_dict(
            _phase_config_dict(
                {"enabled": True, "staleness_window": 1}, dump_dir=dumps
            )
        ),
        reward_fn=_reward,
    )
    chaos.configure([{"site": "engine.admit", "mode": "error", "count": 1}])
    raised = False
    try:
        _run_phase(trainer, jax.device_get(trainer.state), overlap=None)
    except ActorDeadError:
        raised = True
        trainer.abort_streamed_phase()
    finally:
        chaos.clear()
    counts = dict(trainer.health_monitor.event_counts)
    still_continuous = trainer.rollout_engine == "continuous"

    # (b) supervised recovery end-to-end: same failure under the PR-9
    # supervisor — the run must complete to total_steps with no hang
    # (the chaos spec is one-shot; the restarted attempt runs clean)
    ckpt = os.path.join(workdir, "ckpt")
    cfg = _phase_config_dict(
        {"enabled": True, "staleness_window": 1}, dump_dir=dumps
    )
    cfg["train"].update(
        total_steps=4,
        epochs=8,
        checkpoint_dir=ckpt,
        resilience={
            "enabled": True,
            "chaos": [
                {"site": "engine.admit", "mode": "error", "count": 1}
            ],
        },
    )
    rng = np.random.default_rng(0)
    prompts = [list(rng.integers(1, 30, size=3)) for _ in range(16)]
    with contextlib.redirect_stdout(sys.stderr):
        recovered = trlx_tpu.train(
            reward_fn=_reward,
            prompts=prompts,
            config=TRLConfig.from_dict(cfg),
        )
    # asserted on outcomes (the chaos-smoke preempt pattern): part (a)
    # already proved this exact spec fires and raises ActorDeadError,
    # so a supervised run that still completes at total_steps can only
    # have gotten there through the retriable classification + restart
    # (the supervisor's finally clears the chaos event log, so the
    # injection count is not observable here)
    return {
        "actor_dead_raised": raised,
        "actor_dead_events": counts.get("actor-dead", 0),
        "engine_not_degraded": still_continuous,
        "supervised_final_step": int(recovered.state.step),
        "passed": (
            raised
            and counts.get("actor-dead", 0) == 1
            and still_continuous
            and int(recovered.state.step) == 4
        ),
    }


_SCENARIO_FNS: Dict[str, Callable[[str], Dict[str, Any]]] = {
    "staleness0_parity": scenario_staleness0_parity,
    "dead_actor_recovery": scenario_dead_actor_recovery,
}


def run_async_smoke(
    workdir: str = None, only: List[str] = None
) -> Dict[str, Any]:
    """Run the scenarios; returns a JSON-able summary with ``passed``."""
    from trlx_tpu.resilience import chaos

    workdir = workdir or tempfile.mkdtemp(prefix="async-smoke-")
    names = list(only or SCENARIOS)
    unknown = set(names) - set(_SCENARIO_FNS)
    if unknown:
        raise ValueError(
            f"unknown async-smoke scenario(s) {sorted(unknown)}; "
            f"known: {list(SCENARIOS)}"
        )
    results: Dict[str, Dict[str, Any]] = {}
    for name in names:
        chaos.clear()
        scenario_dir = os.path.join(workdir, name)
        os.makedirs(scenario_dir, exist_ok=True)
        try:
            results[name] = _SCENARIO_FNS[name](scenario_dir)
        except Exception as e:  # a scenario crash is a FAIL, not a crash
            results[name] = {
                "passed": False,
                "error": f"{type(e).__name__}: {e}",
            }
        finally:
            chaos.clear()
    return {
        "passed": all(r.get("passed") for r in results.values()),
        "scenarios": results,
        "workdir": workdir,
    }


def format_smoke_text(summary: Dict[str, Any]) -> str:
    lines = []
    for name, result in summary["scenarios"].items():
        status = "PASS" if result.get("passed") else "FAIL"
        detail = ", ".join(
            f"{k}={v}" for k, v in result.items() if k != "passed"
        )
        lines.append(f"{status}  {name}: {detail}")
    lines.append(
        "async-smoke: " + ("PASS" if summary["passed"] else "FAIL")
    )
    return "\n".join(lines)
