"""Rule registry for the static-analysis pass.

Every enforceable invariant is a registered :class:`Rule` with a stable id
(the id is what ``# tpu-lint: disable=<id>`` names). Engines look their
rules up here so the CLI can list, select, and document them uniformly;
adding a rule means registering it and implementing its check in the
owning engine (see docs/static_analysis.md, "Adding a rule").
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List

from trlx_tpu.analysis.findings import SEVERITY_ERROR, SEVERITY_WARNING

ENGINE_JAXPR = "jaxpr"
ENGINE_AST = "ast"
ENGINE_NANFLOW = "nanflow"
ENGINE_COLLECTIVE = "collective"
ENGINE_SANITIZER = "sanitizer"
ENGINE_RESOURCE = "resource"
ENGINE_DONATION = "donation"
ENGINE_COMPILE = "compile"
ENGINE_PRNG = "prng"
ENGINE_PERF = "perf"
ENGINE_LOCKSTEP = "lockstep"
ENGINE_HLO = "hlo"
ENGINE_CONCURRENCY = "concurrency"
ENGINE_STATE = "state"


@dataclass(frozen=True)
class Rule:
    id: str
    engine: str
    description: str
    severity: str = SEVERITY_ERROR
    rationale: str = ""


_RULES: Dict[str, Rule] = {}


def register_rule(rule: Rule) -> Rule:
    if rule.id in _RULES:
        raise ValueError(f"duplicate rule id {rule.id!r}")
    _RULES[rule.id] = rule
    return rule


def get_rule(rule_id: str) -> Rule:
    if rule_id not in _RULES:
        raise KeyError(
            f"unknown rule {rule_id!r}; registered: {sorted(_RULES)}"
        )
    return _RULES[rule_id]


def all_rules(engine: str = "") -> List[Rule]:
    rules = sorted(_RULES.values(), key=lambda r: (r.engine, r.id))
    if engine:
        rules = [r for r in rules if r.engine == engine]
    return rules


# --------------------------- jaxpr-audit rules --------------------------- #

register_rule(Rule(
    "fp64",
    ENGINE_JAXPR,
    "no float64 value anywhere in a traced program",
    SEVERITY_ERROR,
    "TPUs have no f64 units; an f64 leaf silently doubles memory and "
    "falls back to slow emulation (the reference's torch code never "
    "promotes, so any f64 here is an accident).",
))
register_rule(Rule(
    "collective-axis",
    ENGINE_JAXPR,
    "every collective (psum/all_gather/ppermute/reduce_scatter/...) names "
    "an axis of the trainer mesh",
    SEVERITY_ERROR,
    "A collective over an unknown axis either fails at compile on the "
    "real slice topology or — worse — silently reduces over nothing.",
))
register_rule(Rule(
    "donation",
    ENGINE_JAXPR,
    "train steps donate their input state buffers",
    SEVERITY_ERROR,
    "Without donation the optimizer state + params are double-buffered "
    "through every update — the difference between fitting and OOM at "
    "the 20B stretch shapes.",
))
register_rule(Rule(
    "precision-leak",
    ENGINE_JAXPR,
    "no unexpected bf16->f32 convert of an activation-rank tensor inside "
    "the compute-dtype forward (loss/optimizer reductions are allow-listed)",
    SEVERITY_WARNING,
    "A stray f32 upcast of a [B, T, D] tensor doubles that tensor's HBM "
    "traffic and defeats the bf16 compute contract (PAPER.md: policy "
    "loaded in bfloat16).",
))
register_rule(Rule(
    "partition-spec",
    ENGINE_JAXPR,
    "every PartitionSpec produced by a family's partition rules is valid "
    "on the mesh (axis exists, dim divisible)",
    SEVERITY_ERROR,
    "An invalid spec either crashes at jit time on the real topology or "
    "silently replicates a tensor that was meant to shard.",
))

# --------------------------- NaN-dataflow rules -------------------------- #

register_rule(Rule(
    "nan-unguarded",
    ENGINE_NANFLOW,
    "every op that can mint a NaN/Inf (div, log, rsqrt, sqrt, exp "
    "overflow, fractional pow) has its operand dominated by a guard "
    "(+eps, clip/maximum, where on the input)",
    SEVERITY_ERROR,
    "The fsdp/tp PPO divergence is exactly this class: one unguarded "
    "op (unclipped exp(log_ratio), eps-free rsqrt) mints the first "
    "NaN and the optimizer propagates it everywhere within a step.",
))
register_rule(Rule(
    "where-grad-trap",
    ENGINE_NANFLOW,
    "no unguarded non-total op whose output is masked by where/select — "
    "the backward pass evaluates it on masked lanes anyway",
    SEVERITY_ERROR,
    "grad(where(mask, f(x), 0)) evaluates f'(x) on every lane and "
    "multiplies inf by the zero cotangent: 0*inf = NaN gradients while "
    "the forward value looks fine. The guard must sit on f's input.",
))
register_rule(Rule(
    "inf-mask-softmax",
    ENGINE_NANFLOW,
    "no softmax denominator built from a -inf-masked input without a "
    "row-liveness guarantee",
    SEVERITY_WARNING,
    "where(mask, s, -inf) into softmax divides 0/0 on a fully-masked "
    "row. Causal self-attention rows always see themselves; anything "
    "else (padding-only rows, cross-attention) needs a re-select.",
))

# ------------------------ collective-sequence rules ----------------------- #

register_rule(Rule(
    "collective-divergence",
    ENGINE_COLLECTIVE,
    "a trainer's linearized collective sequence (psum/all_gather/"
    "reduce_scatter/ppermute + axes) is identical across the mesh "
    "matrix up to axis renaming",
    SEVERITY_ERROR,
    "Distributed RLHF correctness hinges on all workers executing the "
    "same collective schedule (LlamaRL): a topology-dependent psum "
    "order deadlocks or silently mismatches reductions on the slice.",
))
register_rule(Rule(
    "host-branch",
    ENGINE_AST,
    "no host Python branch on device-derived values (float(x) of a "
    "fetched stat, step_stats[...]) in multi-host trainer loop code",
    SEVERITY_WARNING,
    "A branch on a per-host value can take different arms on "
    "different hosts; the next collective then hangs or reduces "
    "mismatched programs. Branch on config/step counters, or "
    "all-gather the scalar first.",
))

# ----------------------------- sanitizer rule ----------------------------- #

register_rule(Rule(
    "sanitizer-nonfinite",
    ENGINE_SANITIZER,
    "eqn-level replay of a captured step jaxpr finds no equation whose "
    "output is the program's first NaN/Inf",
    SEVERITY_ERROR,
    "Replaying the step eqn-by-eqn turns 'PPO diverges on fsdp/tp' "
    "into 'this equation, this source line, this param path minted "
    "the first NaN' — a one-command localization instead of printf.",
))

# --------------------------- resource-audit rules ------------------------ #

register_rule(Rule(
    "hbm-over-budget",
    ENGINE_RESOURCE,
    "a traced program's statically-computed peak live HBM (per device, "
    "sharding- and donation-aware) stays within its committed budget in "
    "analysis/budgets.json (+ tolerance)",
    SEVERITY_ERROR,
    "Memory regressions today surface as OOMs on real hardware (LlamaRL "
    "makes per-component memory budgets a first-class design input). The "
    "lockfile turns every peak-HBM change into a reviewable diff: grow "
    "the budget deliberately with --update-budgets, never by accident.",
))
register_rule(Rule(
    "collective-bytes-regression",
    ENGINE_RESOURCE,
    "a traced program's modeled collective traffic (bytes moved per "
    "device across psum/all_gather/ppermute/all_to_all, attributed to "
    "mesh axes) stays within its committed budget in analysis/budgets.json",
    SEVERITY_ERROR,
    "Interconnect bytes are the scaling ceiling for multi-slice RLHF: an "
    "accidental extra all_gather costs nothing on the CPU test mesh and "
    "everything on a real slice. Regressions must be explained in the "
    "budget-lockfile diff.",
))

# ----------------------------- donation rules ---------------------------- #

register_rule(Rule(
    "use-after-donate",
    ENGINE_DONATION,
    "host code never reads a pytree after passing it to a donating jitted "
    "step without rebinding the result first",
    SEVERITY_ERROR,
    "A donated buffer is freed/aliased by XLA the moment the step is "
    "dispatched; the host-side reference silently reads garbage (or "
    "crashes) — the exact hazard class PR 3's snapshot logic hit, caught "
    "then only by hand-audit.",
))
register_rule(Rule(
    "donation-ignored",
    ENGINE_DONATION,
    "every donated input buffer has a same-shape/dtype output that can "
    "actually reuse it",
    SEVERITY_WARNING,
    "A donated buffer XLA cannot reuse (no shape/dtype-matching output) "
    "is silent memory waste the runtime only warns about on real "
    "hardware — the donation promise is a lie and peak HBM is higher "
    "than the step's budget claims.",
))
register_rule(Rule(
    "alias-escape",
    ENGINE_DONATION,
    "no traced program returns a non-donated input leaf unchanged — the "
    "output would alias the caller's buffer instead of owning fresh "
    "memory",
    SEVERITY_ERROR,
    "pjit input-forwarding aliases the returned array onto the input "
    "buffer; if any later program donates that buffer, every holder of "
    "the forwarded output reads reused memory (the PR-3 behavior-"
    "snapshot hazard: copy per leaf, or donate explicitly).",
))

# ------------------------- compile-stability rules ----------------------- #

register_rule(Rule(
    "unexpected-retrace",
    ENGINE_COMPILE,
    "no jitted callable recompiles on a steady-state repeat call of the "
    "trainer's canonical loop (same logical step, stable shapes)",
    SEVERITY_ERROR,
    "Silent recompilation is the dominant un-instrumented TPU perf "
    "killer: one shape-varying call site recompiles the whole train "
    "step mid-run (~minutes at real shapes) and nothing in the loss "
    "curves shows it. The finding ships the jaxpr drift — the first "
    "divergent equation (shape / dtype / weak_type / static-arg) — so "
    "the cause lands in the report, not just the count.",
))
register_rule(Rule(
    "compile-count-regression",
    ENGINE_COMPILE,
    "per-callable compile counts over the canonical short loop stay "
    "within the committed compile_budgets entries in "
    "analysis/budgets.json",
    SEVERITY_ERROR,
    "The compile-count lockfile turns every new compile into a "
    "reviewable diff: grow a budget deliberately with "
    "--compile-audit --update-budgets, never by accident. A count "
    "regression on the CPU audit mesh is minutes of XLA time at the "
    "real shapes.",
))
register_rule(Rule(
    "retrace-risk",
    ENGINE_COMPILE,
    "no jitted call site in an untraced trainer/orchestrator loop is fed "
    "a per-step-varying host scalar (len()/.item()/int() of device "
    "values) or a non-literal static argument",
    SEVERITY_WARNING,
    "A Python scalar derived from len()/.item()/int() re-hashes the jit "
    "cache key every time its value changes: the call site compiles per "
    "distinct value, and the retrace harness only catches the ones the "
    "canonical loop happens to exercise. Pass device arrays, or keep "
    "host scalars step-invariant.",
))

# --------------------------- PRNG-lineage rules -------------------------- #

register_rule(Rule(
    "key-reuse",
    ENGINE_PRNG,
    "no PRNG key is consumed by more than one random primitive "
    "(draw/split/fold_in) — every reuse must go through a fresh "
    "split/fold_in derivation",
    SEVERITY_ERROR,
    "Key reuse silently correlates samples: two rollouts drawn from one "
    "key explore identical trajectories and PPO's gradient variance "
    "estimates are wrong with no visible symptom in loss curves — the "
    "failure mode RLHF pipelines are least likely to catch.",
))
register_rule(Rule(
    "key-discard",
    ENGINE_PRNG,
    "every jax.random.split advances its chain: the result is consumed "
    "and the source chain variable (self.rng) is rebound",
    SEVERITY_WARNING,
    "A split whose output is dropped (or whose source chain is not "
    "rebound) repeats the same subkeys at the next call — delayed key "
    "reuse. `_, key = split(self.rng)` is the classic spelling: every "
    "subsequent call re-derives the identical key.",
))
register_rule(Rule(
    "fixed-seed",
    ENGINE_PRNG,
    "no literal seed reaches training-path randomness outside tests "
    "(PRNGKey(0)/key(42)/default_rng(7) in trainer/pipeline/orchestrator "
    "code must come from config)",
    SEVERITY_WARNING,
    "A hard-coded seed pins every run of a sampling path to one "
    "trajectory set: sweeps silently share rollouts, and restarts "
    "replay the same 'random' experience. Seeds belong to "
    "train.seed/config so runs are reproducible on purpose.",
))

# -------------------------- measured-perf rules -------------------------- #

register_rule(Rule(
    "perf-regression",
    ENGINE_PERF,
    "measured per-span wall-clock (p50 over the instrumented phase loop) "
    "stays within the committed perf_budgets section of "
    "analysis/budgets.json (+ per-span tolerance)",
    SEVERITY_ERROR,
    "Faithful throughput drifted 167 -> 162 samples/s/chip across five "
    "bench rounds and only a manual diff caught it: nothing gated "
    "*measured* time. The span lockfile turns wall-clock drift into a "
    "failing job — relock deliberately with --perf-audit "
    "--update-budgets, never by accident.",
))

# ------------------------ multi-controller lockstep ---------------------- #

register_rule(Rule(
    "lockstep-divergence",
    ENGINE_LOCKSTEP,
    "N simulated controller processes running a trainer's canonical host "
    "loop dispatch the SAME jitted/collective-bearing programs in the "
    "same order with the same arg signatures and collective schedules",
    SEVERITY_ERROR,
    "In multi-controller JAX every host drives its own Python loop; a "
    "dispatch present on one host and absent (or different) on another "
    "— a rank-0-gated jit call, a host-local branch — leaves the other "
    "hosts blocked inside the program's first collective forever. The "
    "simulator catches the deadlock before any multi-host hardware "
    "exists, localized to the first diverging ordinal and call site.",
))
register_rule(Rule(
    "dispatch-sequence-drift",
    ENGINE_LOCKSTEP,
    "a trainer's host-0 dispatch-sequence fingerprint over the canonical "
    "loop matches the committed lockstep_budgets section of "
    "analysis/budgets.json",
    SEVERITY_ERROR,
    "The dispatch schedule is the multi-host contract: reordering it, "
    "adding a program, or changing a shape signature silently changes "
    "what every direction-1 component (launcher, per-host restart, "
    "cross-slice push) must replay identically. The lockfile turns "
    "every schedule change into a reviewable diff — relock with "
    "--lockstep --update-budgets, never by accident.",
))

# -------------------- compiled-HLO audit (engine 13) --------------------- #

register_rule(Rule(
    "lowering-collective-drift",
    ENGINE_HLO,
    "the collectives XLA actually emitted for a program (optimized "
    "post-SPMD HLO) match jaxpr intent and the committed hlo_budgets "
    "profile: no concat-minted replica-axis all-reduce, no dropped "
    "explicit collective, no inserted/dropped/re-axised profile key",
    SEVERITY_ERROR,
    "The jaxpr is intent; the compiled module is what the TPU runs. "
    "Both of this repo's worst correctness bugs were XLA's SPMD "
    "partitioner rewriting collectives below the jaxpr (the PR-2 "
    "sharded-concat replica-SUM, the quarantined pp cached-decode "
    "stack) — drift at this layer is invisible to every jaxpr-level "
    "engine and NaNs the run at scale.",
))
register_rule(Rule(
    "hlo-dtype-upcast",
    ENGINE_HLO,
    "no non-scalar f32 tensor minted from bf16 inputs by the optimized "
    "module outside the softmax/layernorm/loss accumulation allowlist",
    SEVERITY_WARNING,
    "XLA may legally widen compute during optimization; an activation-"
    "rank f32 tensor the source never wrote doubles HBM traffic and "
    "defeats the bf16 compute contract (PAPER.md: policy in bfloat16) "
    "— and the jaxpr-level precision-leak rule cannot see compiler-"
    "minted converts.",
))
register_rule(Rule(
    "hlo-memory-drift",
    ENGINE_HLO,
    "each program's compiled buffer-assignment peak (temp + args + "
    "outputs - donation aliasing) stays within tolerance of the "
    "committed hlo_budgets entry",
    SEVERITY_ERROR,
    "Engine 7's static peak is a model; XLA's buffer assignment is the "
    "allocation the device makes. A fusion or layout change can "
    "regress real live memory while the static number holds — the "
    "lockfile turns that silent regression into a reviewable diff.",
))
register_rule(Rule(
    "spmd-concat-hazard",
    ENGINE_HLO,
    "no eager multi-operand concatenate of committed-sharded operands "
    "on a multi-device mesh outside the blessed spmd_stack/concat_cols "
    "helpers",
    SEVERITY_ERROR,
    "XLA's SPMD partitioner has twice mis-lowered exactly this shape "
    "into a replica-axis SUM (PR 2; the quarantined pp cached-decode "
    "stack). The dynamic_update_slice spelling in the blessed helpers "
    "is the sanctioned route — this rule automates the ROADMAP 'watch "
    "for new eager concat/stack' human obligation.",
))

# -------------------- host-concurrency lint (engine 12) ------------------- #

register_rule(Rule(
    "rank-gated-dispatch",
    ENGINE_AST,
    "no jitted or collective-bearing call is reachable only under a "
    "process_index()/is_main_process rank gate in host-loop code",
    SEVERITY_ERROR,
    "A dispatch inside `if is_main_process():` runs a collective-bearing "
    "program on host 0 only; the other hosts never enter it and the "
    "collective blocks until the job is killed. Rank-gate host I/O "
    "(logging, checkpoint writes), never device dispatch.",
))
register_rule(Rule(
    "nondet-host-order",
    ENGINE_AST,
    "no iteration over set()/un-sorted os.listdir()/glob feeds a jitted "
    "or collective-bearing call in host-loop code",
    SEVERITY_ERROR,
    "set/listdir/glob order is process-local: two hosts walking the "
    "same logical collection dispatch the same programs in DIFFERENT "
    "orders, and order is exactly what multi-controller lockstep "
    "requires. Wrap the iterable in sorted(...).",
))
register_rule(Rule(
    "host-time-in-dispatch",
    ENGINE_AST,
    "no wall-clock (time.time/monotonic/datetime.now) or host random "
    "value steers a branch that guards a jitted or collective-bearing "
    "call in host-loop code",
    SEVERITY_WARNING,
    "Host clocks and host RNG are per-process: a deadline or sampled "
    "branch flips arms at different moments on different hosts, so one "
    "host dispatches a program its peers skip — the next collective "
    "hangs. Derive the decision from step counters or broadcast it "
    "from rank 0 (distributed.broadcast_host_value).",
))
register_rule(Rule(
    "unsynced-host-io",
    ENGINE_AST,
    "no value read from a per-host file (open/read/np.load/json.load) "
    "feeds a jitted or collective-bearing call's arguments in host-loop "
    "code",
    SEVERITY_WARNING,
    "Per-host reads of 'the same' file can observe different snapshots "
    "(checkpoint-in-progress, node-local cache); a shape or value "
    "difference re-hashes the jit cache key or mismatches the "
    "collective's operands across hosts. Read on rank 0 and broadcast, "
    "or route through the checkpoint layer's synchronized restore.",
))

# ------------------- host-concurrency races (engine 14) ------------------ #

register_rule(Rule(
    "unguarded-shared-write",
    ENGINE_CONCURRENCY,
    "every attribute mutated from two or more thread roots is guarded by "
    "a common lock on every mutation path (or the owning class carries a "
    "written single-thread contract)",
    SEVERITY_ERROR,
    "The host side is concurrent now — writer thread, drive loop, "
    "weight-push caller, stream pump, signal handlers — and a shared "
    "counter or reference mutated from two roots without one lock is a "
    "data race: torn under free-threading, and a lost update even under "
    "the GIL when the mutation is a read-modify-write.",
))
register_rule(Rule(
    "lock-order-cycle",
    ENGINE_CONCURRENCY,
    "the discovered locks are acquired in one consistent global order "
    "(no path acquires A then B while another acquires B then A)",
    SEVERITY_ERROR,
    "Inconsistent acquisition order is the classic ABBA deadlock: each "
    "thread holds one lock and blocks forever on the other. The cycle "
    "only bites under load on real hardware, where it presents as a "
    "hung slice, not a stack trace.",
))
register_rule(Rule(
    "signal-unsafe-handler",
    ENGINE_CONCURRENCY,
    "SIGTERM/SIGINT handlers do nothing beyond async-signal-safe flag "
    "sets (one attribute/global assignment; no I/O, no allocation-heavy "
    "calls, no locks)",
    SEVERITY_ERROR,
    "A Python signal handler runs between arbitrary bytecodes of the "
    "interrupted thread. print() there can deadlock on the stdout "
    "buffer lock the main thread already holds; anything beyond "
    "setting a flag races the drain that the preemption contract says "
    "happens at phase boundaries.",
))
register_rule(Rule(
    "atomicity-split",
    ENGINE_CONCURRENCY,
    "no check-then-act on shared state outside the lock that guards "
    "that state (the check and the act must sit in one critical "
    "section)",
    SEVERITY_WARNING,
    "`if not stream.closed: stream.push(tok)` is two critical sections: "
    "a close between them loses the token even though both halves are "
    "individually locked. TOCTOU on shared state is invisible to "
    "single-schedule tests — every parity pin in the suite runs one "
    "lucky interleaving.",
))
register_rule(Rule(
    "schedule-invariant-violation",
    ENGINE_CONCURRENCY,
    "the repo's claimed concurrency invariants (version-column "
    "monotonicity, no torn stream rows, staleness_window=0 bitwise "
    "parity, zero lost writer rows) hold under every explored "
    "deterministic thread interleaving",
    SEVERITY_ERROR,
    "Static locksets prove guarding, not semantics. The cooperative "
    "scheduler runs the REAL writer/drive/push/pump code under seeded "
    "interleavings and replays the first violating schedule by seed — "
    "a race gate the 13 jaxpr/HLO-level engines cannot provide.",
))

# ---------------- checkpoint/resume state coverage (engine 15) ----------- #

register_rule(Rule(
    "resume-state-gap",
    ENGINE_STATE,
    "every mutable attribute written inside the phase loop on an object "
    "reachable from a trainer is checkpoint-carried, deterministically "
    "reconstructed from config on restore, or explicitly allowlisted "
    "ephemeral with a written justification",
    SEVERITY_ERROR,
    "Kill/resume parity is the repo's fault-tolerance contract (PR 9's "
    "supervisor + emergency checkpoints), but host state grew past the "
    "save() metadata: an accept-EWMA, token-bucket level, or RNG key "
    "that feeds the sampling schedule and is silently reset on restore "
    "makes a resumed run diverge from the uninterrupted one — exactly "
    "the failure the parity canaries were written to forbid.",
))
register_rule(Rule(
    "stale-state-contract",
    ENGINE_STATE,
    "every ephemeral-allowlist entry and state-manifest key names an "
    "attribute that still exists in the code",
    SEVERITY_WARNING,
    "A contract naming a dead attribute is worse than no contract: the "
    "attribute was renamed or removed, the justification no longer "
    "covers anything, and the next writer inherits a green audit that "
    "is vacuously true. Stale entries must be pruned or renamed so the "
    "allowlist stays a live inventory, not a fossil record.",
))
register_rule(Rule(
    "ckpt-schema-drift",
    ENGINE_STATE,
    "each trainer's checkpoint key-set and per-leaf shape/dtype "
    "fingerprint matches the locked state_manifest section of "
    "analysis/budgets.json",
    SEVERITY_ERROR,
    "A key that vanishes from the save pytree is a resume gap the "
    "static classifier cannot see (the state_dict method still "
    "exists), and a shape/dtype change breaks restore of every "
    "checkpoint already on disk. Locking the schema makes either "
    "drift a reviewed, additive relock instead of a silent break.",
))
register_rule(Rule(
    "resume-divergence",
    ENGINE_STATE,
    "after checkpoint -> rebuild -> restore, one more phase of the "
    "resumed trainer leaves every live host attribute bitwise equal to "
    "an uninterrupted twin's (outside the allowlisted ephemeral set)",
    SEVERITY_ERROR,
    "The dynamic half of the contract: static classification proves an "
    "attribute is carried, only the differ proves it is carried "
    "*correctly* (right tensor, right dtype, restored before first "
    "use). Any diverging attribute path is a real parity break that "
    "the params-only canaries would miss.",
))

# ---------------------------- AST-lint rules ----------------------------- #

register_rule(Rule(
    "host-item",
    ENGINE_AST,
    "no .item() inside jit-decorated/traced functions",
    SEVERITY_ERROR,
    ".item() blocks on a device->host transfer; inside traced code it "
    "either fails to trace or forces a sync per call (~100ms on a "
    "tunneled chip).",
))
register_rule(Rule(
    "host-scalar-cast",
    ENGINE_AST,
    "no float()/int() of a non-literal inside traced functions",
    SEVERITY_ERROR,
    "float(x) on a tracer is a ConcretizationTypeError at best and a "
    "hidden host sync at worst; use x.astype(...) / jnp casts.",
))
register_rule(Rule(
    "host-transfer",
    ENGINE_AST,
    "no jax.device_get / np.asarray / np.array inside traced functions",
    SEVERITY_ERROR,
    "Explicit host transfers inside traced code serialize the step "
    "pipeline (OPPO in PAPERS.md: overlap wins evaporate under hidden "
    "host syncs).",
))
register_rule(Rule(
    "py-random",
    ENGINE_AST,
    "no Python random module inside traced functions",
    SEVERITY_ERROR,
    "Host RNG inside traced code bakes one sample into the compiled "
    "program — every execution replays the same 'random' number; use "
    "jax.random with explicit keys.",
))
register_rule(Rule(
    "np-in-ops",
    ENGINE_AST,
    "ops/ kernels use jnp, not np, inside any function",
    SEVERITY_ERROR,
    "ops/ modules are kernel code whose functions run under trace; "
    "np.* on a tracer escapes to host or fails. Module-level np "
    "constants are fine.",
))
