"""Jaxpr audit: dtype / collective / donation / precision invariants.

Engine 1 of ``trlx_tpu.analysis``. The TPU port's core invariants are
*visible in jaxprs*: the trainers' step and rollout programs are traced
abstractly (``jax.make_jaxpr`` on the jitted callables, CPU mesh, tiny
configs — see ``harness.py``) and the closed jaxpr is walked recursively
through every sub-jaxpr (pjit / shard_map / scan / cond / custom_*):

- ``fp64``: no float64 aval anywhere.
- ``collective-axis``: every named collective (``psum``/``all_gather``/
  ``ppermute``/``reduce_scatter``/...) references an axis of the trainer
  mesh (``parallel/mesh.py`` constants).
- ``donation``: the train-step pjit donates all of its state buffers.
- ``precision-leak``: no bf16/f16 -> f32 ``convert_element_type`` of an
  activation-rank (ndim >= 3) tensor whose source is repo forward code;
  loss/optimizer reduction sites are allow-listed
  (:data:`PRECISION_ALLOWLIST`).
- ``partition-spec``: every registered model family's partition rules
  produce mesh-valid specs for its param tree (axis exists, dim
  divisible) — via ``parallel/partition.py``'s registration-time
  validation.

Rule functions take explicit inputs (jaxpr, axis names, ...) so golden
tests can seed violations without building trainers.
"""

from __future__ import annotations

import os
from typing import Any, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

from trlx_tpu.analysis.findings import Finding
from trlx_tpu.analysis.registry import get_rule

# Primitives that reference a named mesh axis. (psum lowers as psum2 in
# recent JAX; keep both spellings.)
COLLECTIVE_PRIMS = {
    "psum", "psum2", "pmax", "pmin", "ppermute", "pbroadcast",
    "all_gather", "all_to_all", "reduce_scatter", "axis_index",
    "psum_invariant",
}

# (file suffix, function name) pairs allowed to upcast bf16 activations to
# f32: loss math, logprob/entropy reductions, optimizer moment math. A None
# function matches the whole file. Extend here (with a comment saying why)
# rather than sprinkling inline suppressions over kernel code.
PRECISION_ALLOWLIST: Sequence[Tuple[str, Optional[str]]] = (
    ("ops/ppo_math.py", None),  # loss + GAE math is f32 by contract
    ("ops/ilql_math.py", None),  # loss math is f32 by contract
    ("parallel/collectives.py", None),  # whitening/logprob reductions
    ("trainer/common.py", None),  # optimizer moment upcasts
    ("", "_policy_entropy"),  # entropy reduction consumes f32 logits
    ("", "chunk_logprobs"),  # chunked CE upcasts one logits chunk at a time
    # f32 softmax accumulation: attention logits/weights compute in f32
    # (preferred_element_type) and cast back — numerics by design
    ("ops/attention.py", "dot_product_attention"),
    ("ops/flash_attention.py", None),  # same f32-accumulation contract
    ("ops/ring_attention.py", None),  # same f32-accumulation contract
    # T5 consumes f32 directly by parity contract: RMSNorm accumulates
    # f32, rel-pos bias feeds attention at f32, logits are f32 (the
    # seq2seq trainer refuses rollout_param_cast for exactly this)
    ("models/t5.py", None),
    # MLPHead fc2 computes in f32 (value clipping is sensitive to bf16
    # rounding; see utils.ROLLOUT_CAST_EXCLUDE)
    ("models/heads.py", "__call__"),
    # flax nn.LayerNorm accumulates its moments in f32 and casts back
    # (standard stable-norm numerics); flax registers its frames for
    # traceback exclusion, so the converts attribute to the repo call line
    ("models/gpt2.py", "__call__"),
    # AD transpose of the embed tables' compute-dtype downcast: the bf16
    # cotangent upcasts to f32 so gradients accumulate in the param dtype
    ("models/gpt2.py", "embed"),
)


def _sub_jaxprs(eqn) -> Iterator[Any]:
    for value in eqn.params.values():
        candidates = value if isinstance(value, (list, tuple)) else (value,)
        for v in candidates:
            if hasattr(v, "eqns"):
                yield v
            elif hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):
                yield v.jaxpr


def iter_eqns(jaxpr) -> Iterator[Any]:
    """All equations of a (closed) jaxpr, recursing into sub-jaxprs."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in inner.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn):
            yield from iter_eqns(sub)


def _repo_frame(eqn, repo_root: str, innermost_only: bool = False):
    """A traceback frame pointing into this repo, or None.

    ``innermost_only`` returns a frame only when the *innermost* user
    frame is repo code — i.e. the repo source itself wrote the op. A
    convert emitted inside flax/optax (e.g. LayerNorm's f32 accumulation)
    has a library file as its innermost frame even though repo lines sit
    above it in the stack; those libraries own their numerics.
    """
    source_info = getattr(eqn, "source_info", None)
    if source_info is None:
        return None
    try:
        from jax._src import source_info_util

        for frame in source_info_util.user_frames(source_info):
            if repo_root in frame.file_name:
                return frame
            if innermost_only:
                return None
    except Exception:
        return None
    return None


def _loc(eqn, repo_root: str) -> Tuple[Optional[str], Optional[int]]:
    frame = _repo_frame(eqn, repo_root)
    if frame is None:
        return None, None
    return frame.file_name, frame.start_line


def default_repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------ fp64 rule ------------------------------- #

def check_no_fp64(jaxpr, subject: str, repo_root: Optional[str] = None) -> List[Finding]:
    import numpy as np

    repo_root = repo_root or default_repo_root()
    rule = get_rule("fp64")
    findings: List[Finding] = []
    for eqn in iter_eqns(jaxpr):
        for var in list(eqn.outvars) + list(eqn.invars):
            aval = getattr(var, "aval", None)
            dtype = getattr(aval, "dtype", None)
            if dtype is not None and dtype == np.float64:
                file, line = _loc(eqn, repo_root)
                findings.append(
                    Finding(
                        rule=rule.id,
                        message=f"float64 value in `{eqn.primitive.name}` "
                        f"(shape {getattr(aval, 'shape', '?')}) — TPUs "
                        "have no f64 units",
                        severity=rule.severity,
                        file=file,
                        line=line,
                        subject=subject,
                        engine="jaxpr",
                    )
                )
                break  # one finding per eqn is enough
    return findings


# -------------------------- collective-axis rule ------------------------ #

def _axis_names_of(eqn) -> Iterable[str]:
    for key in ("axes", "axis_name", "axis"):
        if key in eqn.params:
            value = eqn.params[key]
            names = value if isinstance(value, (list, tuple)) else (value,)
            for n in names:
                if isinstance(n, str):
                    yield n
            return


def check_collective_axes(
    jaxpr, mesh_axes: Set[str], subject: str, repo_root: Optional[str] = None
) -> List[Finding]:
    repo_root = repo_root or default_repo_root()
    rule = get_rule("collective-axis")
    findings: List[Finding] = []
    for eqn in iter_eqns(jaxpr):
        if eqn.primitive.name not in COLLECTIVE_PRIMS:
            continue
        for axis in _axis_names_of(eqn):
            if axis not in mesh_axes:
                file, line = _loc(eqn, repo_root)
                findings.append(
                    Finding(
                        rule=rule.id,
                        message=f"collective `{eqn.primitive.name}` names "
                        f"axis {axis!r}, not a mesh axis "
                        f"({sorted(mesh_axes)})",
                        severity=rule.severity,
                        file=file,
                        line=line,
                        subject=subject,
                        engine="jaxpr",
                    )
                )
    return findings


# ----------------------------- donation rule ---------------------------- #

def check_donation(
    closed_jaxpr, n_state_leaves: int, subject: str
) -> List[Finding]:
    """The traced callable's outer pjit must donate its first
    ``n_state_leaves`` flat inputs (the train-state buffers)."""
    rule = get_rule("donation")
    inner = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    pjit_eqns = [e for e in inner.eqns if e.primitive.name == "pjit"]
    if not pjit_eqns:
        return [
            Finding(
                rule=rule.id,
                message="no pjit equation found — the step function is "
                "not jitted at all",
                severity=rule.severity,
                subject=subject,
                engine="jaxpr",
            )
        ]
    eqn = pjit_eqns[0]
    donated = eqn.params.get("donated_invars", ())
    missing = [
        i for i in range(min(n_state_leaves, len(donated))) if not donated[i]
    ]
    if len(donated) < n_state_leaves or missing:
        return [
            Finding(
                rule=rule.id,
                message=f"train step donates "
                f"{sum(bool(d) for d in donated)} of {n_state_leaves} "
                f"state buffers (first undonated flat index: "
                f"{missing[0] if missing else len(donated)}) — pass "
                "donate_argnums for the state argument",
                severity=rule.severity,
                subject=subject,
                engine="jaxpr",
            )
        ]
    return []


# -------------------------- precision-leak rule ------------------------- #

def check_precision_leak(
    jaxpr,
    subject: str,
    repo_root: Optional[str] = None,
    allowlist: Sequence[Tuple[str, Optional[str]]] = PRECISION_ALLOWLIST,
    min_rank: int = 3,
) -> List[Finding]:
    """bf16/f16 -> f32 converts of activation-rank tensors traced from repo
    forward code. Converts with no repo frame (jax/optax internals) and
    allow-listed sites are fine; everything else is a leak report."""
    import numpy as np

    repo_root = repo_root or default_repo_root()
    rule = get_rule("precision-leak")
    findings: List[Finding] = []
    for eqn in iter_eqns(jaxpr):
        if eqn.primitive.name != "convert_element_type":
            continue
        new_dtype = eqn.params.get("new_dtype")
        if new_dtype is None or np.dtype(new_dtype) != np.float32:
            continue
        aval = getattr(eqn.invars[0], "aval", None)
        src_dtype = getattr(aval, "dtype", None)
        if src_dtype is None or str(src_dtype) not in ("bfloat16", "float16"):
            continue
        if len(getattr(aval, "shape", ())) < min_rank:
            continue
        frame = _repo_frame(eqn, repo_root, innermost_only=True)
        if frame is None:
            continue  # jax/flax/optax internals own their precision story
        rel = frame.file_name
        if repo_root in rel:
            rel = rel.split(repo_root, 1)[1].lstrip(os.sep)
        allowed = False
        for file_suffix, func in allowlist:
            if file_suffix and not rel.endswith(file_suffix):
                continue
            if func is not None and frame.function_name != func:
                continue
            allowed = True
            break
        if allowed:
            continue
        findings.append(
            Finding(
                rule=rule.id,
                message=f"{src_dtype}->f32 upcast of a rank-"
                f"{len(aval.shape)} tensor (shape {aval.shape}) in "
                f"`{frame.function_name}` — doubles its HBM traffic; "
                "allow-list the site if the upcast is a loss/optimizer "
                "reduction",
                severity=rule.severity,
                file=frame.file_name,
                line=frame.start_line,
                subject=subject,
                engine="jaxpr",
            )
        )
    return findings


# -------------------------- partition-spec rule ------------------------- #

# (family name, tiny arch overrides) — small dims chosen divisible by the
# audit mesh (tp=2 when >= 4 devices) so the check exercises rule matching,
# not toy-shape artifacts.
FAMILY_TINY_ARCH = {
    "gpt2": {
        "vocab_size": 32, "n_positions": 16, "n_embd": 32, "n_layer": 2,
        "n_head": 2,
    },
    "gptj": {
        "vocab_size": 32, "n_positions": 16, "n_embd": 32, "n_layer": 2,
        "n_head": 2, "rotary_dim": 8,
    },
    "gpt_neo": {
        "vocab_size": 32, "max_position_embeddings": 16, "hidden_size": 32,
        "num_layers": 2, "num_heads": 2,
        "attention_layers": ["global", "local"],
    },
    "gpt_neox": {
        "vocab_size": 32, "max_position_embeddings": 16, "hidden_size": 32,
        "num_hidden_layers": 2, "num_attention_heads": 2,
    },
    "t5": {
        "vocab_size": 32, "d_model": 32, "d_kv": 8, "d_ff": 64,
        "num_layers": 2, "num_decoder_layers": 2, "num_heads": 4,
        "relative_attention_num_buckets": 8,
        "relative_attention_max_distance": 16,
        "feed_forward_proj": "gated-gelu", "tie_word_embeddings": False,
    },
    "gpt2_moe": {
        "vocab_size": 32, "n_positions": 16, "n_embd": 32, "n_layer": 2,
        "n_head": 2, "n_experts": 2,
    },
}


def check_partition_specs(
    mesh, families: Optional[Sequence[str]] = None
) -> Tuple[List[Finding], List[str]]:
    """Validate every registered family's partition rules against ``mesh``
    for a representative param tree; returns (findings, covered subjects)."""
    import jax
    import jax.numpy as jnp

    from trlx_tpu.models.registry import get_model_family
    from trlx_tpu.parallel.partition import (
        PartitionRuleError,
        make_partition_specs,
    )

    rule = get_rule("partition-spec")
    findings: List[Finding] = []
    covered: List[str] = []
    for name in families or sorted(FAMILY_TINY_ARCH):
        family = get_model_family(name)
        arch = family.config_cls.from_dict(dict(FAMILY_TINY_ARCH[name]))
        module = family.backbone_cls(arch)
        if family.is_seq2seq:
            shapes = jax.eval_shape(
                lambda m=module: m.init(
                    jax.random.PRNGKey(0),
                    jnp.zeros((1, 8), jnp.int32),
                    decoder_input_ids=jnp.zeros((1, 2), jnp.int32),
                )
            )["params"]
        else:
            shapes = jax.eval_shape(
                lambda m=module: m.init(
                    jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
                )
            )["params"]
        subject = f"partition:{name}"
        covered.append(subject)
        try:
            make_partition_specs(
                shapes, mesh, family.partition_rules, validate=True
            )
        except PartitionRuleError as e:
            findings.append(
                Finding(
                    rule=rule.id,
                    message=str(e),
                    severity=rule.severity,
                    subject=subject,
                    engine="jaxpr",
                )
            )
    return findings, covered


# ------------------------------ orchestration --------------------------- #

def audit_program(
    closed_jaxpr,
    subject: str,
    mesh_axes: Set[str],
    n_donated_state_leaves: Optional[int] = None,
    repo_root: Optional[str] = None,
) -> List[Finding]:
    """Run every per-program jaxpr rule on one traced program."""
    findings = []
    findings += check_no_fp64(closed_jaxpr, subject, repo_root)
    findings += check_collective_axes(
        closed_jaxpr, mesh_axes, subject, repo_root
    )
    if n_donated_state_leaves is not None:
        findings += check_donation(
            closed_jaxpr, n_donated_state_leaves, subject
        )
    findings += check_precision_leak(closed_jaxpr, subject, repo_root)
    # one report per (rule, site, program): scan/vmap bodies repeat the
    # same source eqn once per unrolled context
    seen = set()
    unique = []
    for f in findings:
        key = (f.rule, f.file, f.line, f.subject, f.file is None and f.message)
        if key not in seen:
            seen.add(key)
            unique.append(f)
    return unique


def audit_trainers(kinds: Optional[Sequence[str]] = None, programs=None):
    """Trace all trainer programs via the harness and audit them
    (``programs``: pre-traced :class:`~trlx_tpu.analysis.harness.
    TracedProgram` list, so callers running several jaxpr engines trace
    once).

    Returns a :class:`~trlx_tpu.analysis.findings.Report`.
    """
    from trlx_tpu.analysis import harness
    from trlx_tpu.analysis.findings import Report, filter_suppressed

    report = Report()
    mesh_findings: List[Finding] = []
    for traced in programs if programs is not None else harness.trace_all(kinds):
        report.covered.append(traced.subject)
        mesh_findings += audit_program(
            traced.closed_jaxpr,
            traced.subject,
            traced.mesh_axes,
            traced.n_donated_state_leaves,
        )
    spec_findings, spec_covered = check_partition_specs(harness.audit_mesh())
    mesh_findings += spec_findings
    report.covered += spec_covered
    kept, suppressed = filter_suppressed(mesh_findings)
    report.extend(kept)
    report.suppressed += suppressed
    return report
