"""``--health-smoke``: planted-anomaly self-check for the health layer.

The ``--plant-nan`` / ``--plant-slowdown`` pattern, applied to the
run-health detectors (telemetry/health.py): a monitoring layer that
cannot detect a planted anomaly is vacuous exactly when it breaks. The
smoke runs the REAL streamed phase loop twice over one trainer:

1. **clean phases** — the detectors must stay silent (zero events);
2. **planted phases** — the policy's embedding table is scaled by a
   large factor, which sharpens every logit distribution (entropy
   collapses toward 0) and snaps the policy far from the frozen KL
   reference (rollout KL spikes). The ``kl-spike`` and
   ``entropy-collapse`` detectors must both trip on the next phase's
   real fetched stats — no synthetic series are injected anywhere.

The planted run drives the full failure path: the ``on_error: dump``
policy writes a flight-recorder forensics file, which the smoke then
parses and renders through the same ``--inspect`` code path operators
use. PASS requires all four: clean-quiet, both detectors tripped, a
dump on disk, and the dump inspectable.
"""

from __future__ import annotations

import os
import tempfile
from typing import Any, Dict, Optional

#: detectors the planted anomaly must trip for the smoke to pass
REQUIRED_TRIPS = ("kl-spike", "entropy-collapse")


def smoke_config_dict(dump_dir: str) -> Dict[str, Any]:
    """Harness-shape PPO config with health armed: 3 chunks per phase,
    2 ppo_epochs (6 update rows per phase), dump-on-error policy.

    ``warmup: 3``: the kl-spike series (``policy/mean_rollout_kl``) is
    phase-level — observed ONCE per phase — so its z-score rule needs
    ``warmup`` clean *phases* to arm; the per-row series (entropy,
    ratios) warm far faster. The smoke's clean window runs
    ``warmup + 1`` phases so every armed detector has a baseline."""
    from trlx_tpu.analysis import harness

    cfg = harness.tiny_config_dict("ppo")
    cfg["method"].update(num_rollouts=24, chunk_size=8, ppo_epochs=2)
    cfg["train"]["health"] = {
        "enabled": True,
        "on_error": "dump",
        "dump_dir": dump_dir,
        "warmup": 3,
    }
    return cfg


def _poison_embeddings(trainer, factor: float) -> None:
    """Scale the policy's token-embedding table in place on device.

    With a tied LM head, scaling the embedding scales every logit
    ~linearly: softmax sharpens (entropy -> 0) and the sampled policy
    leaps away from the frozen reference (rollout KL explodes) — a
    *real* divergence planted in the params, exercising sampler, ref
    scoring, and update stats end to end."""
    import jax

    from trlx_tpu.trainer.common import TrainState

    params = dict(trainer.state.params)
    backbone = dict(params[trainer.backbone_key])
    backbone["wte"] = jax.tree_util.tree_map(
        lambda x: (x * factor).astype(x.dtype), backbone["wte"]
    )
    params[trainer.backbone_key] = backbone
    trainer.state = TrainState(
        params=jax.device_put(params, trainer.param_shardings),
        opt_state=trainer.state.opt_state,
        step=trainer.state.step,
    )


def run_health_smoke(
    dump_dir: Optional[str] = None,
    clean_phases: int = 4,
    planted_phases: int = 2,
    poison_factor: float = 30.0,
) -> Dict[str, Any]:
    """Run the self-check; returns a JSON-able summary with ``passed``.

    Forces nothing on the caller's global tracer (scoped, like the perf
    audit) and writes dumps under ``dump_dir`` (a temp dir when unset —
    CI passes an artifact directory)."""
    import numpy as np

    from trlx_tpu import telemetry
    from trlx_tpu.data.configs import TRLConfig
    from trlx_tpu.orchestrator.ppo_orchestrator import PPOOrchestrator
    from trlx_tpu.pipeline.prompt_pipeline import PromptPipeline
    from trlx_tpu.telemetry.flight_recorder import inspect_dump, load_dump
    from trlx_tpu.trainer.ppo_trainer import PPOTrainer

    dump_dir = dump_dir or tempfile.mkdtemp(prefix="health-smoke-")
    config = TRLConfig.from_dict(smoke_config_dict(dump_dir))
    trainer = PPOTrainer(config)

    def reward_fn(samples, queries, response_gt=None):
        return [(len(s) % 5) / 2.0 - 1.0 for s in samples]

    rng = np.random.default_rng(0)
    prompts = [
        [int(x) for x in rng.integers(1, 28, size=4)] for _ in range(64)
    ]
    pipeline = PromptPipeline(prompts, config.train.seq_length)
    orch = PPOOrchestrator(
        trainer, pipeline, reward_fn=reward_fn,
        chunk_size=config.method.chunk_size,
    )

    def one_phase(seed: int) -> None:
        trainer.buffer.clear_history()
        trainer.begin_streamed_phase(seed=seed)
        orch.make_experience(config.method.num_rollouts, 0)
        trainer.finish_streamed_phase()

    monitor = trainer.health_monitor
    try:
        with telemetry.scoped_tracer():
            for i in range(clean_phases):
                one_phase(seed=i)
            clean_events = [ev.to_dict() for ev in monitor.events]

            _poison_embeddings(trainer, poison_factor)
            for i in range(planted_phases):
                one_phase(seed=100 + i)
    finally:
        orch.close(reraise=False)

    tripped = dict(sorted(monitor.event_counts.items()))
    dumps = list(trainer.flight_recorder.dumped)
    inspect_ok = False
    inspect_error = ""
    rendered = ""
    if dumps:
        try:
            payload = load_dump(dumps[-1])
            rendered = inspect_dump(payload)
            inspect_ok = bool(rendered)
        except Exception as e:
            inspect_error = f"{type(e).__name__}: {e}"

    missing = [d for d in REQUIRED_TRIPS if d not in tripped]
    passed = (
        not clean_events and not missing and bool(dumps) and inspect_ok
    )
    return {
        "passed": passed,
        "clean_phases": clean_phases,
        "clean_events": clean_events,
        "planted_phases": planted_phases,
        "tripped": tripped,
        "missing_required": missing,
        "dump": dumps[-1] if dumps else None,
        "dumps": dumps,
        "inspect_ok": inspect_ok,
        "inspect_error": inspect_error,
        "inspect_preview": rendered.splitlines()[:8],
        "dump_dir": dump_dir,
    }


def format_smoke_text(summary: Dict[str, Any]) -> str:
    lines = []
    n_clean = len(summary["clean_events"])
    lines.append(
        f"clean run ({summary['clean_phases']} phases): "
        f"{n_clean} events {'OK' if n_clean == 0 else '— MUST be quiet'}"
    )
    trips = ", ".join(
        f"{d} x{n}" for d, n in summary["tripped"].items()
    ) or "none"
    lines.append(
        f"planted run ({summary['planted_phases']} phases): {trips}"
    )
    if summary["missing_required"]:
        lines.append(
            "MISSING required trips: "
            + ", ".join(summary["missing_required"])
        )
    dump = summary["dump"]
    if dump:
        status = "parseable" if summary["inspect_ok"] else (
            f"INSPECT FAILED: {summary['inspect_error']}"
        )
        lines.append(f"flight dump: {os.path.basename(dump)} ({status})")
    else:
        lines.append("flight dump: MISSING (on_error=dump did not fire)")
    lines.append("health-smoke: " + ("PASS" if summary["passed"] else "FAIL"))
    return "\n".join(lines)
