"""Multi-controller lockstep simulator — engine 11.

In multi-controller JAX (the LlamaRL / direction-1 deployment shape)
every host runs its OWN Python training loop and must dispatch the same
jitted and collective-bearing programs in the same order with the same
abstract signatures — the compiled programs contain cross-host
collectives, so a dispatch present on one host and absent (or shaped
differently) on another leaves its peers blocked inside the program's
first collective until the job is killed. Nothing in engines 1–10 can
see this: they all analyze ONE controller's schedule.

This engine simulates N controller processes before any multi-host
hardware exists:

- each simulated host runs the trainer's canonical short loop — the
  SAME loop as the compile audit (``compile_audit.drive_trainer``
  with an instrumentation hook), so the audited schedule is the
  contract schedule, not a drifting copy;
- hosts execute as sequential threads over per-host views of the
  virtual global mesh, with the public ``jax.process_index()`` /
  ``jax.process_count()`` patched thread-locally — so every rank-0
  gate in the tree (telemetry tracer, ``Logger.is_main``, the health
  monitor / flight recorder construction, the run-ledger manifest)
  takes its REAL per-host arm;
- host-side collectives (``multihost_utils.sync_global_devices`` /
  ``broadcast_one_to_all`` / ``process_allgather``) are stubbed to
  record-and-simulate: they are dispatch events like any jitted call
  (a rank-gated barrier is the classic deadlock), executed locally;
- every dispatch is recorded as an event: program name, canonicalized
  arg shape/dtype signature, the program's collective sequence (via
  engine 5's extractor), and its dispatch ordinal — into one log per
  host;
- the logs are diffed across hosts: any divergence is a future
  multi-host deadlock, localized to the first diverging ordinal, the
  owning call site, and — when a stack frame sits under a
  ``process_index()==0`` / ``is_main_process()`` branch — the guarding
  branch itself, plus a per-host dispatch-count diff
  (rule ``lockstep-divergence``).

Host-0's per-trainer dispatch sequence also locks into the
``lockstep_budgets`` section of ``analysis/budgets.json`` as a
fingerprint (rule ``dispatch-sequence-drift``): intentional schedule
changes ship as reviewable lockfile diffs via ``--lockstep
--update-budgets`` (the relock preserves the other engines' sections,
per the established contract).

CLI: ``python -m trlx_tpu.analysis --lockstep [--hosts N]
[--trainers ...] [--update-budgets] [--plant-divergence]``. The static
half of this story is engine 12 (the host-concurrency rules in
``ast_lint.py``); see docs/static_analysis.md.
"""

from __future__ import annotations

import ast
import hashlib
import os
import threading
from collections import Counter
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from trlx_tpu.analysis.findings import Finding, Report, filter_suppressed
from trlx_tpu.analysis.registry import get_rule

_THIS_FILE = os.path.abspath(__file__)
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(_THIS_FILE)))

# thread-local simulated controller identity; unset outside a simulation
_TLS = threading.local()

# function names inside this module that sit between a dispatch and the
# code that made it — skipped when attributing a call site
_MACHINERY = {
    "_repo_stack", "record", "record_host_collective", "dispatch",
    "_sim_sync_global_devices", "_sim_broadcast_one_to_all",
    "_sim_process_allgather",
}


# --------------------------- simulated identity --------------------------- #

def _sim_state() -> Tuple[Optional[int], Optional[int]]:
    return getattr(_TLS, "index", None), getattr(_TLS, "count", None)


@contextmanager
def simulated_hosts(hosts: int):
    """Patch the public ``jax.process_index``/``jax.process_count`` (and
    the ``multihost_utils`` host collectives) with thread-local-aware
    versions. Code on a thread without a simulated identity — including
    every caller outside a simulation — sees the real functions; jax
    internals read ``xla_bridge`` directly and are untouched, so device
    placement and compilation behave exactly as before."""
    import jax
    from jax.experimental import multihost_utils

    real_index = jax.process_index
    real_count = jax.process_count
    real_sync = multihost_utils.sync_global_devices
    real_bcast = multihost_utils.broadcast_one_to_all
    real_gather = multihost_utils.process_allgather

    def sim_index() -> int:
        idx, _ = _sim_state()
        return real_index() if idx is None else idx

    def sim_count() -> int:
        _, cnt = _sim_state()
        return real_count() if cnt is None else cnt

    def _sim_sync_global_devices(name: str = "sync"):
        rec = getattr(_TLS, "recorder", None)
        if rec is None:
            return real_sync(name)
        rec.record_host_collective(
            "host.sync_global_devices", str(name), "sync_global_devices"
        )
        return None

    def _sim_broadcast_one_to_all(x, is_source=None):
        rec = getattr(_TLS, "recorder", None)
        if rec is None:
            return real_bcast(x, is_source=is_source)
        rec.record_host_collective(
            "host.broadcast_one_to_all",
            canonical_signature((x,), {}),
            "broadcast_one_to_all",
        )
        # every simulated host holds the same loop state, so the local
        # value IS the rank-0 value
        return x

    def _sim_process_allgather(x, tiled: bool = False):
        import numpy as np

        rec = getattr(_TLS, "recorder", None)
        if rec is None:
            return real_gather(x, tiled=tiled)
        rec.record_host_collective(
            "host.process_allgather",
            canonical_signature((x,), {}),
            "process_allgather",
        )
        _, cnt = _sim_state()
        import jax as _jax

        return _jax.tree_util.tree_map(
            lambda leaf: np.stack([np.asarray(leaf)] * int(cnt or 1)), x
        )

    jax.process_index = sim_index
    jax.process_count = sim_count
    multihost_utils.sync_global_devices = _sim_sync_global_devices
    multihost_utils.broadcast_one_to_all = _sim_broadcast_one_to_all
    multihost_utils.process_allgather = _sim_process_allgather
    try:
        yield
    finally:
        jax.process_index = real_index
        jax.process_count = real_count
        multihost_utils.sync_global_devices = real_sync
        multihost_utils.broadcast_one_to_all = real_bcast
        multihost_utils.process_allgather = real_gather


@contextmanager
def host_identity(host: int, hosts: int, recorder: "DispatchRecorder"):
    """One simulated controller's view: thread-local rank plus a fresh
    process-global tracer whose enabled flag follows the simulated rank
    (production gates the tracer on ``is_main_process()`` at first use;
    the global may already exist here, so it is swapped explicitly)."""
    from trlx_tpu import telemetry
    from trlx_tpu.telemetry.tracer import Tracer

    _TLS.index, _TLS.count, _TLS.recorder = host, hosts, recorder
    try:
        with telemetry.scoped_tracer(Tracer(enabled=(host == 0))):
            yield
    finally:
        _TLS.index = _TLS.count = _TLS.recorder = None


# ------------------------------ dispatch log ------------------------------ #

@dataclass
class DispatchEvent:
    """One jitted (or host-collective) dispatch on one simulated host."""

    ordinal: int
    program: str
    signature: str  # canonical arg shape/dtype signature
    collectives: str  # canonical collective sequence of the program
    site: Optional[Tuple[str, int]] = None  # innermost repo call site
    stack: Tuple[Tuple[str, int], ...] = ()

    def key(self) -> Tuple[str, str, str]:
        return (self.program, self.signature, self.collectives)

    def describe(self) -> str:
        sig = self.signature
        if len(sig) > 120:
            sig = sig[:117] + "..."
        coll = f" collectives[{self.collectives}]" if self.collectives else ""
        return f"`{self.program}({sig})`{coll}"


def canonical_signature(args, kwargs) -> str:
    """Shape/dtype signature over the flattened (args, kwargs) pytree —
    the part of a dispatch that keys the jit cache. Python ints/bools
    keep their value (static-arg semantics); array values do not."""
    import jax

    parts: List[str] = []
    for leaf in jax.tree_util.tree_leaves((args, kwargs)):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            weak = "~w" if getattr(leaf, "weak_type", False) else ""
            dims = ",".join(str(int(d)) for d in shape)
            parts.append(f"{dtype}[{dims}]{weak}")
        elif isinstance(leaf, (bool, int, str)):
            parts.append(f"{type(leaf).__name__}:{leaf}")
        else:
            parts.append(type(leaf).__name__)
    return ",".join(parts)


def _repo_stack(limit: int = 6) -> List[Tuple[str, int]]:
    """Innermost-first repo frames above the recording machinery."""
    import sys

    out: List[Tuple[str, int]] = []
    frame = sys._getframe(1)
    while frame is not None and len(out) < limit:
        fname = os.path.abspath(frame.f_code.co_filename)
        machinery = (
            fname == _THIS_FILE and frame.f_code.co_name in _MACHINERY
        )
        if not machinery and fname.startswith(_REPO_ROOT + os.sep):
            out.append((fname, frame.f_lineno))
        frame = frame.f_back
    return out


class DispatchRecorder:
    """Per-(host, trainer) dispatch log. ``trace_cache`` is shared across
    the hosts of one simulation so each program's collective sequence is
    extracted once, not once per host."""

    def __init__(
        self, kind: str, host: int, trace_cache: Dict[Tuple[str, str], str]
    ) -> None:
        self.kind = kind
        self.host = host
        self.events: List[DispatchEvent] = []
        self._trace_cache = trace_cache

    def record(self, program: str, fn, args, kwargs) -> None:
        import jax

        tracer_cls = getattr(jax.core, "Tracer", ())
        leaves = jax.tree_util.tree_leaves((args, kwargs))
        if any(isinstance(leaf, tracer_cls) for leaf in leaves):
            # an abstract trace of the wrapped callable (make_jaxpr /
            # eval_shape in the drift diff) — not a dispatch
            return
        sig = canonical_signature(args, kwargs)
        coll = self._collectives(program, fn, args, kwargs, sig)
        stack = _repo_stack()
        self.events.append(
            DispatchEvent(
                ordinal=len(self.events),
                program=program,
                signature=sig,
                collectives=coll,
                site=stack[0] if stack else None,
                stack=tuple(stack),
            )
        )

    def record_host_collective(
        self, program: str, signature: str, collective: str
    ) -> None:
        stack = _repo_stack()
        self.events.append(
            DispatchEvent(
                ordinal=len(self.events),
                program=program,
                signature=signature,
                collectives=collective,
                site=stack[0] if stack else None,
                stack=tuple(stack),
            )
        )

    def _collectives(self, program, fn, args, kwargs, sig) -> str:
        key = (program, sig)
        if key not in self._trace_cache:
            import jax

            from trlx_tpu.analysis.collective_trace import collective_sequence

            try:
                jaxpr = jax.make_jaxpr(fn)(*args, **kwargs)
                seq = collective_sequence(jaxpr)
                self._trace_cache[key] = ";".join(
                    f"{prim}({','.join(axes)})" for prim, axes, _ in seq
                )
            except Exception:
                self._trace_cache[key] = "<untraceable>"
        return self._trace_cache[key]


def _instrument_trainer(trainer, kind: str, recorder: DispatchRecorder):
    """Replace every callable ``*_jit`` attribute on the trainer (and,
    for ppo, its rollout engine) with a recording proxy. The inner jit
    callable is preserved on ``__wrapped__`` so the compile monitor's
    log-name attribution keeps working."""

    def wrap(program: str, fn):
        def dispatch(*args, **kwargs):
            recorder.record(program, fn, args, kwargs)
            return fn(*args, **kwargs)

        dispatch.__name__ = getattr(fn, "__name__", program)
        dispatch.__wrapped__ = getattr(fn, "__wrapped__", fn)
        dispatch._lockstep_inner = fn
        return dispatch

    def wrap_obj(obj, prefix: str) -> None:
        for name, fn in sorted(vars(obj).items()):
            if not name.endswith("_jit") or not callable(fn):
                continue
            if hasattr(fn, "_lockstep_inner"):
                continue
            setattr(obj, name, wrap(f"{prefix}.{name.strip('_')}", fn))

    wrap_obj(trainer, kind)
    if kind == "ppo":
        # building the engine here (lazy property) keeps construction
        # inside the simulated host identity, like production startup
        wrap_obj(trainer.rollout_engine_obj, f"{kind}.engine")


# ------------------------------- simulation ------------------------------- #

@dataclass
class LockstepResult:
    """One trainer's N-host simulation: per-host dispatch logs."""

    kind: str
    hosts: int
    mesh: Dict[str, int] = field(default_factory=dict)
    logs: Dict[int, List[DispatchEvent]] = field(default_factory=dict)

    def fingerprint(self) -> str:
        return sequence_fingerprint(self.logs.get(0, []))

    def dispatches(self) -> int:
        return len(self.logs.get(0, []))

    def program_counts(self) -> Dict[str, int]:
        return dict(
            sorted(Counter(e.program for e in self.logs.get(0, [])).items())
        )

    def to_row(self) -> Dict:
        return {
            "subject": self.kind,
            "hosts": self.hosts,
            "dispatches": self.dispatches(),
            "fingerprint": self.fingerprint(),
            "programs": self.program_counts(),
        }


def sequence_fingerprint(events: Sequence[DispatchEvent]) -> str:
    """Stable hash of the canonical dispatch sequence (program,
    signature, collective schedule per ordinal)."""
    h = hashlib.sha256()
    for e in events:
        h.update(("|".join(e.key()) + "\n").encode())
    return h.hexdigest()[:16]


def _run_host(
    kind: str,
    mesh: Optional[Dict[str, int]],
    hosts: int,
    host: int,
    steps: int,
    trace_cache: Dict,
    dump_dir: str,
    plant: bool,
) -> Tuple[List[DispatchEvent], Dict[str, int]]:
    from trlx_tpu.analysis.compile_audit import CompileMonitor, drive_trainer

    recorder = DispatchRecorder(kind, host, trace_cache)
    captured: Dict[str, Any] = {}

    def instrument(trainer) -> None:
        _instrument_trainer(trainer, kind, recorder)
        captured["trainer"] = trainer

    with host_identity(host, hosts, recorder):
        # health enabled: host 0 must build the monitor/flight recorder,
        # hosts>0 must skip them — and neither arm may dispatch
        overrides = {
            "health": {"enabled": True, "dump_dir": dump_dir, "on_error": "warn"}
        }
        # the un-entered monitor installs no log handlers; engine 11
        # audits dispatch order, engine 8 owns compile counts
        _, _, mesh_shape = drive_trainer(
            kind,
            mesh,
            monitor=CompileMonitor(),
            steps=steps,
            instrument=instrument,
            train_overrides=overrides,
        )
        trainer = captured["trainer"]
        # the health-observation path must be dispatch-free on every
        # rank (host 0 has a monitor, the others None)
        trainer.observe_health({"loss": 1.0, "kl": 0.1}, step=0, phase=0)
        if plant:
            import jax.numpy as jnp

            from trlx_tpu.parallel.distributed import is_main_process

            B = trainer.config.train.batch_size
            Q = trainer.query_length
            if is_main_process():
                # deliberately planted rank-0-only dispatch: the
                # --plant-divergence self-check that the simulator
                # localizes exactly this hazard class
                trainer.sample(
                    jnp.ones((B, Q), jnp.int32), jnp.ones((B, Q), jnp.int32)
                )
    return recorder.events, mesh_shape


def simulate_trainer(
    kind: str,
    hosts: int = 2,
    mesh: Optional[Dict[str, int]] = None,
    steps: int = 2,
    plant: bool = False,
) -> LockstepResult:
    """Run ``kind``'s canonical loop as ``hosts`` simulated controllers
    (sequential threads — determinism is part of the point) and return
    the per-host dispatch logs."""
    import tempfile

    trace_cache: Dict = {}
    result = LockstepResult(kind=kind, hosts=hosts)
    errors: List[BaseException] = []
    with tempfile.TemporaryDirectory(prefix="lockstep_health_") as dump_dir:
        with simulated_hosts(hosts):
            for host in range(hosts):

                def run(host: int = host) -> None:
                    try:
                        log, mesh_shape = _run_host(
                            kind, mesh, hosts, host, steps, trace_cache,
                            dump_dir, plant,
                        )
                        result.logs[host] = log
                        result.mesh.update(mesh_shape)
                    except BaseException as e:  # surfaced below
                        errors.append(e)

                t = threading.Thread(
                    target=run, name=f"lockstep-host-{host}", daemon=True
                )
                t.start()
                t.join()
                if errors:
                    raise RuntimeError(
                        f"lockstep simulation of {kind} failed on host "
                        f"{host}/{hosts}"
                    ) from errors[0]
    return result


# ------------------------------- divergence ------------------------------- #

_AST_CACHE: Dict[str, Optional[ast.AST]] = {}


def _parsed(fname: str) -> Optional[ast.AST]:
    if fname not in _AST_CACHE:
        try:
            with open(fname, encoding="utf-8") as fh:
                _AST_CACHE[fname] = ast.parse(fh.read(), filename=fname)
        except (OSError, SyntaxError):
            _AST_CACHE[fname] = None
    return _AST_CACHE[fname]


def _enclosing_branch(
    fname: str, lineno: int, rank_only: bool
) -> Optional[Tuple[int, str]]:
    """(line, unparsed test) of the innermost ``if``/``while`` enclosing
    ``lineno`` in ``fname`` — restricted to rank-gate tests when
    ``rank_only`` (``is_main_process()`` / ``process_index()`` /
    ``.is_main``)."""
    from trlx_tpu.analysis.ast_lint import _is_rank_test

    tree = _parsed(fname)
    if tree is None:
        return None
    best: Optional[Tuple[int, str]] = None
    for node in ast.walk(tree):
        if not isinstance(node, (ast.If, ast.While)):
            continue
        end = getattr(node, "end_lineno", node.lineno)
        if not (node.lineno <= lineno <= end):
            continue
        if rank_only and not _is_rank_test(node.test):
            continue
        if best is None or node.lineno > best[0]:
            try:
                best = (node.lineno, ast.unparse(node.test))
            except Exception:
                best = (node.lineno, "<unprintable test>")
    return best


def _guarding_branch(
    event: DispatchEvent,
) -> Optional[Tuple[str, int, str]]:
    """The rank-gate branch a diverging dispatch sits under, searched
    innermost-frame-out across the recorded stack; falls back to the
    innermost enclosing branch of the call site."""
    for fname, lineno in event.stack:
        hit = _enclosing_branch(fname, lineno, rank_only=True)
        if hit is not None:
            return (fname, hit[0], hit[1])
    for fname, lineno in event.stack:
        hit = _enclosing_branch(fname, lineno, rank_only=False)
        if hit is not None:
            return (fname, hit[0], hit[1])
    return None


def _count_diff(
    ref: Sequence[DispatchEvent], cur: Sequence[DispatchEvent]
) -> str:
    a = Counter(e.program for e in ref)
    b = Counter(e.program for e in cur)
    parts = []
    for prog in sorted(set(a) | set(b)):
        if a.get(prog, 0) != b.get(prog, 0):
            parts.append(f"{prog}: {a.get(prog, 0)} vs {b.get(prog, 0)}")
    return "; ".join(parts) or "per-program counts identical (order differs)"


def _relpath(fname: str) -> str:
    try:
        rel = os.path.relpath(fname, _REPO_ROOT)
    except ValueError:
        return fname
    return fname if rel.startswith("..") else rel


def diff_host_logs(result: LockstepResult) -> List[Finding]:
    """``lockstep-divergence`` findings: host 0 is the reference; every
    other host's log must match event-for-event."""
    rule = get_rule("lockstep-divergence")
    findings: List[Finding] = []
    ref = result.logs.get(0, [])
    for host in sorted(result.logs):
        if host == 0:
            continue
        cur = result.logs[host]
        n = min(len(ref), len(cur))
        div = next(
            (i for i in range(n) if ref[i].key() != cur[i].key()), None
        )
        if div is None:
            if len(ref) == len(cur):
                continue
            div = n
        e0 = ref[div] if div < len(ref) else None
        eh = cur[div] if div < len(cur) else None
        guilty = e0 if e0 is not None else eh
        guard = _guarding_branch(guilty)
        site = guilty.site
        where = (
            f" at {_relpath(site[0])}:{site[1]}" if site is not None else ""
        )
        guard_txt = ""
        file, line = site if site is not None else (None, None)
        if guard is not None:
            gf, gl, gtest = guard
            guard_txt = (
                f"; guarding branch: `{gtest}` at {_relpath(gf)}:{gl}"
            )
            file, line = gf, gl
        d0 = e0.describe() if e0 is not None else (
            "<absent — its loop already finished>"
        )
        dh = eh.describe() if eh is not None else (
            "<absent — its loop already finished>"
        )
        findings.append(
            Finding(
                rule=rule.id,
                message=(
                    f"hosts diverge at dispatch ordinal {div} of the "
                    f"{result.kind} canonical loop ({result.hosts} "
                    f"simulated hosts): host 0 dispatched {d0}, host "
                    f"{host} dispatched {dh}{where}{guard_txt}; per-host "
                    f"state diff — {_count_diff(ref, cur)}. In a real "
                    "multi-controller run the minority host(s) block in "
                    "this program's first collective forever"
                ),
                severity=rule.severity,
                file=file,
                line=line,
                subject=f"{result.kind}@host{host}",
                engine="lockstep",
            )
        )
    return findings


# -------------------------------- budgets --------------------------------- #

def make_lockstep_budgets(
    results: Sequence[LockstepResult], hosts: int
) -> Dict:
    mesh: Dict[str, int] = {}
    for r in results:
        mesh = r.mesh or mesh
    return {
        "hosts": int(hosts),
        "mesh": {k: int(v) for k, v in sorted(mesh.items())},
        "trainers": {
            r.kind: {
                "fingerprint": r.fingerprint(),
                "dispatches": r.dispatches(),
                "programs": r.program_counts(),
            }
            for r in sorted(results, key=lambda r: r.kind)
        },
    }


def check_lockstep_budgets(
    results: Sequence[LockstepResult],
    budgets: Dict,
    budgets_path: Optional[str] = None,
) -> List[Finding]:
    """Gate host-0 dispatch fingerprints against the committed
    ``lockstep_budgets`` contract."""
    rule = get_rule("dispatch-sequence-drift")
    where = os.path.basename(budgets_path or "budgets.json")
    section = budgets.get("lockstep_budgets")
    if section is None:
        return [
            Finding(
                rule=rule.id,
                message=(
                    f"{where} has no lockstep_budgets section — lock the "
                    "dispatch fingerprints with --lockstep "
                    "--update-budgets and commit the diff"
                ),
                severity=rule.severity,
                subject="lockstep_budgets",
                engine="lockstep",
            )
        ]
    findings: List[Finding] = []
    mesh = {}
    for r in results:
        mesh = r.mesh or mesh
    locked_mesh = section.get("mesh")
    if locked_mesh is not None and mesh:
        current = {k: int(v) for k, v in sorted(mesh.items())}
        locked = {k: int(v) for k, v in sorted(locked_mesh.items())}
        if locked != current:
            return [
                Finding(
                    rule=rule.id,
                    message=(
                        f"lockstep budgets in {where} were locked for "
                        f"mesh {locked_mesh} but the simulation ran on "
                        f"{current} — fingerprints are not comparable; "
                        "rerun on the locked mesh or --update-budgets"
                    ),
                    severity=rule.severity,
                    subject="lockstep_budgets",
                    engine="lockstep",
                )
            ]
    trainers = section.get("trainers", {})
    for r in results:
        entry = trainers.get(r.kind)
        if entry is None:
            findings.append(
                Finding(
                    rule=rule.id,
                    message=(
                        f"no committed dispatch fingerprint for trainer "
                        f"`{r.kind}` ({r.dispatches()} dispatches "
                        "observed) — run --lockstep --update-budgets and "
                        "review the lockfile diff"
                    ),
                    severity=rule.severity,
                    subject=r.kind,
                    engine="lockstep",
                )
            )
            continue
        if entry.get("fingerprint") != r.fingerprint():
            locked_programs = entry.get("programs", {})
            current_programs = r.program_counts()
            parts = []
            for prog in sorted(set(locked_programs) | set(current_programs)):
                a = int(locked_programs.get(prog, 0))
                b = int(current_programs.get(prog, 0))
                if a != b:
                    parts.append(f"{prog}: locked {a}, now {b}")
            diff = "; ".join(parts) or (
                "per-program counts unchanged — the order, a signature, "
                "or a collective schedule moved"
            )
            findings.append(
                Finding(
                    rule=rule.id,
                    message=(
                        f"`{r.kind}` host-0 dispatch sequence drifted "
                        f"from the committed contract (fingerprint "
                        f"{entry.get('fingerprint')} -> {r.fingerprint()}"
                        f"; {diff}) — every direction-1 component "
                        "replays this schedule on N hosts; if the change "
                        "is intended, relock with --lockstep "
                        "--update-budgets and explain the diff"
                    ),
                    severity=rule.severity,
                    subject=r.kind,
                    engine="lockstep",
                )
            )
    # entries for kinds this run did not simulate stay untouched — the
    # compile-audit partial-run contract; stale entries for a simulated
    # kind are impossible (one entry per kind), so no prune pass here
    return findings


# ----------------------------- orchestration ------------------------------ #

def audit_lockstep(
    kinds: Optional[Sequence[str]] = None,
    hosts: int = 2,
    mesh: Optional[Dict[str, int]] = None,
    budgets_path: Optional[str] = None,
    update: bool = False,
    steps: int = 2,
    plant: bool = False,
) -> Tuple[Report, List[LockstepResult]]:
    """The ``--lockstep`` entry point: simulate every trainer's canonical
    loop on ``hosts`` controllers, diff the per-host dispatch logs, and
    gate (or with ``update=True`` relock) host-0 fingerprints against the
    ``lockstep_budgets`` section of ``analysis/budgets.json``."""
    from trlx_tpu.analysis import harness
    from trlx_tpu.analysis.resource_audit import (
        default_budgets_path,
        load_budgets,
        write_budgets,
    )

    path = budgets_path or default_budgets_path()
    report = Report()
    results: List[LockstepResult] = []
    for kind in kinds or harness.TRAINER_KINDS:
        result = simulate_trainer(
            kind, hosts=hosts, mesh=mesh, steps=steps, plant=plant
        )
        results.append(result)
        report.covered.append(f"lockstep:{kind}@{hosts}hosts")

    findings: List[Finding] = []
    for result in results:
        findings += diff_host_logs(result)

    if update:
        if findings:
            # a diverging schedule is not a contract — refuse the relock
            kept, suppressed = filter_suppressed(findings)
            report.extend(kept)
            report.suppressed += suppressed
            return report, results
        try:
            budgets = load_budgets(path)
        except (OSError, ValueError):
            budgets = {}
        partial = kinds is not None
        section = make_lockstep_budgets(results, hosts)
        old_section = budgets.get("lockstep_budgets") or {}
        if partial and (
            old_section.get("mesh") not in (None, section["mesh"])
            or old_section.get("hosts") not in (None, section["hosts"])
        ):
            rule = get_rule("dispatch-sequence-drift")
            report.extend([
                Finding(
                    rule=rule.id,
                    message=(
                        "refusing --update-budgets: the lockstep "
                        f"lockfile is for mesh "
                        f"{old_section.get('mesh')} / "
                        f"{old_section.get('hosts')} hosts but this "
                        f"--trainers subset ran on {section['mesh']} / "
                        f"{section['hosts']} hosts — rerun without "
                        "--trainers or on the locked configuration"
                    ),
                    severity=rule.severity,
                    subject="lockstep_budgets",
                    engine="lockstep",
                )
            ])
            return report, results
        if partial:
            kept_entries = {
                k: dict(e)
                for k, e in old_section.get("trainers", {}).items()
                if k not in {k2 for k2 in (kinds or ())}
            }
            kept_entries.update(section["trainers"])
            section["trainers"] = {
                k: kept_entries[k] for k in sorted(kept_entries)
            }
        budgets["lockstep_budgets"] = section
        write_budgets(budgets, path)
        return report, results

    if not plant:
        # --plant-divergence is a self-check of the simulator itself;
        # gating its (deliberately divergent) run against the lockfile
        # would bury the planted finding in drift noise
        try:
            budgets = load_budgets(path)
        except (OSError, ValueError) as e:
            rule = get_rule("dispatch-sequence-drift")
            findings.append(
                Finding(
                    rule=rule.id,
                    message=(
                        f"cannot load budget contract {path}: {e} — "
                        "generate it with --lockstep --update-budgets"
                    ),
                    severity=rule.severity,
                    subject="lockstep_budgets",
                    engine="lockstep",
                )
            )
            budgets = {}
        if budgets:
            findings += check_lockstep_budgets(results, budgets, path)
    kept, suppressed = filter_suppressed(findings)
    report.extend(kept)
    report.suppressed += suppressed
    return report, results


def format_lockstep_text(results: Sequence[LockstepResult]) -> str:
    lines = [
        f"{'trainer':10} {'hosts':>5} {'dispatches':>10}  fingerprint"
    ]
    for r in sorted(results, key=lambda r: r.kind):
        lines.append(
            f"{r.kind:10} {r.hosts:>5} {r.dispatches():>10}  "
            f"{r.fingerprint()}"
        )
        for prog, n in r.program_counts().items():
            lines.append(f"    {prog:40} ×{n}")
    return "\n".join(lines)
