"""Measured-perf audit: a wall-clock lockfile over the span stream.

Engine 10 of ``trlx_tpu.analysis`` — the first engine that gates a
*measurement* instead of a traced contract. Engines 6–8 bound what a
program should cost (bytes, collectives, compiles); none of them noticed
faithful throughput drifting 167 → 162 samples/s/chip across five bench
rounds, because nothing watched wall-clock. This engine does:

- **the workload**: the real streamed phase loop (PPO trainer +
  orchestrator + prompt pipeline at the harness shapes), instrumented by
  the telemetry tracer — warmup phases absorb compilation, then N
  measured phases populate per-span p50/p95 ms;
- **the lockfile**: a ``perf_budgets`` section of
  ``analysis/budgets.json`` keyed BY PLATFORM
  (``platforms.cpu/.tpu/...``) — wall-clock is never comparable across
  backends, so each platform carries its own entry: p50/p95 per gated
  span (``phase/collect``, ``phase/train``, ``train/drain``), an
  entry-level tolerance (generous on CPU — shared runners jitter; tight
  on real hardware) plus per-span overrides, and an absolute slack
  floor so microsecond spans don't flap. A TPU relock and the CPU CI
  tripwire coexist in one committed file;
- **the gate** (rule ``perf-regression``): current p50 past
  ``locked_p50 × (1 + tolerance) + abs_slack_ms`` fails; so does a
  missing/stale entry or an unlocked platform. Per-phase span-count
  drift (duplicated/renamed instrumentation, which would halve per-fire
  p50s and dodge the gate) warns. ``--update-budgets`` relocks only the
  current platform's entry, preserving every other platform's lock,
  every other engine's sections, and any committed per-span tolerance
  overrides.

The span stream of the audited run can be exported with ``--span-log``
(Perfetto/chrome-tracing JSONL; CI uploads it as an artifact) so a red
gate ships the timeline that tripped it.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from trlx_tpu.analysis.findings import Finding, Report, filter_suppressed
from trlx_tpu.analysis.registry import get_rule

#: spans gated against the lockfile — the stable phase-level keys
#: (chunk-level spans like collect/decode ride in the report, ungated:
#: their counts vary with chunking config and their absolute values sit
#: in jitter territory on CPU)
GATED_SPANS = ("phase/collect", "phase/train", "train/drain")

#: default relock tolerance by platform: CPU runners are shared and
#: noisy — a single-core box under a concurrent job measures 3-4x on
#: the same code (observed), so the CPU gate is a tripwire for gross
#: drift only; the tight gate lives on hardware, where real
#: accelerators are stable enough for the 3%-drift story the bench
#: rounds needed
DEFAULT_TOLERANCE_PCT = {"cpu": 300.0, "default": 25.0}

#: absolute slack floor (ms) added to every bound: a 0.1 ms drain span
#: doubling is scheduler noise, not a regression
DEFAULT_ABS_SLACK_MS = 25.0


@dataclass
class SpanBudgetRow:
    """Measured stats of one span name over the audited phase loop."""

    subject: str
    count: int
    p50_ms: float
    p95_ms: float
    total_ms: float

    def to_dict(self) -> Dict:
        return {
            "subject": self.subject,
            "count": self.count,
            "p50_ms": round(self.p50_ms, 3),
            "p95_ms": round(self.p95_ms, 3),
            "total_ms": round(self.total_ms, 3),
        }


# ------------------------------ the workload ----------------------------- #

def perf_workload_config() -> Dict:
    """Harness-shape PPO config with a phase big enough to exercise the
    whole span taxonomy: 3 chunks per phase (landing boundaries for the
    streamed dispatcher), 2 ppo_epochs (a residual scan exists)."""
    from trlx_tpu.analysis import harness

    cfg = harness.tiny_config_dict("ppo")
    cfg["method"].update(num_rollouts=24, chunk_size=8, ppo_epochs=2)
    return cfg


def run_perf_phases(
    phases: int = 5,
    warmup: int = 2,
    slowdown_ms: float = 0.0,
) -> Tuple[List[SpanBudgetRow], List]:
    """Run the instrumented streamed phase loop and return (per-span
    stats over the MEASURED phases, the raw span records).

    ``slowdown_ms`` injects a host-side sleep into every measured
    phase's scoring step — the seeded self-check that a planted
    regression actually trips the gate (the ``--plant-nan`` pattern).
    """
    import numpy as np

    from trlx_tpu import telemetry
    from trlx_tpu.analysis import harness
    from trlx_tpu.orchestrator.ppo_orchestrator import PPOOrchestrator
    from trlx_tpu.pipeline.prompt_pipeline import PromptPipeline

    workload = perf_workload_config()["method"]
    sleeping = {"ms": 0.0}

    def reward_fn(samples, queries, response_gt=None):
        if sleeping["ms"]:
            time.sleep(sleeping["ms"] / 1000.0)
        return [(len(s) % 5) / 2.0 - 1.0 for s in samples]

    # the harness trainer, with the phase plan widened to the audit
    # workload (num_rollouts/ppo_epochs feed the stream plan, not any
    # compiled program shape — the widened phase compiles in warmup)
    trainer = harness.build_trainer("ppo")
    trainer.config.method.num_rollouts = workload["num_rollouts"]
    trainer.config.method.ppo_epochs = workload["ppo_epochs"]
    rng = np.random.default_rng(0)
    prompts = [
        [int(x) for x in rng.integers(1, 28, size=4)] for _ in range(64)
    ]
    pipeline = PromptPipeline(prompts, trainer.config.train.seq_length)
    orch = PPOOrchestrator(
        trainer, pipeline, reward_fn=reward_fn,
        chunk_size=workload["chunk_size"],
    )

    def one_phase(seed: int) -> None:
        trainer.buffer.clear_history()
        trainer.begin_streamed_phase(seed=seed)
        orch.make_experience(trainer.config.method.num_rollouts, 0)
        trainer.finish_streamed_phase()

    # a scoped private tracer: the audit's spans neither wipe nor leak
    # into whatever span history the embedding process had accumulated
    try:
        with telemetry.scoped_tracer() as tracer:
            for i in range(warmup):  # compiles + donated-buffer relayouts
                one_phase(seed=i)
            tracer.clear()  # stats cover the measured window only
            sleeping["ms"] = float(slowdown_ms)
            for i in range(phases):
                one_phase(seed=warmup + i)
            records = tracer.spans()
            stats = tracer.stats()
    finally:
        sleeping["ms"] = 0.0
        orch.close()

    rows = [
        SpanBudgetRow(
            subject=name,
            count=int(s["count"]),
            p50_ms=s["p50_ms"],
            p95_ms=s["p95_ms"],
            total_ms=s["total_ms"],
        )
        for name, s in sorted(stats.items())
    ]
    return rows, records


# ------------------------------- budgets --------------------------------- #

def _platform() -> str:
    import jax

    return jax.default_backend()


def make_perf_budgets(
    rows: Sequence[SpanBudgetRow],
    platform: Optional[str] = None,
    phases: int = 5,
    tolerance_pct: Optional[float] = None,
) -> Dict:
    platform = platform or _platform()
    if tolerance_pct is None:
        tolerance_pct = DEFAULT_TOLERANCE_PCT.get(
            platform, DEFAULT_TOLERANCE_PCT["default"]
        )
    return {
        "platform": platform,
        "phases": phases,
        "tolerance_pct": tolerance_pct,
        "abs_slack_ms": DEFAULT_ABS_SLACK_MS,
        "spans": {
            r.subject: {
                "p50_ms": round(r.p50_ms, 3),
                "p95_ms": round(r.p95_ms, 3),
                "count": r.count,
            }
            for r in sorted(rows, key=lambda r: r.subject)
            if r.subject in GATED_SPANS
        },
    }


def merge_perf_budgets(entry: Dict, old_entry: Dict) -> Dict:
    """Preserve reviewer-committed knobs across a same-platform relock:
    the entry-level tolerance/slack and any per-span ``tolerance_pct``
    overrides. (Cross-platform never merges — each platform owns its own
    entry under ``perf_budgets.platforms``, so a TPU relock cannot
    inherit the CPU tripwire tolerance or vice versa.)"""
    for key in ("tolerance_pct", "abs_slack_ms"):
        if key in old_entry:
            entry[key] = old_entry[key]
    old_spans = old_entry.get("spans", {})
    for name, span_entry in entry["spans"].items():
        old = old_spans.get(name)
        if old and "tolerance_pct" in old:
            span_entry["tolerance_pct"] = old["tolerance_pct"]
    return entry


def upsert_perf_budgets(budgets: Dict, entry: Dict) -> Dict:
    """Fold a :func:`make_perf_budgets` entry into ``budgets`` under
    ``perf_budgets.platforms[<platform>]``, preserving every OTHER
    platform's lock untouched — this is what lets the generous CPU CI
    tripwire and a tight hardware lock coexist in one committed file
    (relocking on TPU must not break the CPU gate, and vice versa)."""
    section = budgets.setdefault("perf_budgets", {})
    platforms = section.setdefault("platforms", {})
    plat = entry["platform"]
    platforms[plat] = merge_perf_budgets(
        dict(entry), platforms.get(plat) or {}
    )
    return budgets


def check_perf_budgets(
    rows: Sequence[SpanBudgetRow],
    budgets: Dict,
    platform: Optional[str] = None,
    budgets_path: Optional[str] = None,
    phases: Optional[int] = None,
) -> List[Finding]:
    """Gate measured span p50s against the committed contract for the
    CURRENT platform's entry (``perf_budgets.platforms[<platform>]`` —
    wall-clock is never compared across backends; each platform carries
    its own lock). ``phases`` (the measured phase count) additionally
    cross-checks per-phase span counts, catching renamed/duplicated
    instrumentation whose halved durations would otherwise pass the p50
    gate."""
    rule = get_rule("perf-regression")
    where = os.path.basename(budgets_path or "budgets.json")
    section = budgets.get("perf_budgets")
    if section is None:
        return [
            Finding(
                rule=rule.id,
                message=(
                    f"{where} has no perf_budgets section — lock the "
                    "measured span timings with --perf-audit "
                    "--update-budgets and commit the diff"
                ),
                severity=rule.severity,
                subject="perf_budgets",
                engine="perf",
            )
        ]
    platform = platform or _platform()
    plat_entry = (section.get("platforms") or {}).get(platform)
    if plat_entry is None:
        return [
            Finding(
                rule=rule.id,
                message=(
                    f"perf budgets in {where} carry no entry for "
                    f"platform {platform!r} (locked: "
                    f"{sorted(section.get('platforms') or {}) or 'none'}) "
                    "— wall-clock is not comparable across backends; "
                    "relock on this platform with --perf-audit "
                    "--update-budgets (other platforms' locks are "
                    "preserved)"
                ),
                severity=rule.severity,
                subject="perf_budgets",
                engine="perf",
            )
        ]
    findings: List[Finding] = []
    default_tol = float(
        plat_entry.get(
            "tolerance_pct",
            DEFAULT_TOLERANCE_PCT.get(platform, DEFAULT_TOLERANCE_PCT["default"]),
        )
    )
    slack = float(plat_entry.get("abs_slack_ms", DEFAULT_ABS_SLACK_MS))
    locked_phases = int(plat_entry.get("phases", 0))
    spans = plat_entry.get("spans", {})
    by_name = {r.subject: r for r in rows}
    for name in GATED_SPANS:
        r = by_name.get(name)
        entry = spans.get(name)
        if r is None:
            if entry is not None:
                findings.append(
                    Finding(
                        rule=rule.id,
                        message=(
                            f"locked span `{name}` was not measured by "
                            "this audit — the instrumentation moved or "
                            "the span was renamed; relock with "
                            "--perf-audit --update-budgets"
                        ),
                        severity="warning",
                        subject=name,
                        engine="perf",
                    )
                )
            continue
        if entry is None:
            findings.append(
                Finding(
                    rule=rule.id,
                    message=(
                        f"no committed perf budget for measured span "
                        f"`{name}` (p50 {r.p50_ms:.1f} ms) — run "
                        "--perf-audit --update-budgets and review the "
                        "lockfile diff"
                    ),
                    severity=rule.severity,
                    subject=name,
                    engine="perf",
                )
            )
            continue
        if phases and locked_phases and entry.get("count"):
            locked_per_phase = float(entry["count"]) / locked_phases
            measured_per_phase = float(r.count) / phases
            if abs(locked_per_phase - measured_per_phase) > 1e-9:
                findings.append(
                    Finding(
                        rule=rule.id,
                        message=(
                            f"span `{name}` fired {measured_per_phase:g}× "
                            f"per phase vs the locked "
                            f"{locked_per_phase:g}× — the instrumentation "
                            "moved or a span was duplicated/renamed, so "
                            "its per-fire p50 no longer measures the same "
                            "region; fix the instrumentation or relock "
                            "with --perf-audit --update-budgets"
                        ),
                        severity="warning",
                        subject=name,
                        engine="perf",
                    )
                )
        tol = float(entry.get("tolerance_pct", default_tol))
        locked_p50 = float(entry.get("p50_ms", 0.0))
        bound = locked_p50 * (1.0 + tol / 100.0) + slack
        if r.p50_ms > bound:
            drift = (
                100.0 * (r.p50_ms - locked_p50) / locked_p50
                if locked_p50
                else float("inf")
            )
            findings.append(
                Finding(
                    rule=rule.id,
                    message=(
                        f"measured p50 of `{name}` is {r.p50_ms:.1f} ms, "
                        f"{drift:+.1f}% over the committed "
                        f"{locked_p50:.1f} ms (tolerance {tol:.0f}% "
                        f"+ {slack:.0f} ms slack) — the phase loop got "
                        "slower; find the cause (span JSONL artifact, "
                        "--compile-audit for retraces, bench attribution) "
                        "or relock deliberately with --perf-audit "
                        "--update-budgets"
                    ),
                    severity=rule.severity,
                    subject=name,
                    engine="perf",
                )
            )
    for stale in sorted(set(spans) - set(GATED_SPANS)):
        findings.append(
            Finding(
                rule=rule.id,
                message=(
                    f"perf budget entry `{stale}` is not a gated span — "
                    "prune it with --perf-audit --update-budgets"
                ),
                severity="warning",
                subject=stale,
                engine="perf",
            )
        )
    return findings


# ----------------------------- orchestration ----------------------------- #

def audit_perf(
    budgets_path: Optional[str] = None,
    update: bool = False,
    phases: int = 5,
    warmup: int = 2,
    slowdown_ms: float = 0.0,
    span_log: Optional[str] = None,
) -> Tuple[Report, List[SpanBudgetRow]]:
    """The ``--perf-audit`` entry point: run the instrumented phase loop,
    then gate the measured span p50s against (or with ``update=True``
    relock) the ``perf_budgets`` section of ``analysis/budgets.json``."""
    from trlx_tpu.analysis.resource_audit import (
        default_budgets_path,
        load_budgets,
        write_budgets,
    )

    path = budgets_path or default_budgets_path()
    rows, records = run_perf_phases(
        phases=phases, warmup=warmup, slowdown_ms=slowdown_ms
    )
    report = Report()
    report.covered += [f"perf:{r.subject}" for r in rows]
    report.resources = [r.to_dict() for r in rows]
    if span_log:
        from trlx_tpu.telemetry import export_chrome_jsonl

        # one artifact per audit run: truncate first — appending a rerun
        # onto an old export would interleave two runs' timestamps into
        # one misleading Perfetto timeline
        open(span_log, "w").close()
        export_chrome_jsonl(span_log, records)

    if update:
        try:
            budgets = load_budgets(path)
        except (OSError, ValueError):
            budgets = {}
        upsert_perf_budgets(budgets, make_perf_budgets(rows, phases=phases))
        write_budgets(budgets, path)
        return report, rows

    try:
        budgets = load_budgets(path)
    except (OSError, ValueError) as e:
        rule = get_rule("perf-regression")
        report.extend([
            Finding(
                rule=rule.id,
                message=(
                    f"cannot load budget contract {path}: {e} — generate "
                    "it with --perf-audit --update-budgets"
                ),
                severity=rule.severity,
                subject="perf_budgets",
                engine="perf",
            )
        ])
        return report, rows
    kept, suppressed = filter_suppressed(
        check_perf_budgets(rows, budgets, budgets_path=path, phases=phases)
    )
    report.extend(kept)
    report.suppressed += suppressed
    return report, rows


def format_perf_text(rows: Sequence[SpanBudgetRow]) -> str:
    lines = [
        f"{'span':26} {'count':>6} {'p50 ms':>10} {'p95 ms':>10} "
        f"{'total ms':>10}"
    ]
    for r in sorted(rows, key=lambda r: r.subject):
        gate = "*" if r.subject in GATED_SPANS else " "
        lines.append(
            f"{r.subject:26}{gate}{r.count:>6} {r.p50_ms:>10.2f} "
            f"{r.p95_ms:>10.2f} {r.total_ms:>10.1f}"
        )
    lines.append("(* gated against perf_budgets)")
    return "\n".join(lines)
