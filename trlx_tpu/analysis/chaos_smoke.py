"""``--chaos-smoke``: injected-failure self-check for the resilience layer.

The ``--health-smoke`` pattern applied to recovery: a fault-tolerance
subsystem that cannot survive a *planted* failure is vacuous exactly
when it breaks. Each scenario runs a REAL tiny training job (the
`tests/test_resume.py` harness shape) with one failure injected through
the chaos schedule (resilience/chaos.py) and asserts the specified
recovery — no mocks anywhere on the failure path:

1. **clean** — resilience armed, no chaos: the run completes with zero
   chaos events, zero retries, zero restarts (the supervisor must be
   inert when nothing fails);
2. **transient checkpoint I/O** — ``checkpoint.save`` fails twice: the
   bounded-backoff retry absorbs it with zero user-visible failure and
   the checkpoint lands;
3. **permanent structure mismatch** — a real orbax layout disagreement
   AND an injected permanent error both refuse fast: exactly one
   attempt, an actionable ValueError;
4. **preemption at phase k** — a real SIGTERM delivered at phase 0's
   boundary: emergency checkpoint, supervised auto-resume, and a final
   state **bitwise identical** to the uninterrupted run (params + step +
   KL state);
5. **engine-path failure** — ``engine.admit`` fails under the
   continuous rollout engine: the phase completes on the fixed sampler
   with an ``engine-fallback`` health event, not an abort;
6. **async-writer disk-full** — three consecutive ENOSPC on the rollout
   log: the writer degrades to synchronous writes, and every row is
   durable once the disk recovers.

PASS requires every scenario. Exercised per-PR by the ``chaos-smoke``
CI job (`python -m trlx_tpu.analysis --chaos-smoke --json`).
"""

from __future__ import annotations

import os
import tempfile
from typing import Any, Callable, Dict, List

SCENARIOS = (
    "clean",
    "transient_checkpoint_io",
    "permanent_mismatch",
    "preempt_resume_parity",
    "engine_fallback",
    "writer_disk_full",
)


def tiny_config_dict(
    checkpoint_dir: str,
    total_steps: int,
    resilience: Dict[str, Any],
    **train_overrides: Any,
) -> Dict[str, Any]:
    """The test_resume harness shape: 1-layer/16-wide gpt2, 2-step
    phases (num_rollouts=16, batch=8, ppo_epochs=1) — every scenario
    below preempts/resumes/fails on phase boundaries of this layout."""
    train = {
        "seq_length": 4,
        "batch_size": 8,
        "epochs": 8,
        "total_steps": total_steps,
        "eval_interval": 10000,
        "checkpoint_interval": 100000,
        "checkpoint_dir": checkpoint_dir,
        "mesh": {"dp": -1, "fsdp": 1, "tp": 1},
        "dtype": "float32",
        "resilience": resilience,
    }
    train.update(train_overrides)
    return {
        "model": {
            "model_type": "gpt2",
            "model_arch": {
                "vocab_size": 32,
                "n_positions": 16,
                "n_embd": 16,
                "n_layer": 1,
                "n_head": 2,
            },
        },
        "train": train,
        "method": {
            "name": "PPOConfig",
            "num_rollouts": 16,
            "chunk_size": 8,
            "ppo_epochs": 1,
            "gen_kwargs": {
                "max_new_tokens": 2,
                "do_sample": True,
                "eos_token_id": 30,
                "pad_token_id": 31,
            },
        },
    }


def _train(config_dict: Dict[str, Any]):
    import contextlib
    import sys

    import numpy as np

    import trlx_tpu
    from trlx_tpu.data.configs import TRLConfig

    os.environ["WANDB_DISABLED"] = "1"
    rng = np.random.default_rng(0)
    prompts = [list(rng.integers(1, 30, size=3)) for _ in range(16)]
    # the Logger's per-step JSON lines go to stdout; reroute them to
    # stderr so the smoke's own report (CI tees stdout into the
    # artifact) stays a single parseable JSON document
    with contextlib.redirect_stdout(sys.stderr):
        return trlx_tpu.train(
            reward_fn=lambda samples, queries, response_gt=None: [
                float(len(s)) for s in samples
            ],
            prompts=prompts,
            config=TRLConfig.from_dict(config_dict),
        )


#: retry overrides for the smoke: real backoff shape, test-speed delays
FAST_RETRY = {"max_attempts": 4, "base_delay_s": 0.01, "max_delay_s": 0.05}


def scenario_clean(workdir: str) -> Dict[str, Any]:
    from trlx_tpu.resilience import chaos
    from trlx_tpu.utils.retry import retry_log

    trainer = _train(
        tiny_config_dict(
            os.path.join(workdir, "ckpt"), total_steps=4,
            resilience={"enabled": True},
        )
    )
    return {
        "final_step": int(trainer.state.step),
        "chaos_events": len(chaos.events()),
        "retries": len(retry_log),
        "passed": (
            int(trainer.state.step) == 4
            and not chaos.events()
            and not retry_log
        ),
    }


def scenario_transient_checkpoint_io(workdir: str) -> Dict[str, Any]:
    from trlx_tpu.utils.checkpoint import has_checkpoint
    from trlx_tpu.utils.retry import retry_log

    ckpt = os.path.join(workdir, "ckpt")
    trainer = _train(
        tiny_config_dict(
            ckpt, total_steps=2,
            resilience={
                "enabled": True,
                "retry": dict(FAST_RETRY),
                "chaos": [
                    {"site": "checkpoint.save", "mode": "error", "count": 2}
                ],
            },
        )
    )
    save_retries = [
        r for r in retry_log if "checkpoint save" in r["what"]
    ]
    return {
        "final_step": int(trainer.state.step),
        "save_retries": len(save_retries),
        "checkpoint_exists": has_checkpoint(ckpt),
        "passed": (
            int(trainer.state.step) == 2
            and len(save_retries) == 2  # failed twice, succeeded third
            and has_checkpoint(ckpt)
        ),
    }


def scenario_permanent_mismatch(workdir: str) -> Dict[str, Any]:
    """Both flavors of permanent: a REAL orbax structure mismatch and an
    injected one — neither may consume a retry."""
    import jax.numpy as jnp

    from trlx_tpu.resilience import chaos
    from trlx_tpu.utils.checkpoint import load_checkpoint, save_checkpoint
    from trlx_tpu.utils.retry import reset_retry_log, retry_log

    d = os.path.join(workdir, "ckpt")
    save_checkpoint(
        d,
        {"a": jnp.zeros((4,)), "b": jnp.ones((4,))},
        metadata={"kl_coef": 0.1},
    )

    real_refused = injected_refused = False
    real_error = injected_error = ""
    reset_retry_log()
    try:
        # restore under a different train-state structure: must refuse
        # fast with the actionable translation, not die deep in orbax
        # and not retry
        load_checkpoint(d, {"a": jnp.zeros((4,))})
    except ValueError as e:
        real_refused = True
        real_error = str(e)[:160]
    except Exception as e:  # wrong type = taxonomy failure
        real_error = f"{type(e).__name__}: {e}"[:160]
    real_no_retry = not retry_log

    chaos.configure(
        [{"site": "checkpoint.load", "mode": "permanent", "count": 1}]
    )
    try:
        load_checkpoint(d, {"a": jnp.zeros((4,)), "b": jnp.ones((4,))})
    except ValueError as e:
        injected_refused = True
        injected_error = str(e)[:160]
    except Exception as e:
        injected_error = f"{type(e).__name__}: {e}"[:160]
    finally:
        chaos.clear()
    injected_no_retry = not retry_log

    return {
        "real_refused_fast": real_refused and real_no_retry,
        "real_error": real_error,
        "injected_refused_fast": injected_refused and injected_no_retry,
        "injected_error": injected_error,
        "passed": (
            real_refused
            and real_no_retry
            and injected_refused
            and injected_no_retry
        ),
    }


def scenario_preempt_resume_parity(workdir: str) -> Dict[str, Any]:
    import jax
    import numpy as np

    # run A: uninterrupted, 3 phases
    a = _train(
        tiny_config_dict(
            os.path.join(workdir, "ckpt_a"), total_steps=6,
            resilience={"enabled": True},
        )
    )
    ref_params = jax.device_get(a.state.params)
    ref_step = int(a.state.step)
    ref_kl = float(jax.device_get(a.kl_coef))
    del a

    # run B: SIGTERM delivered at phase 0's boundary (a REAL signal via
    # os.kill) — drain writes the emergency checkpoint, the supervisor
    # restarts resuming from it, and the run must land bitwise where A
    # did
    b = _train(
        tiny_config_dict(
            os.path.join(workdir, "ckpt_b"), total_steps=6,
            resilience={
                "enabled": True,
                "chaos": [
                    {"site": "preempt", "mode": "preempt", "phase": 0}
                ],
            },
        )
    )
    cur_params = jax.device_get(b.state.params)
    bitwise = all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(
            jax.tree_util.tree_leaves(ref_params),
            jax.tree_util.tree_leaves(cur_params),
        )
    )
    kl_equal = float(jax.device_get(b.kl_coef)) == ref_kl
    return {
        "final_step": int(b.state.step),
        "params_bitwise_equal": bitwise,
        "kl_coef_equal": kl_equal,
        "passed": (
            # asserted on outcomes: the phase-0 preempt spec fires
            # deterministically, so a run that completed at the right
            # step with bitwise parity can only have gotten there
            # through drain -> emergency checkpoint -> supervised resume
            int(b.state.step) == ref_step
            and bitwise
            and kl_equal
        ),
    }


def scenario_engine_fallback(workdir: str) -> Dict[str, Any]:
    trainer = _train(
        tiny_config_dict(
            os.path.join(workdir, "ckpt"), total_steps=2,
            resilience={
                "enabled": True,
                "chaos": [
                    {"site": "engine.admit", "mode": "error", "count": 1}
                ],
            },
            rollout={"engine": "continuous"},
            health={"enabled": True},
        )
    )
    counts = (
        trainer.health_monitor.event_counts
        if trainer.health_monitor is not None
        else {}
    )
    return {
        "final_step": int(trainer.state.step),
        "engine_after": trainer.rollout_engine,
        "fallback_events": counts.get("engine-fallback", 0),
        "passed": (
            int(trainer.state.step) == 2
            and trainer.rollout_engine == "fixed"
            and counts.get("engine-fallback", 0) == 1
        ),
    }


def scenario_writer_disk_full(workdir: str) -> Dict[str, Any]:
    import json

    log_dir = os.path.join(workdir, "rollouts")
    trainer = _train(
        tiny_config_dict(
            os.path.join(workdir, "ckpt"), total_steps=2,
            resilience={
                "enabled": True,
                "chaos": [
                    # three consecutive ENOSPC: enough to trip the
                    # degrade threshold, then the "disk" recovers
                    {"site": "writer.write", "mode": "disk_full",
                     "count": 3}
                ],
            },
            rollout_logging_dir=log_dir,
        )
    )
    rows = []
    for root, _, files in os.walk(log_dir):
        for name in sorted(files):
            with open(os.path.join(root, name)) as f:
                rows += [json.loads(line) for line in f]
    return {
        "final_step": int(trainer.state.step),
        "rows_durable": len(rows),
        "passed": int(trainer.state.step) == 2 and len(rows) == 16,
    }


_SCENARIO_FNS: Dict[str, Callable[[str], Dict[str, Any]]] = {
    "clean": scenario_clean,
    "transient_checkpoint_io": scenario_transient_checkpoint_io,
    "permanent_mismatch": scenario_permanent_mismatch,
    "preempt_resume_parity": scenario_preempt_resume_parity,
    "engine_fallback": scenario_engine_fallback,
    "writer_disk_full": scenario_writer_disk_full,
}


def run_chaos_smoke(
    workdir: str = None, only: List[str] = None
) -> Dict[str, Any]:
    """Run the scenarios; returns a JSON-able summary with ``passed``."""
    from trlx_tpu.resilience import chaos
    from trlx_tpu.utils.retry import reset_retry_log

    workdir = workdir or tempfile.mkdtemp(prefix="chaos-smoke-")
    names = list(only or SCENARIOS)
    unknown = set(names) - set(_SCENARIO_FNS)
    if unknown:
        raise ValueError(
            f"unknown chaos-smoke scenario(s) {sorted(unknown)}; "
            f"known: {list(SCENARIOS)}"
        )
    results: Dict[str, Dict[str, Any]] = {}
    for name in names:
        chaos.clear()
        reset_retry_log()
        scenario_dir = os.path.join(workdir, name)
        os.makedirs(scenario_dir, exist_ok=True)
        try:
            results[name] = _SCENARIO_FNS[name](scenario_dir)
        except Exception as e:  # a scenario crash is a FAIL, not a crash
            results[name] = {
                "passed": False,
                "error": f"{type(e).__name__}: {e}",
            }
        finally:
            chaos.clear()
            reset_retry_log()
    return {
        "passed": all(r.get("passed") for r in results.values()),
        "scenarios": results,
        "workdir": workdir,
    }


def format_smoke_text(summary: Dict[str, Any]) -> str:
    lines = []
    for name, result in summary["scenarios"].items():
        status = "PASS" if result.get("passed") else "FAIL"
        detail = ", ".join(
            f"{k}={v}" for k, v in result.items() if k != "passed"
        )
        lines.append(f"{status}  {name}: {detail}")
    lines.append(
        "chaos-smoke: " + ("PASS" if summary["passed"] else "FAIL")
    )
    return "\n".join(lines)
