"""Compiled-HLO lowering audit: gate what XLA emitted against jaxpr intent.

Engine 13 of ``trlx_tpu.analysis``. Every other engine reasons at the
jaxpr level, but the repo's two worst correctness bugs lived *below* it:
XLA's SPMD partitioner mis-lowering an eager sharded ``jnp.concatenate``
into a replica-axis SUM (PR 2 — NaN divergence on fsdp×tp), and the
still-quarantined pp cached-decode ``jnp.stack`` miscompile
(``tools/pp_miscompile_repro.py``). Both are invisible to jaxpr rules by
construction: the jaxpr is *intent*; the optimized post-SPMD module is
what the TPU runs. This engine AOT-lowers and compiles every traced
program from the harness (``jit_fn.lower(*example_args).compile()`` on
the CPU audit mesh, with the trainers' real ``in_shardings``), parses
``compiled.as_text()`` + ``memory_analysis()``, and gates the artifact:

- ``lowering-collective-drift`` (error) — three sub-checks: (a) any
  all-reduce whose metadata attributes to a ``concatenate``/``stack`` op
  (a concat must never lower to a cross-replica reduction — the exact
  PR-2 signature, caught with no lockfile needed); (b) every *explicit*
  jaxpr collective (engine 5's sequence) must survive into the compiled
  module as its HLO counterpart; (c) the per-program collective profile
  (``kind[axes]|dtype`` → count) must match the committed ``hlo_budgets``
  lockfile exactly — an inserted, dropped, or re-axised collective is a
  lowering change that needs human review, not a silent drive-by.
- ``hlo-dtype-upcast`` (warning) — non-scalar f32 tensors minted from
  bf16 inputs by ``convert`` in the optimized module, outside the
  curated allowlist (softmax/layernorm/loss accumulation own their f32).
- ``hlo-memory-drift`` (error) — the compiled buffer-assignment peak
  (temp + args + outputs − donation aliasing) vs the per-program
  ``hlo_budgets`` entry, with engine-7-style tolerance.
- ``spmd-concat-hazard`` (error) — the jaxpr-side tripwire for the PR-2
  class, replacing the ROADMAP "watch for eager multi-operand
  concat/stack of committed-sharded arrays" human obligation: a
  multi-operand ``concatenate`` eqn whose operands taint back to
  committed-sharded program inputs, on a mesh with a spare size>1 axis,
  outside the blessed ``spmd_stack``/``concat_cols`` helpers (which
  build via ``dynamic_update_slice`` and never emit ``concatenate``).

Plus a **known-miscompile registry** (:data:`KNOWN_MISCOMPILES`): the
quarantined lowerings are pinned as *expected-divergence* entries keyed
to the jaxlib versions they were verified broken on. A fixing jaxlib
bump mechanically flips the entry to a stale-quarantine finding telling
the builder which workaround to retire — no human re-running repros
after version bumps. ``--plant-hazard`` is the engine's self-check: it
compiles a seeded eager sharded concat and must trip BOTH
``spmd-concat-hazard`` (at the planted line) and
``lowering-collective-drift`` (on the minted replica-axis all-reduce).
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from trlx_tpu.analysis.findings import (
    SEVERITY_WARNING,
    Finding,
    Report,
    filter_suppressed,
)
from trlx_tpu.analysis.registry import get_rule

# Mesh axis order of every repo mesh (parallel/mesh.py::make_mesh builds
# the device ndarray row-major over exactly these axes from the flat
# jax.devices() list) — lets the parser map the flat device ids in HLO
# replica_groups back to named mesh axes.
MESH_AXIS_ORDER = ("dp", "fsdp", "tp", "sp", "pp", "ep")

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16,
}

# HLO collective opcodes audited, with async -start forms folded into
# their sync spelling (-done carries no groups and is skipped).
_COLLECTIVE_KINDS = (
    "all-reduce", "all-gather", "reduce-scatter", "collective-permute",
    "all-to-all",
)


# ----------------------------- HLO parsing ------------------------------ #

@dataclass
class HloCollective:
    """One collective instruction of an optimized post-SPMD module."""

    kind: str                      # canonical opcode, e.g. "all-reduce"
    dtype: str                     # element type of the (first) result
    elems: int                     # element count across the result tuple
    bytes: int                     # payload bytes across the result tuple
    groups: Optional[List[List[int]]] = None   # expanded replica_groups
    pairs: Optional[List[Tuple[int, int]]] = None  # collective-permute
    to_apply: str = ""             # reduction computation name, if any
    op_name: str = ""              # metadata op_name (jaxpr provenance)
    source_file: str = ""
    source_line: int = 0

    def axes(self, mesh_shape: Optional[Dict[str, int]]) -> Tuple[str, ...]:
        return infer_collective_axes(self, mesh_shape)


_SHAPE_RE = re.compile(r"(\w+)\[([0-9,]*)\]")
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*(\([^)]*\)|\w+\[[0-9,]*\](?:\{[^}]*\})?)\s+"
    r"(all-reduce|all-gather|reduce-scatter|collective-permute|all-to-all)"
    r"(-start)?\("
)
_EXPLICIT_GROUPS_RE = re.compile(r"replica_groups=\{(\{[^=]*?\})\}")
_IOTA_GROUPS_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?"
)
_PAIRS_RE = re.compile(r"source_target_pairs=\{([^}]*(?:\},\{[^}]*)*)\}")
_TO_APPLY_RE = re.compile(r"to_apply=%?([\w.\-]+)")
_METADATA_RE = re.compile(r"metadata=\{([^}]*)\}")
_OP_NAME_RE = re.compile(r'op_name="([^"]*)"')
_SOURCE_FILE_RE = re.compile(r'source_file="([^"]*)"')
_SOURCE_LINE_RE = re.compile(r"source_line=(\d+)")


def _parse_shape(shape_text: str) -> Tuple[str, int, int]:
    """(first dtype, total elements, total bytes) of a shape or a tuple
    of shapes, e.g. ``f32[32,32]{1,0}`` or ``(f32[32,32], f32[32])``."""
    dtype, elems, total = "", 0, 0
    for m in _SHAPE_RE.finditer(shape_text):
        dt, dims = m.group(1), m.group(2)
        if dt not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        dtype = dtype or dt
        elems += n
        total += n * _DTYPE_BYTES[dt]
    return dtype, elems, total


def expand_replica_groups(line: str) -> Optional[List[List[int]]]:
    """Expanded replica groups of one HLO instruction line, handling the
    explicit ``{{0,1},{2,3}}`` form and both iota forms
    ``[g,s]<=[dims]`` / ``[g,s]<=[dims]T(perm)``."""
    m = _EXPLICIT_GROUPS_RE.search(line)
    if m:
        return [
            [int(d) for d in grp.split(",") if d.strip()]
            for grp in re.findall(r"\{([^{}]*)\}", m.group(1) + "}")
            if grp.strip()
        ]
    m = _IOTA_GROUPS_RE.search(line)
    if m:
        n_groups, group_size = int(m.group(1)), int(m.group(2))
        dims = [int(d) for d in m.group(3).split(",")]
        perm = (
            [int(p) for p in m.group(4).split(",")]
            if m.group(4)
            else list(range(len(dims)))
        )
        total = 1
        for d in dims:
            total *= d
        # iota(total) reshaped to dims, transposed by perm, flattened,
        # then chunked into groups — the HLO IotaReplicaGroupList spec
        import numpy as np

        flat = (
            np.arange(total).reshape(dims).transpose(perm).reshape(-1)
        )
        if n_groups * group_size != total:
            return None
        return flat.reshape(n_groups, group_size).tolist()
    return None


def _parse_pairs(line: str) -> Optional[List[Tuple[int, int]]]:
    m = _PAIRS_RE.search(line)
    if not m:
        return None
    return [
        (int(a), int(b))
        for a, b in re.findall(r"\{(\d+),(\d+)\}", "{" + m.group(1) + "}")
    ]


def parse_hlo_collectives(hlo_text: str) -> List[HloCollective]:
    """All collective instructions of an optimized module, in text order
    (async ``-start`` forms folded; ``-done`` carries no new info)."""
    out: List[HloCollective] = []
    for line in hlo_text.splitlines():
        m = _INSTR_RE.match(line)
        if m is None:
            continue
        dtype, elems, nbytes = _parse_shape(m.group(1))
        meta = _METADATA_RE.search(line)
        meta_text = meta.group(1) if meta else ""
        op_name_m = _OP_NAME_RE.search(meta_text)
        src_file_m = _SOURCE_FILE_RE.search(meta_text)
        src_line_m = _SOURCE_LINE_RE.search(meta_text)
        to_apply_m = _TO_APPLY_RE.search(line)
        out.append(
            HloCollective(
                kind=m.group(2),
                dtype=dtype,
                elems=elems,
                bytes=nbytes,
                groups=expand_replica_groups(line),
                pairs=_parse_pairs(line),
                to_apply=to_apply_m.group(1) if to_apply_m else "",
                op_name=op_name_m.group(1) if op_name_m else "",
                source_file=src_file_m.group(1) if src_file_m else "",
                source_line=int(src_line_m.group(1)) if src_line_m else 0,
            )
        )
    return out


def _device_coords(dev: int, sizes: Sequence[int]) -> Tuple[int, ...]:
    coords = []
    for s in reversed(sizes):
        coords.append(dev % s)
        dev //= s
    return tuple(reversed(coords))


def infer_collective_axes(
    c: HloCollective, mesh_shape: Optional[Dict[str, int]]
) -> Tuple[str, ...]:
    """Named mesh axes a collective's groups span (device ids map back
    to mesh coordinates row-major over :data:`MESH_AXIS_ORDER` — how
    ``make_mesh`` lays the flat device list out)."""
    if not mesh_shape:
        return ("?",)
    names = [a for a in MESH_AXIS_ORDER if a in mesh_shape]
    sizes = [int(mesh_shape[a]) for a in names]
    varying: Set[str] = set()
    if c.groups:
        for group in c.groups:
            coords = [_device_coords(d, sizes) for d in group]
            for i, name in enumerate(names):
                if len({co[i] for co in coords}) > 1:
                    varying.add(name)
    elif c.pairs:
        for src, dst in c.pairs:
            a, b = _device_coords(src, sizes), _device_coords(dst, sizes)
            for i, name in enumerate(names):
                if a[i] != b[i]:
                    varying.add(name)
    else:
        # no groups attribute => the collective spans all devices
        varying = {n for n, s in zip(names, sizes) if s > 1}
    if not varying:
        return ("self",)
    return tuple(sorted(varying))


def collective_profile(
    collectives: Sequence[HloCollective],
    mesh_shape: Optional[Dict[str, int]],
) -> Dict[str, int]:
    """Count collectives keyed ``kind[axes]|dtype`` — the locked shape
    of a program's compiled collective schedule. Counts (not sequences):
    XLA reorders freely, but minting, dropping, or re-axising a
    collective changes a key."""
    profile: Dict[str, int] = {}
    for c in collectives:
        key = f"{c.kind}[{','.join(c.axes(mesh_shape))}]|{c.dtype}"
        profile[key] = profile.get(key, 0) + 1
    return profile


# -------------------------- dtype-upcast scan --------------------------- #

# f32 compute legitimately minted from bf16 in the optimized module —
# mirrors jaxpr_audit.PRECISION_ALLOWLIST but keys on HLO metadata
# op_name (the jaxpr-provenance path XLA threads through optimization).
HLO_UPCAST_ALLOWLIST = (
    r"softmax", r"log_softmax", r"logsumexp", r"layer_norm", r"layernorm",
    r"rms_norm", r"norm/", r"loss", r"entropy", r"kl", r"logprob",
    r"cross_entropy", r"attention_weights", r"reduce_sum", r"reduce_mean",
    r"/mean", r"/sum", r"/var", r"gae", r"returns", r"advantage",
    r"cumsum", r"cumlogsumexp", r"global_norm", r"clip_by_global_norm",
    r"adam", r"optimizer", r"whiten", r"/dot_general",
    # f32 attention-score path: logits/weights compute in f32
    # (preferred_element_type) and cast back — numerics by design
    r"attn/", r"attention/",
    # LM heads mint f32 logits for stable softmax/log-softmax
    r"logits",
    # T5 RMSNorm scopes (`ln_self`/`ln_cross`/`ln_mlp`) accumulate f32
    r"/ln_",
)

# source files whose converts are f32-by-design end to end — the HLO
# twin of jaxpr_audit.PRECISION_ALLOWLIST's whole-file entries, keyed on
# the metadata source_file suffix (op_name scopes vary with AD/fusion,
# the authoring file does not)
HLO_UPCAST_SOURCE_ALLOWLIST = (
    "ops/ppo_math.py",        # loss + GAE math is f32 by contract
    "ops/ilql_math.py",       # loss math is f32 by contract
    "parallel/collectives.py",  # whitening/logprob reductions
    "trainer/common.py",      # optimizer moment upcasts
    "ops/attention.py",       # f32 softmax accumulation contract
    "ops/flash_attention.py",
    "ops/ring_attention.py",
    "models/t5.py",           # T5 consumes f32 directly by parity contract
    "models/heads.py",        # MLPHead fc2 computes in f32
)
_UPCAST_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*f32\[([0-9,]+)\](?:\{[^}]*\})?\s+"
    r"convert\([^)]*\)"
)


@dataclass
class DtypeUpcast:
    shape: str
    op_name: str
    source_file: str
    source_line: int


def extract_dtype_upcasts(hlo_text: str) -> List[DtypeUpcast]:
    """Non-scalar (rank≥2) f32 ``convert`` results in an optimized
    module, outside :data:`HLO_UPCAST_ALLOWLIST`. Scalars and vectors
    are reduction/accumulator plumbing (every all-reduce region converts
    its bf16 operands) — only activation-rank tensors double HBM
    traffic, which is what the bf16 compute contract protects.

    Converts with no ``op_name`` metadata are skipped: those are
    compiler-minted fusion/rematerialization plumbing (the clean tree
    carries ~15k of them, all at loop-carried scan shapes) that can
    neither be attributed to source nor curated through the allowlist —
    the rule audits *authored* f32 compute that survived into the
    optimized module. Repeated instances of the same authored convert
    (per-layer scans, AD transposes) are deduplicated to one report."""
    out: List[DtypeUpcast] = []
    seen: Set[Tuple[str, str, str, int]] = set()
    allow = re.compile("|".join(HLO_UPCAST_ALLOWLIST))
    for line in hlo_text.splitlines():
        m = _UPCAST_RE.match(line)
        if m is None or "bf16[" not in line:
            continue
        dims = m.group(1)
        if dims.count(",") < 1:  # rank < 2
            continue
        meta = _METADATA_RE.search(line)
        meta_text = meta.group(1) if meta else ""
        op_name_m = _OP_NAME_RE.search(meta_text)
        op_name = op_name_m.group(1) if op_name_m else ""
        if not op_name:  # unattributable compiler plumbing
            continue
        if allow.search(op_name):
            continue
        src_file_m = _SOURCE_FILE_RE.search(meta_text)
        src_line_m = _SOURCE_LINE_RE.search(meta_text)
        source_file = src_file_m.group(1) if src_file_m else ""
        source_line = int(src_line_m.group(1)) if src_line_m else 0
        if source_file.endswith(HLO_UPCAST_SOURCE_ALLOWLIST):
            continue
        key = (f"f32[{dims}]", op_name, source_file, source_line)
        if key in seen:
            continue
        seen.add(key)
        out.append(
            DtypeUpcast(
                shape=f"f32[{dims}]",
                op_name=op_name,
                source_file=source_file,
                source_line=source_line,
            )
        )
    return out


# --------------------------- compiled program --------------------------- #

@dataclass
class CompiledProgram:
    """One AOT-compiled traced program plus its parsed ground truth."""

    subject: str
    mesh_label: str
    mesh_shape: Optional[Dict[str, int]]
    collectives: List[HloCollective] = field(default_factory=list)
    profile: Dict[str, int] = field(default_factory=dict)
    collective_bytes: int = 0
    upcasts: List[DtypeUpcast] = field(default_factory=list)
    # buffer-assignment stats from compiled.memory_analysis()
    temp_bytes: int = 0
    argument_bytes: int = 0
    output_bytes: int = 0
    alias_bytes: int = 0
    def_site: Optional[Tuple[str, int]] = None
    explicit_intent: List[Tuple[str, Tuple[str, ...], str]] = field(
        default_factory=list
    )

    @property
    def peak_bytes(self) -> int:
        """Live-at-entry + temporaries − donation aliasing: the
        compiled counterpart of engine 7's static peak."""
        return max(
            0,
            self.temp_bytes + self.argument_bytes + self.output_bytes
            - self.alias_bytes,
        )

    def budget_entry(self) -> Dict:
        return {
            "collectives": {k: self.profile[k] for k in sorted(self.profile)},
            "collective_bytes": int(self.collective_bytes),
            "peak_bytes": int(self.peak_bytes),
            "temp_bytes": int(self.temp_bytes),
            "argument_bytes": int(self.argument_bytes),
            "output_bytes": int(self.output_bytes),
            "alias_bytes": int(self.alias_bytes),
        }


def _mesh_label(mesh_shape: Optional[Dict[str, int]]) -> str:
    if not mesh_shape:
        return "?"
    return (
        "/".join(
            f"{k}={v}" for k, v in sorted(mesh_shape.items()) if int(v) != 1
        )
        or "single-axis"
    )


def compile_program(program) -> CompiledProgram:
    """AOT-lower and compile one harness program; parse the optimized
    module and buffer-assignment stats into a :class:`CompiledProgram`."""
    lowered = program.jit_fn.lower(*program.example_args)
    compiled = lowered.compile()
    hlo_text = compiled.as_text()
    cp = CompiledProgram(
        subject=program.subject,
        mesh_label=_mesh_label(program.mesh_shape),
        mesh_shape=program.mesh_shape,
        collectives=parse_hlo_collectives(hlo_text),
        upcasts=extract_dtype_upcasts(hlo_text),
        def_site=program.def_site,
    )
    cp.profile = collective_profile(cp.collectives, cp.mesh_shape)
    cp.collective_bytes = sum(c.bytes for c in cp.collectives)
    try:
        mem = compiled.memory_analysis()
        cp.temp_bytes = int(getattr(mem, "temp_size_in_bytes", 0))
        cp.argument_bytes = int(getattr(mem, "argument_size_in_bytes", 0))
        cp.output_bytes = int(getattr(mem, "output_size_in_bytes", 0))
        cp.alias_bytes = int(getattr(mem, "alias_size_in_bytes", 0))
    except Exception:
        pass
    from trlx_tpu.analysis.collective_trace import collective_sequence

    cp.explicit_intent = collective_sequence(program.closed_jaxpr)
    return cp


# --------------------- lowering-collective-drift rule ------------------- #

# jaxpr collective primitive -> the HLO opcode GSPMD lowers it to
_PRIM_TO_HLO = {
    "psum": "all-reduce",
    "psum2": "all-reduce",
    "psum_invariant": "all-reduce",
    "pmax": "all-reduce",
    "pmin": "all-reduce",
    "all_gather": "all-gather",
    "reduce_scatter": "reduce-scatter",
    "ppermute": "collective-permute",
    "pbroadcast": "collective-permute",
    "all_to_all": "all-to-all",
}

_CONCAT_OP_RE = re.compile(r"(?:^|/)(concatenate|stack)(?:\[|$|/)")

# JAX-library scopes whose internal concatenates legitimately lower to
# a zero-pad + all-reduce(add) shard combine: threefry bit generation
# (`_uniform`/`_gumbel`/`_normal` concat the two u32 output halves of
# replicated PRNG state, and the partitioner recombines by summing
# disjoint nonzero shards — a correct partial-value lowering, verified
# concretely by the sanitizer replays). The PR-2 signature is an
# all-reduce minted from a *repo-authored* concat of committed-sharded
# data, whose op scope never crosses these private jax.random frames.
_CONCAT_EXEMPT_OPS = re.compile(
    r"jit\(_uniform\)|jit\(_gumbel\)|jit\(_normal\)|threefry|random_bits"
)


def concat_minted_collectives(
    collectives: Sequence[HloCollective],
) -> List[HloCollective]:
    """All-reduces whose jaxpr provenance is a ``concatenate``/``stack``
    op — outside the jax.random bit-gen scopes above, a concat must
    never lower to a cross-replica reduction, so any hit is the PR-2
    replica-sum signature regardless of lockfiles."""
    return [
        c
        for c in collectives
        if c.kind == "all-reduce"
        and _CONCAT_OP_RE.search(c.op_name)
        and not _CONCAT_EXEMPT_OPS.search(c.op_name)
    ]


def check_lowering_drift(
    cp: CompiledProgram,
    locked_entry: Optional[Dict],
    budgets_where: str = "budgets.json",
) -> List[Finding]:
    """The three ``lowering-collective-drift`` sub-checks for one
    compiled program (concat-minted sums, explicit-intent survival,
    locked-profile equality)."""
    rule = get_rule("lowering-collective-drift")
    findings: List[Finding] = []
    file, line = cp.def_site or (None, None)

    for c in concat_minted_collectives(cp.collectives):
        axes = ",".join(c.axes(cp.mesh_shape))
        findings.append(
            Finding(
                rule=rule.id,
                message=(
                    f"XLA lowered a concatenate/stack in `{cp.subject}` "
                    f"to a replica-axis all-reduce over [{axes}] "
                    f"({c.dtype}, {c.elems} elems, reduction "
                    f"`{c.to_apply}`, op {c.op_name!r}) — the PR-2 "
                    "sharded-concat miscompile signature; route the "
                    "concat through spmd_stack/concat_cols "
                    "(dynamic_update_slice never mis-lowers)"
                ),
                severity=rule.severity,
                file=file,
                line=line,
                subject=cp.subject,
                engine="hlo",
            )
        )

    # explicit jaxpr collectives must survive lowering as their HLO kind
    compiled_kinds = {c.kind for c in cp.collectives}
    for prim, axes, _detail in cp.explicit_intent:
        want = _PRIM_TO_HLO.get(prim)
        if want is None:
            continue
        if want not in compiled_kinds:
            findings.append(
                Finding(
                    rule=rule.id,
                    message=(
                        f"jaxpr of `{cp.subject}` names an explicit "
                        f"`{prim}` over {list(axes)} but the optimized "
                        f"module contains no {want} — XLA dropped or "
                        "rewrote a collective the program author wrote"
                    ),
                    severity=rule.severity,
                    file=file,
                    line=line,
                    subject=cp.subject,
                    engine="hlo",
                )
            )

    if locked_entry is not None:
        locked = {
            k: int(v)
            for k, v in (locked_entry.get("collectives") or {}).items()
        }
        if locked != cp.profile:
            drift = []
            for key in sorted(set(locked) | set(cp.profile)):
                a, b = locked.get(key, 0), cp.profile.get(key, 0)
                if a != b:
                    drift.append(f"{key}: {a} -> {b}")
            findings.append(
                Finding(
                    rule=rule.id,
                    message=(
                        f"compiled collective profile of `{cp.subject}` "
                        f"drifted from {budgets_where}: "
                        + "; ".join(drift)
                        + " — XLA inserted/dropped/re-axised a "
                        "collective; review the lowering and relock "
                        "with --hlo-audit --update-budgets"
                    ),
                    severity=rule.severity,
                    file=file,
                    line=line,
                    subject=cp.subject,
                    engine="hlo",
                )
            )
    return findings


def check_dtype_upcasts(cp: CompiledProgram) -> List[Finding]:
    rule = get_rule("hlo-dtype-upcast")
    findings: List[Finding] = []
    file, line = cp.def_site or (None, None)
    for u in cp.upcasts:
        where = (
            f" (from {os.path.basename(u.source_file)}:{u.source_line})"
            if u.source_file
            else ""
        )
        findings.append(
            Finding(
                rule=rule.id,
                message=(
                    f"optimized module of `{cp.subject}` mints "
                    f"{u.shape} from bf16 at op {u.op_name!r}{where} — "
                    "f32 compute outside the softmax/layernorm/loss "
                    "allowlist doubles that tensor's HBM traffic; cast "
                    "back to the compute dtype or extend "
                    "HLO_UPCAST_ALLOWLIST with a justification"
                ),
                severity=rule.severity,
                file=file,
                line=line,
                subject=cp.subject,
                engine="hlo",
            )
        )
    return findings


def check_memory_drift(
    cp: CompiledProgram,
    locked_entry: Optional[Dict],
    tolerance_pct: float,
    budgets_where: str = "budgets.json",
) -> List[Finding]:
    rule = get_rule("hlo-memory-drift")
    file, line = cp.def_site or (None, None)
    if locked_entry is None:
        return [
            Finding(
                rule=rule.id,
                message=(
                    f"no committed hlo budget for `{cp.subject}` "
                    f"(compiled peak {cp.peak_bytes} B observed) — run "
                    "--hlo-audit --update-budgets and review the "
                    "lockfile diff"
                ),
                severity=rule.severity,
                file=file,
                line=line,
                subject=cp.subject,
                engine="hlo",
            )
        ]
    locked_peak = int(locked_entry.get("peak_bytes", 0))
    tol = float(locked_entry.get("tolerance_pct", tolerance_pct))
    if locked_peak and cp.peak_bytes > locked_peak * (1 + tol / 100.0):
        pct = 100.0 * (cp.peak_bytes - locked_peak) / locked_peak
        return [
            Finding(
                rule=rule.id,
                message=(
                    f"compiled buffer-assignment peak of `{cp.subject}` "
                    f"grew {pct:.1f}% past {budgets_where} "
                    f"({locked_peak} -> {cp.peak_bytes} B, tolerance "
                    f"{tol:g}%) — a lowering or fusion change regressed "
                    "live memory; review, then relock with "
                    "--hlo-audit --update-budgets"
                ),
                severity=rule.severity,
                file=file,
                line=line,
                subject=cp.subject,
                engine="hlo",
            )
        ]
    return []


# ------------------------- spmd-concat-hazard --------------------------- #

# helpers blessed to assemble sharded arrays (both build their result
# with dynamic_update_slice and never emit a `concatenate` eqn — seeing
# one attributed to them would itself be news)
BLESSED_CONCAT_HELPERS = ("spmd_stack", "concat_cols")


def check_concat_hazard(program, repo_root: Optional[str] = None) -> List[Finding]:
    """Jaxpr walk for the PR-2 hazard *class*: a multi-operand
    ``concatenate`` **along a mesh-split dimension** whose operands
    taint back to committed-sharded program inputs (``input_divisors``
    > 1), on a mesh that actually distributes (some axis size > 1),
    outside the blessed helpers. Concatenating along a *replicated*
    dimension of sharded operands (e.g. ``[query; response]`` along the
    sequence axis of batch-sharded rollout tensors) lowers to a local
    per-shard concat and is benign — only the along-the-split shape
    forces the partitioner reshard that GSPMD has twice mis-lowered
    into a replica-axis SUM in this repo's history. Taint carries the
    set of candidate split dimensions per value (seeded from
    ``input_sharded_dims`` when the harness recorded them, else every
    dimension of a sharded input) and propagates as a union — crude
    across reshapes/transposes, but the hazard shape in practice
    concatenates program inputs directly."""
    from jax._src.core import Literal

    from trlx_tpu.analysis.jaxpr_audit import (
        _repo_frame,
        _sub_jaxprs,
        default_repo_root,
    )

    rule = get_rule("spmd-concat-hazard")
    repo_root = repo_root or default_repo_root()
    findings: List[Finding] = []
    mesh_shape = program.mesh_shape or {}
    if not any(v > 1 for v in mesh_shape.values()):
        return findings  # single-device mesh cannot mis-partition
    divisors = program.input_divisors or []
    sharded_dims = getattr(program, "input_sharded_dims", None)

    def _rank(v) -> int:
        return len(getattr(getattr(v, "aval", None), "shape", ()) or ())

    def _shift(dims: frozenset, src_rank: int, dst_rank: int) -> frozenset:
        """Re-index taint dims across a rank change by trailing
        alignment: a scan/loop body slicing the stacked leading axis
        (or a squeeze/broadcast of it) keeps the trailing layout, so
        the batch axis that was dim 1 of ``(n_mb, batch, seq)`` is dim
        0 of the ``(batch, seq)`` slice. Wrong for transposes — the
        hazard shape in practice never reorders the split axis."""
        delta = src_rank - dst_rank
        if delta == 0:
            return dims
        return frozenset(
            d - delta for d in dims if 0 <= d - delta < max(dst_rank, 1)
        )

    def walk(jaxpr, tainted: Dict[Any, frozenset]) -> bool:
        """Returns True when any outvar of ``jaxpr`` is tainted."""
        for eqn in jaxpr.eqns:
            hot_in = [
                v
                for v in eqn.invars
                if not isinstance(v, Literal) and v in tainted
            ]
            in_dims = frozenset().union(*(tainted[v] for v in hot_in))
            in_taint = bool(in_dims)
            if eqn.primitive.name == "concatenate":
                dim = int(eqn.params.get("dimension", 0))
                operands = [
                    v
                    for v in eqn.invars
                    if not isinstance(v, Literal)
                ]
                hot = [
                    v
                    for v in operands
                    if dim in tainted.get(v, frozenset())
                ]
                if len(operands) >= 2 and len(hot) >= 2:
                    frame = _repo_frame(eqn, repo_root)
                    fn_name = getattr(frame, "function_name", "") if frame else ""
                    if fn_name not in BLESSED_CONCAT_HELPERS:
                        file = frame.file_name if frame else None
                        line = frame.start_line if frame else None
                        findings.append(
                            Finding(
                                rule=rule.id,
                                message=(
                                    "eager multi-operand concatenate of "
                                    "committed-sharded operands in "
                                    f"`{program.subject}` on mesh "
                                    f"{_mesh_label(mesh_shape)} — the "
                                    "PR-2 miscompile class (XLA's SPMD "
                                    "partitioner has minted a "
                                    "replica-axis SUM from this shape); "
                                    "assemble via spmd_stack/concat_cols "
                                    "instead"
                                ),
                                severity=rule.severity,
                                file=file,
                                line=line,
                                subject=program.subject,
                                engine="hlo",
                            )
                        )
            # conservative taint propagation, recursing into sub-jaxprs
            # with the eqn-level taint mapped onto their invars
            for sub in _sub_jaxprs(eqn):
                inner = getattr(sub, "jaxpr", sub)
                sub_taint: Dict[Any, frozenset] = {}
                n = min(len(inner.invars), len(eqn.invars))
                for sv, ov in zip(inner.invars[-n:], eqn.invars[-n:]):
                    if not isinstance(ov, Literal) and ov in tainted:
                        dims = _shift(tainted[ov], _rank(ov), _rank(sv))
                        if dims:
                            sub_taint[sv] = dims
                if not sub_taint and in_taint:
                    sub_taint = {sv: in_dims for sv in inner.invars}
                walk(inner, sub_taint)
            if in_taint:
                for ov in eqn.outvars:
                    dims = frozenset().union(
                        *(
                            _shift(tainted[v], _rank(v), _rank(ov))
                            for v in hot_in
                        )
                    )
                    if dims:
                        tainted[ov] = tainted.get(ov, frozenset()) | dims
        return any(v in tainted for v in jaxpr.outvars)

    jaxpr = program.closed_jaxpr.jaxpr
    seed: Dict[Any, frozenset] = {}
    for i, v in enumerate(jaxpr.invars):
        if i < len(divisors) and divisors[i] > 1:
            if sharded_dims is not None and i < len(sharded_dims):
                dims = frozenset(sharded_dims[i])
            else:
                # harness predates per-dim recording: treat every
                # dimension as a candidate split (conservative)
                ndim = len(getattr(getattr(v, "aval", None), "shape", ()) or ())
                dims = frozenset(range(max(ndim, 1)))
            if dims:
                seed[v] = dims
    if not seed:
        return findings
    walk(jaxpr, seed)
    return findings


# ----------------------- known-miscompile registry ---------------------- #

@dataclass(frozen=True)
class KnownMiscompile:
    """One quarantined XLA lowering bug, pinned as expected divergence.

    ``verified_broken`` is the set of jaxlib versions the repro was
    confirmed on; a jaxlib outside the set flips the entry to a
    stale-quarantine finding (the mechanical "re-run the repro after a
    bump" that used to be a human ROADMAP obligation)."""

    id: str
    description: str
    repro: str               # command that prints REPRODUCED/FIXED UPSTREAM
    verified_broken: Tuple[str, ...]
    retire: str              # what to dismantle when fixed upstream


KNOWN_MISCOMPILES: Tuple[KnownMiscompile, ...] = (
    KnownMiscompile(
        id="sharded-concat-replica-sum",
        description=(
            "eager multi-operand concatenate of committed-sharded arrays "
            "on a mesh with a spare size>1 axis mis-lowers into a "
            "replica-axis SUM (PR 2)"
        ),
        repro="python -m trlx_tpu.analysis --plant-hazard",
        verified_broken=("0.4.36",),
        retire=(
            "spmd_stack/concat_cols quarantine helpers "
            "(parallel/pipeline.py, ops/sampling.py) and this registry "
            "entry"
        ),
    ),
    KnownMiscompile(
        id="pp-cached-decode-stack",
        description=(
            "pp cached-decode jnp.stack of per-stage KV rows miscompiles "
            "under pipeline-parallel SPMD (quarantined behind spmd_stack)"
        ),
        repro="python tools/pp_miscompile_repro.py",
        verified_broken=("0.4.36",),
        retire="spmd_stack quarantine in parallel/pipeline.py",
    ),
    KnownMiscompile(
        id="multihost-sync-barrier-abort",
        description=(
            "multi-process CPU sync barrier aborts at init "
            "(quarantines the multi-controller integration tests)"
        ),
        repro="python tools/multiprocess_probe.py",
        verified_broken=("0.4.36",),
        retire=(
            "the simulated-host lockstep fallback note in "
            "docs/multihost.md and the skipped integration tests"
        ),
    ),
)


def check_known_miscompiles(
    jaxlib_version: Optional[str] = None,
    probe: bool = True,
) -> Tuple[List[Finding], List[str]]:
    """Registry sweep: report each entry's status. On the verified
    jaxlib the entries are *expected* divergence (covered, no finding);
    a jaxlib outside an entry's verified set yields a stale-quarantine
    warning naming the repro to run and the workaround to retire. For
    ``sharded-concat-replica-sum`` the audit additionally live-probes
    the lowering (compile a seeded concat, look for the minted
    all-reduce) so the flip is detected even with no version bump."""
    if jaxlib_version is None:
        import jaxlib

        jaxlib_version = jaxlib.__version__
    rule = get_rule("lowering-collective-drift")
    findings: List[Finding] = []
    covered: List[str] = []
    for entry in KNOWN_MISCOMPILES:
        covered.append(f"known-miscompile:{entry.id}")
        stale_reason = None
        if jaxlib_version not in entry.verified_broken:
            stale_reason = (
                f"jaxlib {jaxlib_version} is outside the verified-broken "
                f"set {list(entry.verified_broken)}"
            )
        elif entry.id == "sharded-concat-replica-sum" and probe:
            if not _probe_concat_miscompile():
                stale_reason = (
                    f"the live probe no longer reproduces on jaxlib "
                    f"{jaxlib_version}"
                )
        if stale_reason:
            findings.append(
                Finding(
                    rule=rule.id,
                    message=(
                        f"known-miscompile `{entry.id}` may be FIXED "
                        f"UPSTREAM: {stale_reason} — run `{entry.repro}` "
                        "and, if it prints FIXED UPSTREAM, retire "
                        f"{entry.retire}, then update verified_broken"
                    ),
                    severity=SEVERITY_WARNING,
                    subject=f"known-miscompile:{entry.id}",
                    engine="hlo",
                )
            )
    return findings, covered


def _probe_concat_miscompile() -> bool:
    """Compile the minimal PR-2 shape and return True when the minted
    replica-axis all-reduce is still present (i.e. still broken)."""
    try:
        program = plant_hazard_program()
        cp = compile_program(program)
        return bool(concat_minted_collectives(cp.collectives))
    except Exception:
        # a probe that cannot run must not mask real findings — treat
        # as still-broken (the CI upstream-probe job runs the full repro)
        return True


# ------------------------------ the plant ------------------------------- #

def plant_hazard_program():
    """The ``--plant-hazard`` self-check subject: an eager two-operand
    concat of batch-committed rows on the audit mesh (spare tp axis) —
    the minimal PR-2 shape. Running the full rule set over it must trip
    ``spmd-concat-hazard`` at the concat's line below AND
    ``lowering-collective-drift`` on the compiled replica-sum."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from trlx_tpu.analysis import harness

    mesh = harness.audit_mesh()
    row = NamedSharding(mesh, P(("dp", "fsdp"), None))

    def planted_eager_concat(a, b):
        return jnp.concatenate([a, b], axis=0)

    fn = jax.jit(planted_eager_concat, in_shardings=(row, row))
    sds = jax.ShapeDtypeStruct((8, 6), jnp.int32)
    closed = jax.make_jaxpr(fn)(sds, sds)
    return harness.TracedProgram(
        subject="plant.eager_concat",
        closed_jaxpr=closed,
        mesh_axes=set(mesh.axis_names),
        mesh_shape={k: int(v) for k, v in mesh.shape.items()},
        input_divisors=harness.flat_sharding_divisors(
            ((sds, sds),), ((row, row),)
        ),
        input_sharded_dims=harness.flat_sharded_dims(
            ((sds, sds),), ((row, row),)
        ),
        def_site=harness.callable_def_site(planted_eager_concat),
        jit_fn=fn,
        example_args=(sds, sds),
    )


# ------------------------------- budgets -------------------------------- #

def make_hlo_budgets(
    compiled: Sequence[CompiledProgram],
    mesh: Dict[str, int],
    tolerance_pct: float,
) -> Dict:
    audit_label = _mesh_label(mesh)
    return {
        "mesh": {k: int(v) for k, v in sorted(mesh.items())},
        "tolerance_pct": float(tolerance_pct),
        "programs": {
            _budget_key(cp, audit_label): cp.budget_entry()
            for cp in sorted(compiled, key=lambda c: (c.subject, c.mesh_label))
        },
    }


def _budget_key(cp: CompiledProgram, audit_label: str) -> str:
    """Programs compiled on the audit mesh key by bare subject; the
    mesh-matrix train-step extras carry their mesh label so cross-mesh
    entries never collide (and partial relocks can tell them apart)."""
    if cp.mesh_label == audit_label:
        return cp.subject
    return f"{cp.subject}@{cp.mesh_label}"


# ------------------------------ entry point ----------------------------- #

@dataclass
class HloAuditResult:
    mesh: Dict[str, int] = field(default_factory=dict)
    compiled: List[CompiledProgram] = field(default_factory=list)
    compile_seconds: float = 0.0
    registry_status: List[str] = field(default_factory=list)

    def to_rows(self) -> List[Dict]:
        audit_label = _mesh_label(self.mesh)
        return [
            {
                "subject": _budget_key(cp, audit_label),
                "collectives": sum(cp.profile.values()),
                "collective_bytes": cp.collective_bytes,
                "peak_bytes": cp.peak_bytes,
                "upcasts": len(cp.upcasts),
            }
            for cp in sorted(
                self.compiled, key=lambda c: (c.subject, c.mesh_label)
            )
        ]


def audit_hlo(
    kinds: Optional[Sequence[str]] = None,
    mesh: Optional[Dict[str, int]] = None,
    budgets_path: Optional[str] = None,
    update: bool = False,
    matrix: bool = True,
    plant: bool = False,
    programs: Optional[Sequence[Any]] = None,
    registry_probe: bool = True,
) -> Tuple[Report, HloAuditResult]:
    """The ``--hlo-audit`` entry point: compile every harness program
    (plus the train step on the rest of engine 5's mesh matrix — the
    PR-2 bug only mis-lowered on meshes with a spare axis), run the four
    rules, and gate (or with ``update=True`` relock) the ``hlo_budgets``
    section of ``analysis/budgets.json``. ``plant=True`` swaps the
    program set for the seeded eager concat and must produce findings
    from both ``spmd-concat-hazard`` and ``lowering-collective-drift``.
    """
    import time

    from trlx_tpu.analysis import harness
    from trlx_tpu.analysis.collective_trace import MESH_MATRIX
    from trlx_tpu.analysis.resource_audit import (
        DEFAULT_TOLERANCE_PCT,
        default_budgets_path,
        load_budgets,
        write_budgets,
    )

    path = budgets_path or default_budgets_path()
    where = os.path.basename(path)
    report = Report()
    result = HloAuditResult()
    rule_drift = get_rule("lowering-collective-drift")

    if programs is not None and programs:
        # injected subjects (tests): the run's mesh is theirs
        audit_mesh = {
            k: int(v)
            for k, v in (list(programs)[0].mesh_shape or {}).items()
        }
    else:
        audit_mesh = {
            k: int(v)
            for k, v in harness.audit_mesh().shape.items()
        }
    result.mesh = audit_mesh
    audit_label = _mesh_label(audit_mesh)

    if programs is not None:
        programs = list(programs)
    elif plant:
        programs = [plant_hazard_program()]
    else:
        programs = []
        for kind in kinds or harness.TRAINER_KINDS:
            programs.extend(harness.trace_trainer(kind, mesh))
        if matrix and mesh is None:
            for kind in kinds or harness.TRAINER_KINDS:
                for matrix_mesh in MESH_MATRIX:
                    shaped = harness.trace_train_step_program(
                        kind, matrix_mesh
                    )
                    if _mesh_label(shaped.mesh_shape) == audit_label:
                        continue  # the audit mesh is matrix row 4
                    programs.append(shaped)

    findings: List[Finding] = []
    t0 = time.monotonic()
    for program in programs:
        label = _mesh_label(program.mesh_shape)
        if program.jit_fn is None:
            continue
        try:
            cp = compile_program(program)
        except Exception as e:
            findings.append(
                Finding(
                    rule=rule_drift.id,
                    message=(
                        f"failed to AOT-compile `{program.subject}` on "
                        f"mesh {label}: {type(e).__name__}: {e} — the "
                        "compiled artifact cannot be audited"
                    ),
                    severity=rule_drift.severity,
                    subject=program.subject,
                    engine="hlo",
                )
            )
            continue
        result.compiled.append(cp)
        findings.extend(check_dtype_upcasts(cp))
        findings.extend(check_concat_hazard(program))
        report.covered += [
            f"hlo:{program.subject}[{label}]:{facet}"
            for facet in ("collectives", "dtypes", "memory", "intent")
        ] + [
            f"hlo:{program.subject}[{label}]",
            f"hazard:{program.subject}[{label}]",
        ]
    result.compile_seconds = time.monotonic() - t0

    if update:
        if findings:
            kept, suppressed = filter_suppressed(findings)
            report.extend(kept)
            report.suppressed += suppressed
            if report.findings:
                return report, result  # REFUSED: fix findings first
        try:
            budgets = load_budgets(path)
        except (OSError, ValueError):
            budgets = {}
        partial = kinds is not None
        section = make_hlo_budgets(
            result.compiled, result.mesh, DEFAULT_TOLERANCE_PCT
        )
        old_section = budgets.get("hlo_budgets") or {}
        if partial and old_section.get("mesh") not in (
            None, section["mesh"]
        ):
            report.extend([
                Finding(
                    rule=rule_drift.id,
                    message=(
                        "refusing --update-budgets: the hlo lockfile is "
                        f"for mesh {old_section.get('mesh')} but this "
                        f"--trainers subset ran on {section['mesh']} — "
                        "rerun without --trainers or on the locked mesh"
                    ),
                    severity=rule_drift.severity,
                    subject="hlo_budgets",
                    engine="hlo",
                )
            ])
            return report, result
        if partial:
            kept_entries = {
                s: dict(e)
                for s, e in old_section.get("programs", {}).items()
                if s.split(".")[0] not in set(kinds or ())
            }
            kept_entries.update(section["programs"])
            section["programs"] = {
                s: kept_entries[s] for s in sorted(kept_entries)
            }
        budgets["hlo_budgets"] = section
        write_budgets(budgets, path)
        return report, result

    try:
        budgets = load_budgets(path)
    except (OSError, ValueError) as e:
        budgets = {}
        if not plant:
            findings.append(
                Finding(
                    rule=rule_drift.id,
                    message=(
                        f"cannot load budget contract {path}: {e} — "
                        "generate it with --hlo-audit --update-budgets"
                    ),
                    severity=rule_drift.severity,
                    subject="hlo_budgets",
                    engine="hlo",
                )
            )
    section = budgets.get("hlo_budgets")
    if section is None and budgets and not plant:
        findings.append(
            Finding(
                rule=rule_drift.id,
                message=(
                    f"{where} has no hlo_budgets section — lock the "
                    "compiled contract with --hlo-audit --update-budgets "
                    "and commit the diff"
                ),
                severity=rule_drift.severity,
                subject="hlo_budgets",
                engine="hlo",
            )
        )
    locked_mesh = (section or {}).get("mesh")
    mesh_comparable = locked_mesh is None or {
        k: int(v) for k, v in sorted(locked_mesh.items())
    } == {k: int(v) for k, v in sorted(result.mesh.items())}
    if section is not None and not mesh_comparable and not plant:
        findings.append(
            Finding(
                rule=rule_drift.id,
                message=(
                    f"hlo budgets in {where} were locked for mesh "
                    f"{locked_mesh} but the audit ran on {result.mesh} "
                    "— compiled profiles are not comparable; rerun on "
                    "the locked mesh or --update-budgets"
                ),
                severity=rule_drift.severity,
                subject="hlo_budgets",
                engine="hlo",
            )
        )
    tol = float(
        (section or {}).get("tolerance_pct", DEFAULT_TOLERANCE_PCT)
    )
    locked_programs = (section or {}).get("programs", {})
    for cp in result.compiled:
        key = _budget_key(cp, audit_label)
        entry = (
            locked_programs.get(key)
            if section is not None and mesh_comparable and not plant
            else None
        )
        findings.extend(check_lowering_drift(cp, entry, where))
        if not plant:
            findings.extend(check_memory_drift(cp, entry, tol, where))

    if not plant and registry_probe:
        registry_findings, registry_covered = check_known_miscompiles()
        findings.extend(registry_findings)
        report.covered += registry_covered
        import jaxlib

        for entry in KNOWN_MISCOMPILES:
            status = (
                "expected-divergence"
                if jaxlib.__version__ in entry.verified_broken
                else "STALE?"
            )
            result.registry_status.append(f"{entry.id}: {status}")

    kept, suppressed = filter_suppressed(findings)
    report.extend(kept)
    report.suppressed += suppressed
    return report, result


# ------------------------------ bench hook ------------------------------ #

def compiled_step_stats(trainer, kind: str) -> Dict[str, float]:
    """Compiled ground truth for bench.py's ``static_vs_compiled`` row:
    the train step's HLO-measured collective payload and the
    buffer-assignment peak, from the same jit instance bench drives."""
    from trlx_tpu.analysis import harness

    state_sds = harness._sds(trainer.state)
    mb = (
        harness._ilql_minibatch_sds(trainer)
        if kind == "ilql"
        else harness._ppo_minibatch_sds(trainer)
    )
    compiled = trainer._train_step_jit.lower(state_sds, mb).compile()
    collectives = parse_hlo_collectives(compiled.as_text())
    stats = {
        "compiled_train_step_collective_mb": (
            sum(c.bytes for c in collectives) / 2**20
        ),
        "compiled_train_step_collectives": float(len(collectives)),
    }
    try:
        mem = compiled.memory_analysis()
        peak = (
            int(getattr(mem, "temp_size_in_bytes", 0))
            + int(getattr(mem, "argument_size_in_bytes", 0))
            + int(getattr(mem, "output_size_in_bytes", 0))
            - int(getattr(mem, "alias_size_in_bytes", 0))
        )
        stats["compiled_train_step_peak_hbm_gb"] = max(0, peak) / 2**30
    except Exception:
        pass
    return stats


# ------------------------------ rendering ------------------------------- #

def format_hlo_text(result: HloAuditResult) -> str:
    lines = [
        f"{'program':44} {'colls':>5} {'coll MB':>8} {'peak MB':>8} "
        f"{'upcasts':>7}"
    ]
    for row in result.to_rows():
        lines.append(
            f"{row['subject']:44} {row['collectives']:>5} "
            f"{row['collective_bytes'] / 2**20:>8.3f} "
            f"{row['peak_bytes'] / 2**20:>8.3f} {row['upcasts']:>7}"
        )
    for status in result.registry_status:
        lines.append(f"known-miscompile {status}")
    lines.append(
        f"total: {len(result.compiled)} program(s) compiled in "
        f"{result.compile_seconds:.1f}s on mesh {result.mesh}"
    )
    return "\n".join(lines)
