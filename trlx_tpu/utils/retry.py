"""Bounded-exponential-backoff retry for host-side I/O paths.

The genuinely retriable failures in a long TPU run are host-side and
environmental — a flaky network filesystem under the checkpoint
directory, a wandb endpoint timing out, a momentarily-full disk under
the rollout log. Those must not kill a multi-day job. Everything else
(a checkpoint whose train-state structure changed, a config typo, a
programming error) must keep failing *fast*: retrying a structure
mismatch three times with backoff just delays the actionable error.

:func:`retry_call` encodes that split: a ``classify`` function maps each
exception to ``"transient"`` (retry with backoff, bounded by attempts
and an optional wall-clock budget) or ``"permanent"`` (re-raise
immediately). :func:`classify_io_error` is the default taxonomy, shared
by checkpoint save/load (`utils/checkpoint.py`), the background rollout
writer, server admission, and the fault-injection harness's self-checks
(docs/resilience.md "Failure taxonomy").

Every retry is appended to a bounded module-level :data:`retry_log` so
tests and the ``--chaos-smoke`` self-check can assert "this scenario
recovered via N retries" without scraping stderr.
"""

from __future__ import annotations

import sys
import time
from dataclasses import dataclass, fields
from typing import Any, Callable, Dict, List, Optional

from trlx_tpu.telemetry.tracer import monotonic

#: errors that are permanent no matter what: the path itself is wrong,
#: not the filesystem's mood — a retry re-fails identically
PERMANENT_IO_ERRORS = (
    FileNotFoundError,
    NotADirectoryError,
    IsADirectoryError,
    PermissionError,
)

#: unambiguously-transient OS/network failures
TRANSIENT_IO_ERRORS = (
    TimeoutError,
    ConnectionError,
    BrokenPipeError,
    InterruptedError,
)


def classify_io_error(error: BaseException) -> str:
    """Default transient-vs-permanent taxonomy for host I/O failures.

    Any remaining :class:`OSError` (EIO, ENOSPC, ESTALE, the generic
    orbax/gcsfs wrapping of a flaky filesystem) counts as transient: the
    environment may recover. Any non-OS exception (ValueError structure
    mismatch, TypeError, KeyError) is permanent: retrying deterministic
    Python errors only delays them.
    """
    if isinstance(error, PERMANENT_IO_ERRORS):
        return "permanent"
    if isinstance(error, TRANSIENT_IO_ERRORS):
        return "transient"
    if isinstance(error, OSError):
        return "transient"
    return "permanent"


@dataclass
class RetryPolicy:
    """Bounded exponential backoff: ``base * multiplier^k``, capped at
    ``max_delay_s`` per wait and ``timeout_s`` total (None = unbounded
    by wall-clock; attempts still bound it)."""

    max_attempts: int = 4
    base_delay_s: float = 0.05
    multiplier: float = 2.0
    max_delay_s: float = 2.0
    timeout_s: Optional[float] = None

    @classmethod
    def from_dict(cls, config: Optional[Dict[str, Any]]) -> "RetryPolicy":
        config = dict(config or {})
        known = {f.name for f in fields(cls)}
        unknown = set(config) - known
        if unknown:
            raise ValueError(
                f"Unknown retry-policy keys: {sorted(unknown)} "
                f"(known: {sorted(known)})"
            )
        out = cls(**config)
        if out.max_attempts < 1:
            raise ValueError("retry max_attempts must be >= 1")
        return out


# Module default, overridable by the resilience supervisor
# (`train.resilience.retry`) so one config section tunes every wrapped
# I/O path at once.
_default_policy = RetryPolicy()


def default_policy() -> RetryPolicy:
    return _default_policy


def set_default_policy(policy: Optional[RetryPolicy]) -> None:
    global _default_policy
    _default_policy = policy or RetryPolicy()


#: bounded record of retries this process performed (newest last);
#: entries: {"what", "attempt", "delay_s", "error"} — assertable by
#: tests and the chaos smoke
retry_log: List[Dict[str, Any]] = []
_RETRY_LOG_CAP = 256


def reset_retry_log() -> None:
    retry_log.clear()


def _note_retry(what: str, attempt: int, delay: float,
                error: BaseException) -> None:
    retry_log.append(
        {
            "what": what,
            "attempt": attempt,
            "delay_s": round(delay, 4),
            "error": f"{type(error).__name__}: {error}",
        }
    )
    if len(retry_log) > _RETRY_LOG_CAP:
        del retry_log[: len(retry_log) - _RETRY_LOG_CAP]
    print(
        f"retry: {what} failed transiently "
        f"({type(error).__name__}: {error}); attempt {attempt} — "
        f"backing off {delay:.2f}s",
        file=sys.stderr,
    )


def retry_call(
    fn: Callable[[], Any],
    *,
    policy: Optional[RetryPolicy] = None,
    classify: Callable[[BaseException], str] = classify_io_error,
    describe: str = "operation",
    sleep: Callable[[float], None] = time.sleep,
) -> Any:
    """Call ``fn()``; retry transient failures with bounded backoff.

    ``classify(error) -> "transient" | "permanent"`` decides; permanent
    errors and transient errors past the attempt/timeout budget re-raise
    unchanged (callers keep their existing error-translation logic).
    """
    policy = policy or default_policy()
    delay = policy.base_delay_s
    started = monotonic()
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn()
        except Exception as error:
            if classify(error) != "transient":
                raise
            if attempt >= policy.max_attempts:
                raise
            if (
                policy.timeout_s is not None
                and (monotonic() - started) + delay > policy.timeout_s
            ):
                raise
            _note_retry(describe, attempt, delay, error)
            sleep(delay)
            delay = min(delay * policy.multiplier, policy.max_delay_s)
