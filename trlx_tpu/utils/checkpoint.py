"""Orbax checkpointing of train-state pytrees + host metadata.

TPU-native replacement for ``accelerator.save_state/load_state``
(`accelerate_base_model.py:144-146`, SURVEY §5.4): the whole train state
(params, optimizer state, step) is one pytree saved via Orbax — sharded
arrays are written/restored per-shard without host gathering — plus a JSON
sidecar for host-side loop state (iter count, KL coefficient, RNG seed),
mirroring the reference's Ray `state.json` (`accelerate_base_model.py:232-240`).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import jax
import orbax.checkpoint as ocp


def save_checkpoint(
    directory: str,
    state: Any,
    metadata: Optional[Dict[str, Any]] = None,
) -> None:
    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    # Orbax save is a collective: every process participates (each writes
    # its own shards). Only the JSON sidecar is single-writer.
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.join(directory, "state"), state, force=True)
    from trlx_tpu.parallel.distributed import is_main_process

    if is_main_process():
        with open(os.path.join(directory, "host_state.json"), "w") as f:
            json.dump(metadata or {}, f)


def load_checkpoint(
    directory: str, abstract_state: Any
) -> Tuple[Any, Dict[str, Any]]:
    """Restore into the shapes/shardings of ``abstract_state`` (obtain via
    ``jax.eval_shape`` + shardings, or pass a live state of the right spec)."""
    directory = os.path.abspath(directory)
    with ocp.StandardCheckpointer() as ckptr:
        state = ckptr.restore(os.path.join(directory, "state"), abstract_state)
    meta_path = os.path.join(directory, "host_state.json")
    metadata: Dict[str, Any] = {}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            metadata = json.load(f)
    return state, metadata
