"""Orbax checkpointing of train-state pytrees + host metadata.

TPU-native replacement for ``accelerator.save_state/load_state``
(`accelerate_base_model.py:144-146`, SURVEY §5.4): the whole train state
(params, optimizer state, step) and the host-side loop metadata (KL
coefficient, rollout KL) are saved as ONE composite Orbax checkpoint —
sharded arrays are written/restored per-shard without host gathering, and
the state+metadata pair commits atomically (no torn sidecar on a crash
mid-write), mirroring what the reference's Ray `state.json`
(`accelerate_base_model.py:232-240`) records.

``async_save=True`` returns once device arrays are snapshotted to host
buffers; the filesystem write proceeds on Orbax's background thread
(SURVEY §5.4 "Orbax async checkpointing"). :func:`wait_for_checkpoints`
joins any in-flight write and surfaces background write errors.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import orbax.checkpoint as ocp

# Long-lived async checkpointer: it owns a background thread pool and
# (multi-host) a coordination barrier, so it must not be per-call.
_async_ckptr: Optional[ocp.AsyncCheckpointer] = None


def _composite_handler():
    return ocp.CompositeCheckpointHandler()


def _get_async_ckptr() -> ocp.AsyncCheckpointer:
    global _async_ckptr
    if _async_ckptr is None:
        _async_ckptr = ocp.AsyncCheckpointer(_composite_handler())
    return _async_ckptr


def _save_args(state: Any, metadata: Optional[Dict[str, Any]]):
    return ocp.args.Composite(
        state=ocp.args.StandardSave(state),
        host_state=ocp.args.JsonSave(metadata or {}),
    )


def save_checkpoint(
    directory: str,
    state: Any,
    metadata: Optional[Dict[str, Any]] = None,
    async_save: bool = False,
) -> None:
    """Save state + metadata as one atomically-committed checkpoint."""
    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, "state")
    if async_save:
        _get_async_ckptr().save(path, args=_save_args(state, metadata), force=True)
    else:
        with ocp.Checkpointer(_composite_handler()) as ckptr:
            ckptr.save(path, args=_save_args(state, metadata), force=True)


def wait_for_checkpoints() -> None:
    """Block until any in-flight async checkpoint write has committed
    (re-raises background write errors)."""
    if _async_ckptr is not None:
        _async_ckptr.wait_until_finished()


def load_checkpoint(
    directory: str, abstract_state: Any
) -> Tuple[Any, Dict[str, Any]]:
    """Restore into the shapes/shardings of ``abstract_state`` (obtain via
    ``jax.eval_shape`` + shardings, or pass a live state of the right spec).
    Reads both the composite layout and the legacy state-dir +
    host_state.json sidecar layout."""
    wait_for_checkpoints()
    directory = os.path.abspath(directory)
    path = os.path.join(directory, "state")
    legacy_json = os.path.join(directory, "host_state.json")
    if os.path.exists(legacy_json):
        with ocp.StandardCheckpointer() as ckptr:
            state = ckptr.restore(path, abstract_state)
        with open(legacy_json) as f:
            return state, json.load(f)
    with ocp.Checkpointer(_composite_handler()) as ckptr:
        restored = ckptr.restore(
            path,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(abstract_state),
                host_state=ocp.args.JsonRestore(),
            ),
        )
    return restored["state"], dict(restored["host_state"] or {})
