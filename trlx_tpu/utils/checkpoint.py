"""Orbax checkpointing of train-state pytrees + host metadata.

TPU-native replacement for ``accelerator.save_state/load_state``
(`accelerate_base_model.py:144-146`, SURVEY §5.4). Checkpoints are managed
by ``ocp.CheckpointManager``: each save lands in a step-numbered directory
and the previous checkpoint is garbage-collected only *after* the new one
commits — a crash mid-write (sync or async) always leaves the last good
checkpoint restorable. State (sharded arrays, written/restored per-shard
with no host gather) and host metadata (KL controller, the reference's Ray
`state.json` analogue, `accelerate_base_model.py:232-240`) are one
composite checkpoint, committed atomically.

``async_save=True`` returns once device arrays are snapshotted to host
buffers; the write proceeds on Orbax's background thread (SURVEY §5.4
"Orbax async checkpointing"). :func:`wait_for_checkpoints` joins in-flight
writes and surfaces background write errors.

Failure taxonomy (docs/resilience.md): save/load failures are classified
by :func:`classify_checkpoint_error` into *transient* (flaky filesystem
— retried with bounded backoff via `utils/retry.py`) and *permanent*
(train-state structure mismatch, wrong path — refused fast with the
actionable :func:`_structure_mismatch_error` translation). Both paths
carry the ``checkpoint.save`` / ``checkpoint.load`` fault-injection
sites (resilience/chaos.py), which is how the ``--chaos-smoke``
self-check proves a transient error recovers and a permanent one does
not retry.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional, Tuple

import orbax.checkpoint as ocp

from trlx_tpu.resilience import chaos
from trlx_tpu.utils.retry import classify_io_error, retry_call

# One manager per directory: managers own background threads, per-directory
# step bookkeeping, and (multi-host) coordination state. Async is always
# enabled at the manager level; a *sync* save simply joins the write before
# returning — so a directory never has two managers with divergent GC state.
_managers: Dict[str, ocp.CheckpointManager] = {}


def _manager(directory: str) -> ocp.CheckpointManager:
    if directory not in _managers:
        _managers[directory] = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=2,
                enable_async_checkpointing=True,
            ),
        )
    return _managers[directory]


def save_checkpoint(
    directory: str,
    state: Any,
    metadata: Optional[Dict[str, Any]] = None,
    async_save: bool = False,
    step: Optional[int] = None,
) -> None:
    """Save state + metadata as one atomically-committed checkpoint under
    ``directory/<step>/``; the previous checkpoint survives until the new
    one commits."""
    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    mgr = _manager(directory)
    if step is None:
        step = (mgr.latest_step() or 0) + 1
    # Stamp the save wall-clock so load_checkpoint can prefer the newest
    # *timeline* over the highest step number: a crash between the new
    # save's commit and stale-step GC below can leave a higher-numbered
    # step from a previous run alongside this one.
    args = ocp.args.Composite(
        state=ocp.args.StandardSave(state),
        host_state=ocp.args.JsonSave(
            dict(metadata or {}, _saved_at=time.time())
        ),
    )
    # A fresh run reusing a directory from a longer previous run: steps
    # beyond the one being written belong to the stale timeline and must go
    # (retention GC keeps latest-by-step and would otherwise delete this
    # run's checkpoint; resume would restore the old run via latest_step()).
    # Keep the newest stale step until the new save commits so a crash in
    # between never leaves the directory with zero restorable checkpoints.
    stale = sorted(s for s in mgr.all_steps() if s > int(step))
    for s in stale[:-1]:
        mgr.delete(s)

    def _attempt() -> None:
        chaos.check("checkpoint.save", step=int(step))
        try:
            mgr.save(int(step), args=args, force=True)
        except ocp.checkpoint_manager.StepAlreadyExistsError:
            # same-step re-save (incl. a retry after a partially-failed
            # attempt): replace that step's checkpoint
            mgr.delete(int(step))
            mgr.save(int(step), args=args, force=True)
        if stale or not async_save:
            # join the write when the caller needs durability now (sync
            # save) or stale-step GC must wait on the commit; a
            # background failure surfaces here, inside the retry scope
            mgr.wait_until_finished()

    # transient filesystem errors retry with bounded backoff; anything
    # else (wrong path, serialization bug) still fails fast
    retry_call(
        _attempt,
        classify=classify_io_error,
        describe=f"checkpoint save to {directory}",
    )
    if stale:
        mgr.delete(stale[-1])  # new step committed -> stale can go


def wait_for_checkpoints() -> None:
    """Block until in-flight async checkpoint writes have committed
    (re-raises background write errors)."""
    for mgr in _managers.values():
        mgr.wait_until_finished()


def has_checkpoint(directory: str) -> bool:
    """True when ``directory`` holds a restorable checkpoint (managed
    step-numbered layout or the legacy ``state`` + sidecar layout)."""
    directory = os.path.abspath(directory)
    if os.path.isdir(os.path.join(directory, "state")):
        return True  # legacy layout
    if not os.path.isdir(directory):
        return False
    return any(name.isdigit() for name in os.listdir(directory))


_MISMATCH_HINTS = (
    # structure-mismatch phrasings from orbax's StandardRestore stack; keep
    # these NARROW — broad words ("shape", "different") appear in unrelated
    # IO/topology failures that must surface untranslated
    "structure", "mismatch", "not match", "treedef",
)


def _structure_mismatch_error(directory: str, e: Exception) -> Optional[ValueError]:
    """Map Orbax's deep structure-mismatch failures to an actionable error.

    The optimizer-state layout is configuration-dependent: a frozen-mask
    run (``model.num_layers_unfrozen``) stores moments only for the
    trainable slice (``optax.masked``), and ``train.adam_moment_dtype``
    changes the moment dtype — checkpoints written under one layout do not
    restore into another, and Orbax surfaces that as an opaque error deep
    in its restore stack."""
    text = f"{type(e).__name__}: {e}".lower()
    if not any(h in text for h in _MISMATCH_HINTS):
        return None
    if isinstance(e, OSError):
        # an I/O error whose strerror happens to contain a hint word is
        # still an I/O error — never translate it into a layout remedy
        return None
    return ValueError(
        f"checkpoint under {directory} does not match the current "
        "train-state structure. This likely means the optimizer-state "
        "layout changed between the run that wrote the checkpoint and this "
        "configuration — e.g. `model.num_layers_unfrozen` (frozen-mask "
        "runs store moments only for the trainable slice) or "
        "`train.adam_moment_dtype` differs. Frozen-mask layout changes are "
        "not restorable: restore with the original configuration, or "
        "restart the run fresh with a new checkpoint dir. If neither key "
        f"changed, the underlying error was: {type(e).__name__}: {e}"
    )


def classify_checkpoint_error(e: Exception) -> str:
    """Transient-vs-permanent taxonomy for checkpoint I/O failures
    (docs/resilience.md). A structure mismatch is permanent no matter
    how orbax typed it — retrying a layout disagreement only delays the
    actionable error; everything else follows the shared host-I/O
    taxonomy (OSError family transient, deterministic Python errors
    permanent)."""
    if _structure_mismatch_error("", e) is not None:
        return "permanent"
    return classify_io_error(e)


def load_checkpoint(
    directory: str, abstract_state: Any
) -> Tuple[Any, Dict[str, Any]]:
    """Restore into the shapes/shardings of ``abstract_state`` (obtain via
    ``jax.eval_shape`` + shardings, or pass a live state of the right
    spec). Reads the managed layout and the legacy state-dir + sidecar.
    A checkpoint whose train-state structure does not match
    ``abstract_state`` (e.g. a different freezing mask or moment dtype)
    raises a :class:`ValueError` naming the config keys instead of Orbax's
    opaque internal mismatch error."""
    wait_for_checkpoints()
    directory = os.path.abspath(directory)
    mgr = _manager(directory)
    step = mgr.latest_step()
    legacy_state = os.path.join(directory, "state")
    if step is None and os.path.isdir(legacy_state):
        # legacy layout only — once managed steps exist they are newer
        # (an upgraded run keeps saving next to the old 'state' dir)
        with ocp.StandardCheckpointer() as ckptr:

            def _restore_legacy():
                chaos.check("checkpoint.load")
                return ckptr.restore(legacy_state, abstract_state)

            try:
                # transient I/O retries with backoff; a structure
                # mismatch is permanent and refuses on the first attempt
                state = retry_call(
                    _restore_legacy,
                    classify=classify_checkpoint_error,
                    describe=f"checkpoint restore from {legacy_state}",
                )
            except Exception as e:  # noqa: BLE001 — orbax raises many types
                wrapped = _structure_mismatch_error(directory, e)
                if wrapped is None:
                    raise
                raise wrapped from e
        metadata: Dict[str, Any] = {}
        legacy_json = os.path.join(directory, "host_state.json")
        if os.path.exists(legacy_json):
            with open(legacy_json) as f:
                metadata = json.load(f)
        return state, metadata
    if step is None:
        raise FileNotFoundError(f"no checkpoint found under {directory}")
    # Prefer the newest checkpoint by commit wall-clock, not step number:
    # after a crash in save_checkpoint's commit->GC window, a stale
    # higher-numbered step from a previous run can coexist with the newer
    # save. Unstamped (legacy) steps sort by step number alone.
    steps = sorted(mgr.all_steps())
    if len(steps) > 1:

        def _saved_at(s: int) -> float:
            try:
                meta = mgr.restore(
                    s, args=ocp.args.Composite(host_state=ocp.args.JsonRestore())
                )["host_state"]
                return float((meta or {}).get("_saved_at", 0.0))
            except Exception:
                return 0.0

        step = max(steps, key=lambda s: (_saved_at(s), s))
    def _restore():
        chaos.check("checkpoint.load")
        return mgr.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(abstract_state),
                host_state=ocp.args.JsonRestore(),
            ),
        )

    try:
        # the transient/permanent split (classify_checkpoint_error): a
        # flaky filesystem read retries with bounded backoff, a
        # structure mismatch refuses on the first attempt
        restored = retry_call(
            _restore,
            classify=classify_checkpoint_error,
            describe=f"checkpoint restore from {directory}",
        )
    except Exception as e:  # noqa: BLE001 — orbax raises many types
        wrapped = _structure_mismatch_error(directory, e)
        if wrapped is None:
            raise
        raise wrapped from e
    metadata = dict(restored["host_state"] or {})
    metadata.pop("_saved_at", None)
    return restored["state"], metadata
