"""General utilities: seeding, timing, tree ops, top-k masking.

TPU-native re-design of the reference's ``trlx/utils/__init__.py`` (172 LoC:
set_seed :15-22, Clock :63-101, topk_mask :107-116, tree_map/to_device
:132-150, filter_non_scalars :153-164, get_git_tag :167-172). Host-side
helpers stay Python; anything that runs on device is pure jax.numpy so it can
live inside jitted programs.
"""

from __future__ import annotations

import math
import os
import random
import subprocess
from typing import Any, Dict, Iterable, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# the repo's single timing source (telemetry/tracer.py): every reported
# duration — Clock ticks, Logger timestamps, spans, the perf lockfile —
# shares this monotonic clock, so numbers are comparable and immune to
# wall-clock steps (NTP adjustments skewed time.time() deltas)
from trlx_tpu.telemetry.tracer import monotonic


def set_seed(seed: int) -> jax.Array:
    """Seed host-side RNGs and return the root JAX PRNG key.

    Unlike the reference (which seeds torch/cuda globals), JAX randomness is
    explicit: the returned key threads through the framework as part of the
    train state.
    """
    random.seed(seed)
    np.random.seed(seed)
    return jax.random.PRNGKey(seed)


def flatten(xs: Iterable[Iterable[Any]]) -> List[Any]:
    """Flatten one level of nesting."""
    return [item for sub in xs for item in sub]


def chunk(xs: List[Any], chunk_size: int) -> List[List[Any]]:
    """Split ``xs`` into chunks of at most ``chunk_size``."""
    return [xs[i : i + chunk_size] for i in range(0, len(xs), chunk_size)]


def safe_mkdir(path: str) -> None:
    os.makedirs(path, exist_ok=True)


def significant(x: float, ndigits: int = 2) -> float:
    """Round ``x`` to ``ndigits`` significant figures (for log readability)."""
    if x == 0 or not math.isfinite(x):
        return x
    return round(x, ndigits - int(math.floor(math.log10(abs(x)))) - 1)


class Clock:
    """Wall-clock timer that tracks total time and samples processed.

    Mirrors the reference Clock's API (tick returns ms since last tick;
    get_stat reports time-per-1000-samples) so trainer timing stats keep the
    same meaning. Reads the tracer's monotonic clock — one timebase for
    Clock ticks and span durations.
    """

    def __init__(self):
        self.start = monotonic()
        self.total_time = 0.0
        self.total_samples = 0

    def tick(self, samples: int = 0) -> float:
        end = monotonic()
        delta = end - self.start
        self.start = end
        if samples != 0:
            self.total_time += delta
            self.total_samples += samples
        return delta * 1000.0

    def get_stat(self, n_samp: int = 1000, reset: bool = False) -> float:
        stat = 0.0
        if self.total_samples > 0:
            stat = self.total_time * n_samp / self.total_samples
        if reset:
            self.total_time = 0.0
            self.total_samples = 0
        return stat


def topk_mask(xs: jax.Array, k: int) -> jax.Array:
    """Set all elements outside the top-k of the last axis to -inf.

    Device-side (jit-safe) equivalent of the reference's topk_mask; used by
    top-k sampling in the jitted decode loop and ILQL generation.
    """
    if k >= xs.shape[-1]:
        return xs
    kth = jax.lax.top_k(xs, k)[0][..., -1:]
    return jnp.where(xs < kth, jnp.full_like(xs, -jnp.inf), xs)


def sentiment_score(sentiments: Iterable[Any]) -> "jax.Array":
    """Extract the positive-class score from HF sentiment-pipeline outputs
    (reference `trlx/utils/__init__.py:122-129`): each entry is a list of
    ``{"label", "score"}`` dicts; returns the POSITIVE scores as an array."""
    import jax.numpy as jnp

    scores = []
    for entry in sentiments:
        by_label = {d["label"]: d["score"] for d in entry}
        if "POSITIVE" in by_label:
            scores.append(by_label["POSITIVE"])
        else:
            # generic 2-class heads: positive is the highest label name
            # (LABEL_1 > LABEL_0) — pipeline output order is score-sorted,
            # so never index by position
            scores.append(by_label[max(by_label)])
    return jnp.asarray(scores, jnp.float32)


def tree_map(f, tree: Any) -> Any:
    """Apply ``f`` to every leaf of a pytree (dict/list/tuple/array)."""
    return jax.tree_util.tree_map(f, tree)


def to_device(tree: Any, device=None) -> Any:
    """Move a pytree of arrays onto a device (default: first local device)."""
    return jax.device_put(tree, device)


# f32-consuming leaves excluded from the rollout-phase compute-dtype cast:
# value/Q-head final layers (MLPHead "fc2" computes in f32 — value clipping
# is sensitive to bf16 rounding) and MoE router logits.
ROLLOUT_CAST_EXCLUDE = ("router", "fc2")


def compute_dtype_cast(params: Any, compute_dtype) -> Any:
    """Cast float param leaves to the compute dtype for the rollout phase.

    Decode re-reads every parameter once per generated token; f32 masters
    double that HBM traffic vs the compute dtype. Bit-identical outputs:
    causal-family ops already cast params to the compute dtype per use
    (embedding adds round per-table first), and leaves whose path matches
    :data:`ROLLOUT_CAST_EXCLUDE` — the ones genuinely consumed at f32 —
    keep their storage dtype. Jit with param shardings in/out so the copy
    lands sharded like the masters (`train.rollout_param_cast`)."""
    cdtype = jnp.dtype(compute_dtype)

    def cast(path, leaf):
        keys = "/".join(str(getattr(p, "key", p)) for p in path)
        if any(ex in keys for ex in ROLLOUT_CAST_EXCLUDE):
            return leaf
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            return leaf.astype(cdtype)
        return leaf

    return jax.tree_util.tree_map_with_path(cast, params)


def filter_non_scalars(xs: Dict[str, Any]) -> Dict[str, float]:
    """Keep only entries castable to float — used before metric logging."""
    ys = {}
    for k, v in xs.items():
        try:
            ys[k] = float(v)
        except (TypeError, ValueError):
            continue
    return ys


def get_git_tag() -> str:
    """Return `(short-hash, commit-date)` of HEAD for run naming."""
    try:
        output = subprocess.check_output(
            "git log --format='%h/%as' -n1".split(),
            stderr=subprocess.DEVNULL,
        )
        branch = subprocess.check_output(
            "git rev-parse --abbrev-ref HEAD".split(),
            stderr=subprocess.DEVNULL,
        )
        return f"{branch.decode()[:-1]}/{output.decode()[1:-2]}"
    except (subprocess.CalledProcessError, FileNotFoundError):
        return "unknown"


def rampup_decay_schedule(
    rampup_steps: int, decay_steps: int, init_lr: float, target_lr: float
):
    """Linear warmup then exponential decay, as an optax-compatible schedule.

    Replaces the reference's LambdaLR `rampup_decay`.
    """

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        warm = target_lr * jnp.minimum(step / jnp.maximum(rampup_steps, 1), 1.0)
        decay_frac = jnp.maximum(step - rampup_steps, 0.0) / jnp.maximum(
            decay_steps, 1
        )
        decayed = target_lr * jnp.power(
            jnp.asarray(init_lr / target_lr, jnp.float32), jnp.minimum(decay_frac, 1.0)
        )
        return jnp.where(step < rampup_steps, warm, jnp.maximum(decayed, init_lr))

    return schedule


def infinite_loader(loader) -> Iterable:
    """Cycle a loader forever (prompt draws in rollout collection).

    ``loader`` is either a reusable iterable or a ``factory(epoch) ->
    iterable`` (lets pipelines reshuffle per pass). Raises instead of
    spinning if an iteration yields nothing.
    """
    epoch = 0
    while True:
        it = loader(epoch) if callable(loader) else loader
        yielded = False
        for item in it:
            yielded = True
            yield item
        if not yielded:
            raise ValueError("infinite_loader: underlying loader is empty")
        epoch += 1
