"""Metric logging: stdout JSON-lines always, wandb when available.

Re-design of the reference's wandb-only path
(``Accelerator(log_with="wandb")`` + ``init_trackers``,
`accelerate_base_model.py:38,78-92`): the tracker here is a thin host-side
sink — training stats arrive as plain dicts of floats (device scalars are
pulled once per log step, never inside jitted code). ``debug`` env disables
wandb as the reference does (`accelerate_base_model.py:88`).
"""

from __future__ import annotations

import json
import os
import sys
from typing import Any, Dict, Optional

from trlx_tpu.telemetry.tracer import monotonic
from trlx_tpu.utils import filter_non_scalars, get_git_tag


class Logger:
    def __init__(
        self,
        project_name: str = "trlx_tpu",
        run_name: str = "",
        config: Optional[Dict[str, Any]] = None,
        tags=(),
        use_wandb: Optional[bool] = None,
        stream=None,
        total_steps: Optional[int] = None,
    ):
        self.stream = stream or sys.stdout
        # the tracer's monotonic clock, not time.time(): logged "time"
        # deltas share the timebase of every span/Clock measurement
        self.start = monotonic()
        self._wandb = None
        # graceful degradation (docs/resilience.md): consecutive wandb
        # emission failures past this disable the tracker with one
        # stderr warning — a crash-looping/unreachable tracker must not
        # kill (or stall) a training run; stdout JSONL keeps flowing
        self._wandb_failure_limit = 3
        self._wandb_failures = 0
        # interactive tqdm progress line (reference shows a tqdm bar with a
        # live loss description, `accelerate_base_model.py:245-297`);
        # stderr-only, so stdout's JSON lines stay machine-parseable
        self._pbar = None
        self._total_steps = total_steps
        # rank-0 gating on multi-host pods (reference gates trackers on
        # accelerator.is_main_process, `accelerate_base_model.py:78`)
        from trlx_tpu.parallel.distributed import is_main_process

        self.is_main = is_main_process()
        if use_wandb is None:
            use_wandb = (
                self.is_main
                and os.environ.get("debug", "") == ""
                and os.environ.get("WANDB_DISABLED", "") not in ("1", "true")
            )
        if use_wandb:
            try:
                import wandb

                self._wandb = wandb.init(
                    project=project_name,
                    name=run_name or None,
                    config=config,
                    tags=[*tags, get_git_tag()],
                    mode=os.environ.get("WANDB_MODE", "offline"),
                )
            except Exception as e:
                # one visible line, not silence: a misconfigured tracker
                # (bad API key, unwritable dir, version clash) used to be
                # indistinguishable from wandb-not-installed — runs ended
                # with no curves and no clue why
                print(
                    f"warning: wandb init failed, logging to stdout only "
                    f"({type(e).__name__}: {e})",
                    file=sys.stderr,
                )
                self._wandb = None

    def log(self, stats: Dict[str, Any], step: Optional[int] = None) -> None:
        import jax

        # pull ALL device values in ONE transfer event — per-key float()
        # conversions each cost a full round-trip on a tunneled chip.
        # Flattening the whole stats pytree (not just top-level entries)
        # catches device scalars nested under sub-dicts/lists too.
        if not self.is_main:
            return
        leaves, treedef = jax.tree_util.tree_flatten(stats)
        device_ix = [
            i for i, leaf in enumerate(leaves) if isinstance(leaf, jax.Array)
        ]
        if device_ix:
            fetched = jax.device_get([leaves[i] for i in device_ix])
            for i, v in zip(device_ix, fetched):
                leaves[i] = v
            stats = jax.tree_util.tree_unflatten(treedef, leaves)
        scalars = filter_non_scalars(stats)
        record = {"step": step, "time": round(monotonic() - self.start, 2), **scalars}
        if self._pbar is not None:
            # erase the live bar first: stdout and stderr often share the
            # terminal, and printing at the bar's cursor garbles both
            self._pbar.clear()
        print(json.dumps(record, default=float), file=self.stream, flush=True)
        self._wandb_emit(
            lambda: self._wandb.log(scalars, step=step), what="metrics"
        )
        self._update_progress(step, scalars)

    def _wandb_emit(self, emit, what: str) -> None:
        """Run one wandb emission with degradation: an exception never
        propagates into the train loop (the stdout JSONL line already
        landed), and repeated consecutive failures disable the tracker
        with a single warning instead of failing every step. Carries
        the ``logger.emit`` fault-injection site (resilience/chaos.py)."""
        if self._wandb is None:
            return
        from trlx_tpu.resilience import chaos

        try:
            chaos.check("logger.emit")
            emit()
            self._wandb_failures = 0
        except Exception as e:
            self._wandb_failures += 1
            if self._wandb_failures == 1:
                print(
                    f"warning: wandb {what} emission failed "
                    f"({type(e).__name__}: {e}); will keep trying",
                    file=sys.stderr,
                )
            if self._wandb_failures >= self._wandb_failure_limit:
                print(
                    f"warning: wandb emission failed "
                    f"{self._wandb_failures} times in a row — disabling "
                    "wandb for this run; metrics continue as stdout JSON "
                    "lines",
                    file=sys.stderr,
                )
                self._wandb = None

    def _update_progress(self, step, scalars) -> None:
        if not (hasattr(sys.stderr, "isatty") and sys.stderr.isatty()):
            return
        if self._pbar is None:
            try:
                from tqdm import tqdm
            except ImportError:
                return
            self._pbar = tqdm(
                total=self._total_steps, desc="train", dynamic_ncols=True
            )
        if step is not None:
            self._pbar.n = int(step)
        postfix = {}
        for key in ("losses/total_loss", "reward/mean", "exp/score_mean"):
            if key in scalars:
                postfix[key.split("/")[-1]] = f"{float(scalars[key]):.4f}"
        if postfix:
            self._pbar.set_postfix(postfix, refresh=False)
        self._pbar.refresh()

    def log_health_event(
        self, event: Dict[str, Any], step: Optional[int] = None
    ) -> None:
        """Emit one structured run-health event (telemetry/health.py) as
        a ``health_event`` JSON line on the metrics stream — greppable
        next to the stats rows that tripped it — plus a wandb counter
        bump so dashboards can alert on trips without parsing stdout."""
        if not self.is_main:
            return
        if self._pbar is not None:
            self._pbar.clear()  # same terminal-sharing guard as log()
        record = {
            "step": step,
            "time": round(monotonic() - self.start, 2),
            "health_event": event,
        }
        print(json.dumps(record, default=float), file=self.stream, flush=True)
        detector = event.get("detector", "unknown")
        self._wandb_emit(
            lambda: self._wandb.log(
                {f"health/event/{detector}": float(event.get("value", 1.0))},
                step=step,
            ),
            what="health event",
        )

    def log_samples(self, rows, columns, step: Optional[int] = None) -> None:
        """Log generated-sample tables (reference wandb Table,
        `accelerate_base_model.py:180-221`); stdout shows the first rows."""
        if not self.is_main:
            return
        if self._pbar is not None:
            self._pbar.clear()  # same terminal-sharing guard as log()
        for row in rows[:4]:
            printable = {c: str(v)[:120] for c, v in zip(columns, row)}
            print(json.dumps({"sample": printable}, default=str), file=self.stream)
        if self._wandb is not None:
            import wandb

            self._wandb_emit(
                lambda: self._wandb.log(
                    {"samples": wandb.Table(columns=list(columns), rows=[list(r) for r in rows])},
                    step=step,
                ),
                what="sample table",
            )

    def finish(self) -> None:
        if self._pbar is not None:
            self._pbar.close()
            self._pbar = None
        if self._wandb is not None:
            self._wandb.finish()
