"""Cooperative-scheduler yield points for the race auditor (engine 14).

Production host code that participates in the deterministic interleaving
harness calls :func:`yield_point` at every lock/queue/shared-attribute
touch. In normal operation the hook is ``None`` and the call is a single
global load + falsy branch — effectively free. Under
``analysis/concurrency.py`` the hook parks the calling thread and hands
control to the scheduler, which picks the next runnable thread from a
seeded RNG, making every interleaving deterministic and replayable.

Threads created *inside* instrumented code (the background JSONL
writer's daemon thread) call :func:`announce_thread` right after
``Thread.start()`` so the scheduler adopts them before they do any
observable work.

This module is intentionally dependency-free: it must be importable from
the deepest utility layers without cycles.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Callable, Iterator, Optional

# Single mutable cell so the fast path is one global read. Writes happen
# only from install()/uninstall() under _hook_lock; readers tolerate
# staleness (a late no-op yield is harmless).
_HOOK: Optional[Callable[[str], None]] = None
_ANNOUNCE: Optional[Callable[[threading.Thread], None]] = None
_hook_lock = threading.Lock()


def yield_point(tag: str) -> None:
    """Mark a schedulable point named ``tag`` (e.g. ``writer.enqueue``).

    No-op unless a scheduler installed a hook. Production call sites pay
    one global load when uninstrumented.
    """
    hook = _HOOK
    if hook is not None:
        hook(tag)


def announce_thread(thread: threading.Thread) -> None:
    """Tell an installed scheduler about a thread created by
    instrumented code, so it is adopted before it runs observably."""
    announce = _ANNOUNCE
    if announce is not None:
        announce(thread)


@contextmanager
def guard(lock: threading.Lock, tag: str) -> Iterator[None]:
    """``with guard(self._lock, "writer.lock"):`` — a plain ``with lock``
    when uninstrumented; under the scheduler it yields before acquiring
    and spins acquire(blocking=False)+yield on contention, so a thread
    parked *inside* a critical section can never wedge the schedule
    (the contender parks instead of blocking in C)."""
    hook = _HOOK
    if hook is None:
        with lock:
            yield
        return
    hook(tag)
    while not lock.acquire(blocking=False):
        hook(tag + ".wait")
    try:
        yield
    finally:
        lock.release()


def instrumented() -> bool:
    """True while a scheduler hook is installed (lets blocking calls
    switch to poll-and-yield loops the scheduler can serialize)."""
    return _HOOK is not None


def install(
    hook: Callable[[str], None],
    announce: Optional[Callable[[threading.Thread], None]] = None,
) -> None:
    """Install the scheduler hook. Exactly one scheduler may be active."""
    global _HOOK, _ANNOUNCE
    with _hook_lock:
        if _HOOK is not None:
            raise RuntimeError("a sched_points hook is already installed")
        _HOOK = hook
        _ANNOUNCE = announce


def uninstall() -> None:
    """Remove the scheduler hook; always runs in a finally block of the
    harness so a crashed schedule cannot leave production code parked."""
    global _HOOK, _ANNOUNCE
    with _hook_lock:
        _HOOK = None
        _ANNOUNCE = None
