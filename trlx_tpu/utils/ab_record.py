"""Self-recording measurement artifacts (repo discipline: results live
in committed JSON artifacts, not docstring TODOs).

Each A/B script calls :func:`record_latest` after printing its JSON
line: the artifact keeps ONE dated record per (metric, device_kind) —
the latest measurement per shape+backend, not a log — so the first
hardware run of any A/B lands its delta in a reviewable diff
automatically (the AB_PHASE_OVERLAP.json pattern, PR 6)."""

from __future__ import annotations

import json
import time
from typing import Any, Dict


def record_latest(artifact_path: str, record: Dict[str, Any]) -> None:
    """Insert ``record`` (must carry "metric" and "device_kind") into the
    JSON-list artifact at ``artifact_path``, replacing any previous
    record with the same (metric, device_kind); stamps today's date."""
    try:
        with open(artifact_path, encoding="utf-8") as fh:
            history = json.load(fh)
    except (OSError, ValueError):
        history = []
    if not isinstance(history, list) or not all(
        isinstance(r, dict) for r in history
    ):
        # hand-edited/wrong-shaped artifact: start fresh rather than
        # crash AFTER the measurement already ran
        history = []
    dated = dict(record, date=time.strftime("%Y-%m-%d"))
    history = [
        r for r in history
        if (r.get("metric"), r.get("device_kind"))
        != (record.get("metric"), record.get("device_kind"))
    ] + [dated]
    with open(artifact_path, "w", encoding="utf-8") as fh:
        json.dump(history, fh, indent=2)
        fh.write("\n")
