"""Background JSONL writer: host file I/O off the collect critical path.

``PPOOrchestrator._log_rollouts`` used to append rollout rows to disk
synchronously inside the collection loop — on a network filesystem a
single flush can cost tens of milliseconds, sitting squarely on the
host-side tail the overlapped phase works to hide (docs/async_pipeline.md).
:class:`BackgroundJSONLWriter` moves the writes to one daemon thread
behind a BOUNDED queue:

- ``submit(path, rows)`` enqueues one batch of JSON-serializable dicts;
  it only blocks when the queue is full (backpressure instead of
  unbounded memory growth when the disk cannot keep up);
- ``flush()`` waits until everything enqueued so far has hit the
  filesystem and re-raises the first writer-thread error — callers flush
  at phase end, so a full phase's rows are durable before the next phase
  begins, and a failing disk is surfaced at a deterministic point instead
  of silently dropping rows;
- the writer is crash-safe: the orchestrator flushes from a ``finally``,
  so rows already queued are drained to disk even when collection raises.
"""

from __future__ import annotations

import json
import queue
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple


class BackgroundJSONLWriter:
    """Append batches of JSON lines to files from a background thread."""

    def __init__(self, maxsize: int = 64):
        self._q: "queue.Queue[Optional[Tuple[str, List[Dict[str, Any]]]]]" = (
            queue.Queue(maxsize)
        )
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._lock = threading.Lock()
        self._closed = False

    # ------------------------------ API ------------------------------- #

    def submit(self, path: str, rows: Sequence[Dict[str, Any]]) -> None:
        """Enqueue ``rows`` for appending to ``path`` (one JSON object per
        line). Serialization happens here, on the caller, so a
        non-serializable row fails loudly at the call site rather than
        asynchronously in the writer thread."""
        if self._closed:
            raise RuntimeError("writer is closed")
        self._raise_pending()
        lines = [json.dumps(r) for r in rows]
        self._ensure_thread()
        self._q.put((path, lines))

    def flush(self, reraise: bool = True) -> None:
        """Block until every submitted batch has been written; surface the
        first background error (``reraise=False`` suppresses it — for
        ``finally`` blocks where another exception is already in
        flight)."""
        if self._thread is not None:
            self._q.join()
        if reraise:
            self._raise_pending()

    def close(self, reraise: bool = True) -> None:
        """Drain, stop the thread, and surface any pending error.

        The thread is stopped BEFORE the pending error is re-raised: a
        raising close must not leak a live writer thread, and an error
        that an earlier ``flush(reraise=False)`` swallowed (the
        drain-on-exception path — e.g. a phase-end flush running while
        another exception was already propagating) still surfaces here
        instead of dying with the process."""
        self.flush(reraise=False)
        self._closed = True
        if self._thread is not None:
            self._q.put(None)
            self._thread.join(timeout=10)
            self._thread = None
        if reraise:
            self._raise_pending()

    @property
    def pending(self) -> int:
        return self._q.unfinished_tasks

    # ---------------------------- internal ---------------------------- #

    def _ensure_thread(self) -> None:
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name="rollout-jsonl-writer", daemon=True
                )
                self._thread.start()

    def _raise_pending(self) -> None:
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError(
                "background rollout writer failed; rows after the failure "
                "may be missing"
            ) from err

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                self._q.task_done()
                return
            path, lines = item
            try:
                if self._error is None:
                    with open(path, "a") as f:
                        f.write("\n".join(lines) + "\n")
            except BaseException as e:  # surfaced at the next flush/submit
                if self._error is None:
                    self._error = e
            finally:
                self._q.task_done()
