"""Background JSONL writer: host file I/O off the collect critical path.

``PPOOrchestrator._log_rollouts`` used to append rollout rows to disk
synchronously inside the collection loop — on a network filesystem a
single flush can cost tens of milliseconds, sitting squarely on the
host-side tail the overlapped phase works to hide (docs/async_pipeline.md).
:class:`BackgroundJSONLWriter` moves the writes to one daemon thread
behind a BOUNDED queue:

- ``submit(path, rows)`` enqueues one batch of JSON-serializable dicts;
  it only blocks when the queue is full (backpressure instead of
  unbounded memory growth when the disk cannot keep up);
- ``flush()`` waits until everything enqueued so far has hit the
  filesystem and re-raises the first writer-thread error — callers flush
  at phase end, so a full phase's rows are durable before the next phase
  begins, and a failing disk is surfaced at a deterministic point instead
  of silently dropping rows;
- the writer is crash-safe: the orchestrator flushes from a ``finally``,
  so rows already queued are drained to disk even when collection raises.

Graceful degradation (docs/resilience.md): write failures are classified
by the `utils/retry.py` taxonomy. *Permanent* errors (a missing
directory) surface at the next flush/submit exactly as before.
*Transient* errors (disk momentarily full, flaky NFS) keep their batches
in an ordered retry buffer, retried before every later write; after
``degrade_after`` consecutive transient failures the writer degrades to
synchronous in-caller writes with a one-time stderr warning — failures
then surface (or recover) at the write site instead of a phase-end
flush. If the filesystem recovers, every buffered batch lands in order
and nothing is raised; rows still unwritable when the run ends surface
at ``close()`` as a hard error. The write path carries the
``writer.write`` fault-injection site (resilience/chaos.py) so the
disk-full scenario is testable deterministically.
"""

from __future__ import annotations

import json
import queue
import sys
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from trlx_tpu.resilience import chaos
from trlx_tpu.utils import sched_points
from trlx_tpu.utils.retry import classify_io_error

#: buffered-batch cap: past this, unwritable rows become a hard error
#: (bounded memory beats silently hoarding a run's worth of rollouts)
_RETRY_CAP = 256


class BackgroundJSONLWriter:
    """Append batches of JSON lines to files from a background thread."""

    def __init__(self, maxsize: int = 64, degrade_after: int = 3):
        self._q: "queue.Queue[Optional[Tuple[str, List[str]]]]" = (
            queue.Queue(maxsize)
        )
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._lock = threading.Lock()
        self._closed = False
        self.degrade_after = int(degrade_after)
        self._consecutive_failures = 0
        self._degraded = False
        self._warned_degrade = False
        # ordered (path, lines) batches that failed transiently and are
        # retried before any later write — rows stay in arrival order
        self._retry: List[Tuple[str, List[str]]] = []

    # ------------------------------ API ------------------------------- #

    def submit(self, path: str, rows: Sequence[Dict[str, Any]]) -> None:
        """Enqueue ``rows`` for appending to ``path`` (one JSON object per
        line). Serialization happens here, on the caller, so a
        non-serializable row fails loudly at the call site rather than
        asynchronously in the writer thread."""
        if self._closed:
            raise RuntimeError("writer is closed")
        self._raise_pending()
        lines = [json.dumps(r) for r in rows]
        # _degraded is a monotone latch (False->True, never back): a stale
        # False here just enqueues one more batch, which the draining
        # thread still writes in order — no torn state is reachable, so
        # the check may stay outside _lock
        if self._degraded:  # tpu-lint: disable=atomicity-split
            # degraded mode: write in the caller, after the queue's
            # remaining batches drain (ordering per path is preserved)
            self._join_queue()
            self._write_buffered(then=(path, lines))
            return
        self._ensure_thread()
        sched_points.yield_point("writer.enqueue")
        if sched_points.instrumented():
            # cooperative scheduler: a blocking put on a full queue would
            # stall the whole schedule; poll-and-yield instead
            while True:
                try:
                    self._q.put_nowait((path, lines))
                    return
                except queue.Full:
                    sched_points.yield_point("writer.enqueue.full")
        self._q.put((path, lines))

    @property
    def degraded(self) -> bool:
        """True once the writer fell back to synchronous writes."""
        return self._degraded

    def flush(self, reraise: bool = True) -> None:
        """Block until every submitted batch has been written; surface the
        first background *permanent* error (``reraise=False`` suppresses
        it — for ``finally`` blocks where another exception is already
        in flight). Batches buffered by transient failures get another
        synchronous attempt here; still-failing ones stay buffered (the
        degradation contract: a momentarily-full disk must not kill the
        phase) and become a hard error only at :meth:`close`."""
        sched_points.yield_point("writer.flush")
        self._join_queue()
        self._write_buffered()
        if reraise:
            self._raise_pending()

    def close(self, reraise: bool = True) -> None:
        """Drain, stop the thread, and surface any pending error.

        The thread is stopped BEFORE the pending error is re-raised: a
        raising close must not leak a live writer thread, and an error
        that an earlier ``flush(reraise=False)`` swallowed (the
        drain-on-exception path — e.g. a phase-end flush running while
        another exception was already propagating) still surfaces here
        instead of dying with the process."""
        self.flush(reraise=False)
        self._closed = True
        if self._thread is not None:
            self._q.put(None)
            if sched_points.instrumented():
                # let the scheduler drive the writer thread to its exit
                # instead of blocking the schedule inside join()
                while self._thread.is_alive():
                    sched_points.yield_point("writer.close.join")
            self._thread.join(timeout=10)
            self._thread = None
        self._write_buffered()  # last chance for transient-buffered rows
        if reraise:
            self._raise_pending()
            if self._retry:
                n = sum(len(lines) for _, lines in self._retry)
                raise RuntimeError(
                    "background rollout writer failed; "
                    f"{n} row(s) could not be written (transient write "
                    "failures never recovered)"
                )

    @property
    def pending(self) -> int:
        return self._q.unfinished_tasks

    # ---------------------------- internal ---------------------------- #

    def _ensure_thread(self) -> None:
        with sched_points.guard(self._lock, "writer.lock"):
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name="rollout-jsonl-writer", daemon=True
                )
                self._thread.start()
                # adopt the new thread into an active deterministic
                # scheduler before it does any observable work
                sched_points.announce_thread(self._thread)

    def _join_queue(self) -> None:
        """Wait until the queue drains; under the deterministic scheduler
        a blocking ``Queue.join`` would stall the schedule, so poll and
        yield instead (the writer thread only makes progress while the
        scheduler runs it)."""
        if self._thread is None:
            return
        if sched_points.instrumented():
            while self._q.unfinished_tasks:
                sched_points.yield_point("writer.flush.wait")
            return
        self._q.join()

    def _raise_pending(self) -> None:
        # the swap must hold _lock: _error is written by the writer
        # thread (_run's except / _on_write_failure) and consumed here on
        # the caller thread — an unlocked test-then-swap can both lose an
        # error and double-raise one
        with sched_points.guard(self._lock, "writer.lock"):
            err, self._error = self._error, None
        if err is not None:
            raise RuntimeError(
                "background rollout writer failed; rows after the failure "
                "may be missing"
            ) from err

    def _append(self, path: str, lines: List[str]) -> None:
        sched_points.yield_point("writer.append")
        chaos.check("writer.write")
        with open(path, "a") as f:
            f.write("\n".join(lines) + "\n")

    def _on_write_failure(
        self, batch: Tuple[str, List[str]], error: BaseException
    ) -> None:
        """Classify one failed batch: transient ⇒ buffer for retry (and
        maybe degrade), permanent ⇒ pend the error (old behavior).
        Caller must hold ``_lock`` (only ``_write_buffered`` calls this,
        from inside its critical section)."""
        if (
            isinstance(error, Exception)
            and classify_io_error(error) == "transient"
            and len(self._retry) < _RETRY_CAP
        ):
            self._retry.append(batch)
            self._consecutive_failures += 1
            if (
                self._consecutive_failures >= self.degrade_after
                and not self._degraded
            ):
                self._degraded = True
                if not self._warned_degrade:
                    self._warned_degrade = True
                    print(
                        "resilience: background rollout writer hit "
                        f"{self._consecutive_failures} consecutive "
                        f"transient write failures "
                        f"({type(error).__name__}: {error}) — degrading "
                        "to synchronous writes; buffered rows retry "
                        "before each write",
                        file=sys.stderr,
                    )
            return
        if self._error is None:
            self._error = error

    def _write_buffered(
        self, then: Optional[Tuple[str, List[str]]] = None
    ) -> None:
        """Retry buffered batches in order, then (optionally) one new
        batch; the first failure re-buffers the remainder so ordering
        survives a still-broken disk."""
        with sched_points.guard(self._lock, "writer.lock"):
            work = self._retry
            self._retry = []
            if then is not None:
                work.append(then)
            for i, batch in enumerate(work):
                try:
                    self._append(*batch)
                    self._consecutive_failures = 0
                except BaseException as e:
                    self._on_write_failure(batch, e)
                    # keep the untried tail buffered, in order
                    self._retry.extend(work[i + 1:])
                    return

    def _get_next(self) -> Optional[Tuple[str, List[str]]]:
        """Next queue item; under the deterministic scheduler a blocking
        ``get`` would park the writer thread inside C code where the
        scheduler cannot preempt it, so poll-and-yield instead."""
        if sched_points.instrumented():
            while True:
                try:
                    return self._q.get_nowait()
                except queue.Empty:
                    sched_points.yield_point("writer.idle")
        return self._q.get()

    def _run(self) -> None:
        while True:
            sched_points.yield_point("writer.loop")
            item = self._get_next()
            if item is None:
                self._q.task_done()
                return
            try:
                with sched_points.guard(self._lock, "writer.lock"):
                    pending = self._error is not None
                if not pending:
                    self._write_buffered(then=item)
            except BaseException as e:  # surfaced at the next flush/submit
                with sched_points.guard(self._lock, "writer.lock"):
                    if self._error is None:
                        self._error = e
            finally:
                self._q.task_done()
