"""Registry lookups (reference ``trlx/utils/loading.py:18-52``)."""

from trlx_tpu.orchestrator import get_orchestrator
from trlx_tpu.pipeline import get_datapipeline as get_pipeline
from trlx_tpu.trainer import get_trainer

__all__ = ["get_trainer", "get_pipeline", "get_orchestrator"]
