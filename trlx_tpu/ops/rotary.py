"""Rotary position embeddings — both conventions.

GPT-J rotates interleaved pairs (``rotate_every_two``); GPT-NeoX rotates
concatenated halves (``rotate_half``). Getting the convention right per
family is what exact-logit checkpoint parity hinges on (verified in
``tests/test_gptj_parity.py`` / ``test_neox_parity.py``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rotary_angles(
    position_ids: jax.Array,  # [B, T]
    rotary_dim: int,
    base: float = 10000.0,
):
    """-> (sin, cos) of shape [B, T, rotary_dim/2], float32."""
    inv_freq = 1.0 / (
        base ** (jnp.arange(0, rotary_dim, 2, dtype=jnp.float32) / rotary_dim)
    )
    angles = position_ids.astype(jnp.float32)[..., None] * inv_freq  # [B, T, D/2]
    return jnp.sin(angles), jnp.cos(angles)


def apply_rotary_interleaved(
    x: jax.Array,  # [B, T, H, D] (first rotary_dim dims rotated)
    sin: jax.Array,  # [B, T, rotary_dim/2]
    cos: jax.Array,
    rotary_dim: int,
) -> jax.Array:
    """GPT-J convention: pairs (x0,x1),(x2,x3),... rotate together."""
    rot, rest = x[..., :rotary_dim], x[..., rotary_dim:]
    sin2 = jnp.repeat(sin, 2, axis=-1)[:, :, None, :]  # [B, T, 1, rotary_dim]
    cos2 = jnp.repeat(cos, 2, axis=-1)[:, :, None, :]
    x1 = rot[..., ::2]
    x2 = rot[..., 1::2]
    rotated = jnp.stack([-x2, x1], axis=-1).reshape(rot.shape)
    rot = rot * cos2.astype(x.dtype) + rotated * sin2.astype(x.dtype)
    return jnp.concatenate([rot, rest], axis=-1) if rest.shape[-1] else rot


def apply_rotary_half(
    x: jax.Array,  # [B, T, H, D]
    sin: jax.Array,  # [B, T, rotary_dim/2]
    cos: jax.Array,
    rotary_dim: int,
) -> jax.Array:
    """GPT-NeoX convention: first and second halves of the rotary dims
    rotate against each other."""
    rot, rest = x[..., :rotary_dim], x[..., rotary_dim:]
    sin2 = jnp.concatenate([sin, sin], axis=-1)[:, :, None, :]
    cos2 = jnp.concatenate([cos, cos], axis=-1)[:, :, None, :]
    half = rotary_dim // 2
    rotated = jnp.concatenate([-rot[..., half:], rot[..., :half]], axis=-1)
    rot = rot * cos2.astype(x.dtype) + rotated * sin2.astype(x.dtype)
    return jnp.concatenate([rot, rest], axis=-1) if rest.shape[-1] else rot
