"""Pallas TPU flash attention (forward + custom-VJP backward).

The reference leaves attention to torch/HF kernels; here the training/prefill
hot op (SURVEY §2.9: "Pallas kernels only where XLA fusion is insufficient")
is a blocked online-softmax kernel so the [B, H, Q, K] score matrix never
round-trips HBM. The kernels use the canonical TPU structure: the key-tile
loop is the innermost *grid* dimension (TPU grids run sequentially), with
VMEM scratch accumulators persisting across those grid steps — initialized
at the first key tile, emitted at the last — so Mosaic double-buffers the
K/V tile DMAs against the MXU work and VMEM stays O(block² + block·D)
regardless of sequence length. ``causal=True`` masks inside the kernel and
predicates away fully-future tiles (half the MXU work) instead of
materializing a [Q, K] causal bias in HBM.

Backward recomputes scores per tile from the saved output/logsumexp (the
standard flash recomputation) in two kernels: dQ (key tiles innermost) and
dK/dV (query tiles innermost); ``delta = rowsum(dO · O)`` is folded into
both rather than materialized.

Numerics match :func:`trlx_tpu.ops.attention.dot_product_attention`: logits
and softmax statistics in float32, the two MXU matmuls in the input dtype,
finite ``NEG_INF`` masking (fully-masked rows degrade to uniform weights
exactly like ``jax.nn.softmax`` over constant logits — under ``causal`` row
0 always sees one key, so this arises only for all-padding rows).

Bias support: any additive bias broadcastable to [B, H, Q, K]; size-1
batch / head / query / key dims stay size-1 in VMEM — the BlockSpec index
map pins them to block 0. The custom VJP returns a **zero** cotangent for
the bias operand: route learned biases (T5 relative position bias) through
the XLA path instead (``dot_product_attention(..., learned_bias=True)``).

TPU layout notes: row statistics (logsumexp) carry a broadcast 128-lane
trailing dim because Mosaic requires the last two dims of every block to be
(8, 128)-aligned or span the whole array.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from trlx_tpu.compat import pallas_tpu_compiler_params

from trlx_tpu.ops.attention import NEG_INF

BLOCK_Q = 512  # best on v5e across 1k-4k sequences (see tests/test_flash_attention.py)
BLOCK_K = 512
LANES = 128  # trailing broadcast dim for row statistics


def _bias_spec(bias_shape, block_q, block_k, q_axis, k_axis):
    """BlockSpec for a [b?, h?, Q?, K?] bias under a (B, H, t1, t2) grid.

    ``q_axis``/``k_axis`` name which grid axis (2 or 3) tiles Q and K.
    Size-1 bias dims stay size-1 (index pinned to 0) so broadcast biases
    never materialize at full rank in VMEM.
    """
    b, h, q, k = bias_shape
    block = (1, 1, block_q if q > 1 else 1, block_k if k > 1 else 1)

    def index(bi, hi, t1, t2):
        ts = {2: t1, 3: t2}
        return (
            bi if b > 1 else 0,
            hi if h > 1 else 0,
            ts[q_axis] if q > 1 else 0,
            ts[k_axis] if k > 1 else 0,
        )

    return pl.BlockSpec(block, index, memory_space=pltpu.VMEM)


def _read_bias(bias_ref):
    """Load the (possibly size-1-broadcast) [q?, k?] bias block as f32."""
    if bias_ref is None:
        return None
    return bias_ref[0, 0].astype(jnp.float32)


def _causal_mask(q_lo, tq, k_lo, tk):
    """[tq, tk] additive mask: query q_lo+i sees key k_lo+j iff j+k_lo <= i+q_lo."""
    q_pos = q_lo + jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 0)
    k_pos = k_lo + jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
    return jnp.where(k_pos <= q_pos, 0.0, NEG_INF).astype(jnp.float32)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _fwd_kernel(*refs, scale, block_q, block_k, has_bias, causal):
    if has_bias:
        q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref, m_s, l_s, acc_s = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, m_s, l_s, acc_s = refs
        bias_ref = None

    qi = pl.program_id(2)
    ki = pl.program_id(3)
    n_k = pl.num_programs(3)
    q_lo = qi * block_q
    k_lo = ki * block_k

    @pl.when(ki == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, -jnp.inf)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    live = (k_lo <= q_lo + block_q - 1) if causal else True

    @pl.when(live)
    def _tile():
        q = q_ref[0, 0]  # [TQ, D]
        k_blk = k_ref[0, 0]  # [TK, D]
        v_blk = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [TQ, TK]
        b = _read_bias(bias_ref)
        if b is not None:
            s = s + b
        if causal:
            s = s + _causal_mask(q_lo, block_q, k_lo, block_k)
        m = m_s[:, 0:1]
        blk_max = jnp.max(s, axis=-1, keepdims=True)
        new_m = jnp.maximum(m, blk_max)
        alpha = jnp.exp(m - new_m)
        p = jnp.exp(s - new_m)
        l_s[:, 0:1] = l_s[:, 0:1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_s[:] = acc_s[:] * alpha + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_s[:, 0:1] = new_m

    @pl.when(ki == n_k - 1)
    def _emit():
        m = m_s[:, 0:1]
        l_safe = jnp.maximum(l_s[:, 0:1], 1e-30)
        o_ref[0, 0] = (acc_s[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = jnp.broadcast_to(
            m + jnp.log(l_safe), (block_q, lse_ref.shape[-1])
        )


def _fwd(q, k, v, bias, *, scale, block_q, block_k, causal, interpret):
    """q/k/v: [B, H, Qp, D] / [B, H, Kp, D]; returns (o, lse)."""
    B, H, Qp, D = q.shape
    Kp = k.shape[2]
    grid = (B, H, Qp // block_q, Kp // block_k)

    q_spec = pl.BlockSpec(
        (1, 1, block_q, D), lambda b, h, qi, ki: (b, h, qi, 0),
        memory_space=pltpu.VMEM,
    )
    kv_spec = pl.BlockSpec(
        (1, 1, block_k, D), lambda b, h, qi, ki: (b, h, ki, 0),
        memory_space=pltpu.VMEM,
    )
    in_specs = [q_spec, kv_spec, kv_spec]
    args = [q, k, v]
    if bias is not None:
        in_specs.append(_bias_spec(bias.shape, block_q, block_k, 2, 3))
        args.append(bias)

    out_specs = [
        q_spec,
        pl.BlockSpec(
            (1, 1, block_q, LANES), lambda b, h, qi, ki: (b, h, qi, 0),
            memory_space=pltpu.VMEM,
        ),
    ]
    o, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, scale=scale, block_q=block_q, block_k=block_k,
            has_bias=bias is not None, causal=causal,
        ),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Qp, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, Qp, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, LANES), jnp.float32),  # running max
            pltpu.VMEM((block_q, LANES), jnp.float32),  # running sum
            pltpu.VMEM((block_q, D), jnp.float32),      # output accumulator
        ],
        compiler_params=pallas_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*args)
    return o, lse


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------


def _dq_kernel(*refs, scale, block_q, block_k, has_bias, causal):
    if has_bias:
        (q_ref, k_ref, v_ref, bias_ref, do_ref, o_ref, lse_ref, dq_ref,
         dq_s) = refs
    else:
        q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, dq_ref, dq_s = refs
        bias_ref = None

    qi = pl.program_id(2)
    ki = pl.program_id(3)
    n_k = pl.num_programs(3)
    q_lo = qi * block_q
    k_lo = ki * block_k

    @pl.when(ki == 0)
    def _init():
        dq_s[:] = jnp.zeros_like(dq_s)

    live = (k_lo <= q_lo + block_q - 1) if causal else True

    @pl.when(live)
    def _tile():
        q = q_ref[0, 0]
        k_blk = k_ref[0, 0]
        v_blk = v_ref[0, 0]
        do = do_ref[0, 0].astype(jnp.float32)
        o = o_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0, :, 0:1]  # [TQ, 1]
        delta = jnp.sum(do * o, axis=-1, keepdims=True)  # [TQ, 1]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        b = _read_bias(bias_ref)
        if b is not None:
            s = s + b
        if causal:
            s = s + _causal_mask(q_lo, block_q, k_lo, block_k)
        p = jnp.exp(s - lse)  # [TQ, TK]
        dp = jax.lax.dot_general(
            do, v_blk.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta)
        dq_s[:] = dq_s[:] + jax.lax.dot_general(
            ds.astype(k_blk.dtype), k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == n_k - 1)
    def _emit():
        dq_ref[0, 0] = (dq_s[:] * scale).astype(dq_ref.dtype)


def _dkv_kernel(*refs, scale, block_q, block_k, has_bias, causal):
    if has_bias:
        (q_ref, k_ref, v_ref, bias_ref, do_ref, o_ref, lse_ref,
         dk_ref, dv_ref, dk_s, dv_s) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, dk_ref, dv_ref,
         dk_s, dv_s) = refs
        bias_ref = None

    ki = pl.program_id(2)
    qi = pl.program_id(3)
    n_q = pl.num_programs(3)
    k_lo = ki * block_k
    q_lo = qi * block_q

    @pl.when(qi == 0)
    def _init():
        dk_s[:] = jnp.zeros_like(dk_s)
        dv_s[:] = jnp.zeros_like(dv_s)

    # skip q tiles whose last query is before the first key
    live = (q_lo + block_q - 1 >= k_lo) if causal else True

    @pl.when(live)
    def _tile():
        k_blk = k_ref[0, 0]  # [TK, D]
        v32 = v_ref[0, 0].astype(jnp.float32)
        q_blk = q_ref[0, 0]  # [TQ, D]
        do = do_ref[0, 0].astype(jnp.float32)
        o = o_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0, :, 0:1]
        delta = jnp.sum(do * o, axis=-1, keepdims=True)
        s = jax.lax.dot_general(
            q_blk, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [TQ, TK]
        b = _read_bias(bias_ref)
        if b is not None:
            s = s + b
        if causal:
            s = s + _causal_mask(q_lo, block_q, k_lo, block_k)
        p = jnp.exp(s - lse)
        dv_s[:] = dv_s[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v32, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta)  # [TQ, TK]
        dk_s[:] = dk_s[:] + jax.lax.dot_general(
            ds, q_blk.astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qi == n_q - 1)
    def _emit():
        dk_ref[0, 0] = (dk_s[:] * scale).astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_s[:].astype(dv_ref.dtype)


def _bwd(q, k, v, bias, o, lse, do, *, scale, block_q, block_k, causal,
         interpret):
    B, H, Qp, D = q.shape
    Kp = k.shape[2]
    n_q, n_k = Qp // block_q, Kp // block_k

    q_tile_qk = pl.BlockSpec(
        (1, 1, block_q, D), lambda b, h, qi, ki: (b, h, qi, 0),
        memory_space=pltpu.VMEM,
    )
    kv_tile_qk = pl.BlockSpec(
        (1, 1, block_k, D), lambda b, h, qi, ki: (b, h, ki, 0),
        memory_space=pltpu.VMEM,
    )
    lse_tile_qk = pl.BlockSpec(
        (1, 1, block_q, LANES), lambda b, h, qi, ki: (b, h, qi, 0),
        memory_space=pltpu.VMEM,
    )

    # dQ: grid (B, H, nQ, nK) — K innermost, dq accumulates across it
    in_specs = [q_tile_qk, kv_tile_qk, kv_tile_qk]
    args = [q, k, v]
    if bias is not None:
        in_specs.append(_bias_spec(bias.shape, block_q, block_k, 2, 3))
        args.append(bias)
    in_specs += [q_tile_qk, q_tile_qk, lse_tile_qk]
    args += [do, o, lse]
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, block_q=block_q, block_k=block_k,
            has_bias=bias is not None, causal=causal,
        ),
        grid=(B, H, n_q, n_k),
        in_specs=in_specs,
        out_specs=q_tile_qk,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=pallas_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*args)

    # dK/dV: grid (B, H, nK, nQ) — Q innermost, dk/dv accumulate across it
    q_tile_kq = pl.BlockSpec(
        (1, 1, block_q, D), lambda b, h, ki, qi: (b, h, qi, 0),
        memory_space=pltpu.VMEM,
    )
    kv_tile_kq = pl.BlockSpec(
        (1, 1, block_k, D), lambda b, h, ki, qi: (b, h, ki, 0),
        memory_space=pltpu.VMEM,
    )
    lse_tile_kq = pl.BlockSpec(
        (1, 1, block_q, LANES), lambda b, h, ki, qi: (b, h, qi, 0),
        memory_space=pltpu.VMEM,
    )
    in_specs = [q_tile_kq, kv_tile_kq, kv_tile_kq]
    args = [q, k, v]
    if bias is not None:
        in_specs.append(_bias_spec(bias.shape, block_q, block_k, 3, 2))
        args.append(bias)
    in_specs += [q_tile_kq, q_tile_kq, lse_tile_kq]
    args += [do, o, lse]
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, block_q=block_q, block_k=block_k,
            has_bias=bias is not None, causal=causal,
        ),
        grid=(B, H, n_k, n_q),
        in_specs=in_specs,
        out_specs=[kv_tile_kq, kv_tile_kq],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=pallas_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*args)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp wrapper over padded [B, H, Q, D] layout
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, bias, scale, block_q, block_k, causal, interpret):
    o, _ = _fwd(
        q, k, v, bias, scale=scale, block_q=block_q, block_k=block_k,
        causal=causal, interpret=interpret,
    )
    return o


def _flash_fwd(q, k, v, bias, scale, block_q, block_k, causal, interpret):
    o, lse = _fwd(
        q, k, v, bias, scale=scale, block_q=block_q, block_k=block_k,
        causal=causal, interpret=interpret,
    )
    return o, (q, k, v, bias, o, lse)


def _flash_bwd(scale, block_q, block_k, causal, interpret, res, do):
    q, k, v, bias, o, lse = res
    dq, dk, dv = _bwd(
        q, k, v, bias, o, lse, do, scale=scale, block_q=block_q,
        block_k=block_k, causal=causal, interpret=interpret,
    )
    dbias = None if bias is None else jnp.zeros_like(bias)
    return dq, dk, dv, dbias


_flash.defvjp(_flash_fwd, _flash_bwd)


def _prep_block_inputs(q, k, v, bias, block_q, block_k, interpret, scale):
    """Shared prologue for the kernel entry points: interpret default,
    shrink-to-ceil8 tile sizes, [B, H, T, D] transpose + tile padding, bias
    padding/masking, default 1/sqrt(D) scale."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    D = q.shape[-1]
    if scale is None:
        scale = float(1.0 / (D ** 0.5))
    Q, K = q.shape[1], k.shape[1]
    block_q = min(block_q, max(8, -(-Q // 8) * 8))
    block_k = min(block_k, max(8, -(-K // 8) * 8))
    qt, _ = _pad_to(jnp.transpose(q, (0, 2, 1, 3)), 2, block_q)
    kt, _ = _pad_to(jnp.transpose(k, (0, 2, 1, 3)), 2, block_k)
    vt, _ = _pad_to(jnp.transpose(v, (0, 2, 1, 3)), 2, block_k)
    bias = _prepare_bias(bias, kt.shape[2], K, block_q, block_k)
    return qt, kt, vt, bias, block_q, block_k, interpret, scale


def flash_block_fwd(q, k, v, bias, scale: Optional[float] = None,
                    block_q: int = BLOCK_Q, block_k: int = BLOCK_K,
                    interpret: Optional[bool] = None):
    """Single-block forward returning the logsumexp — the building block for
    cross-block softmax combination (ring attention over the sp axis).

    q [B, Tq, H, D], k/v [B, Tk, H, D], bias broadcastable to
    [B, H, Tq, Tk]; returns (o [B, H, Tq, D] softmax-normalized in q.dtype,
    lse [B, H, Tq] f32). No causal flag: ring blocks carry positions in the
    bias. Not differentiable by itself — ring's custom VJP calls
    :func:`flash_block_bwd`.
    """
    Q = q.shape[1]
    qt, kt, vt, bias, block_q, block_k, interpret, scale = _prep_block_inputs(
        q, k, v, bias, block_q, block_k, interpret, scale
    )
    o, lse = _fwd(
        qt, kt, vt, bias, scale=scale, block_q=block_q, block_k=block_k,
        causal=False, interpret=interpret,
    )
    return o[:, :, :Q, :], lse[:, :, :Q, 0]


def flash_block_bwd(q, k, v, bias, o, lse, do, scale: Optional[float] = None,
                    block_q: int = BLOCK_Q, block_k: int = BLOCK_K,
                    interpret: Optional[bool] = None):
    """Single-block backward against an *external* (combined) logsumexp.

    Layouts: q/k/v [B, T, H, D]; o/do [B, H, Tq, D]; lse [B, H, Tq].
    Returns (dq [B, Tq, H, D], dk, dv [B, Tk, H, D]) in f32. Because ``lse``
    may come from combining many blocks, p = exp(s - lse) are the *global*
    softmax weights — exactly what the flash backward recomputes. Inputs are
    upcast to f32 so ring-accumulated gradients match the XLA block math
    bit-for-bit regardless of the activations' dtype.
    """
    B, Q, H, D = q.shape
    K = k.shape[1]
    qt, kt, vt, bias, block_q, block_k, interpret, scale = _prep_block_inputs(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32),
        bias, block_q, block_k, interpret, scale,
    )
    Qp = qt.shape[2]
    op, _ = _pad_to(o.astype(jnp.float32), 2, block_q)
    dop, _ = _pad_to(do.astype(jnp.float32), 2, block_q)
    lse_p = jnp.broadcast_to(
        _pad_to(lse, 2, block_q)[0][..., None], (B, H, Qp, LANES)
    )
    dq, dk, dv = _bwd(
        qt, kt, vt, bias, op, lse_p, dop, scale=scale, block_q=block_q,
        block_k=block_k, causal=False, interpret=interpret,
    )
    dq = jnp.transpose(dq[:, :, :Q, :], (0, 2, 1, 3))
    dk = jnp.transpose(dk[:, :, :K, :], (0, 2, 1, 3))
    dv = jnp.transpose(dv[:, :, :K, :], (0, 2, 1, 3))
    return dq, dk, dv


def _prepare_bias(bias, Kp, K, block_q, block_k):
    """Pad a [b?, h?, Q?, K?] bias to tile multiples and mask padded keys."""
    if bias is not None:
        if bias.ndim != 4:
            raise ValueError(f"bias must be rank-4, got {bias.shape}")
        bias = bias.astype(jnp.float32)
        if bias.shape[3] > 1:
            bias, _ = _pad_to(bias, 3, block_k)
        if bias.shape[2] > 1:
            bias, _ = _pad_to(bias, 2, block_q)
    if Kp != K:
        pad_bias = jnp.where(
            jnp.arange(Kp)[None, None, None, :] < K, 0.0, NEG_INF
        ).astype(jnp.float32)
        bias = pad_bias if bias is None else bias + pad_bias
    return bias


def _pad_to(x, axis, multiple):
    size = x.shape[axis]
    rem = -size % multiple
    if rem == 0:
        return x, size
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, rem)
    return jnp.pad(x, pad), size


def flash_attention(
    q: jax.Array,  # [B, Q, H, D]
    k: jax.Array,  # [B, K, H, D]
    v: jax.Array,  # [B, K, H, D]
    bias: Optional[jax.Array] = None,  # broadcastable to [B, H, Q, K]
    causal: bool = False,
    block_q: int = BLOCK_Q,
    block_k: int = BLOCK_K,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Flash attention over the framework's [B, T, H, D] layout.

    Pads Q/K to tile multiples (padded keys masked via bias, padded query
    rows dropped), transposes to [B, H, T, D] for lane-aligned tiles, and
    dispatches the custom-VJP pallas kernels. ``causal=True`` masks in-kernel
    and skips future key tiles — pass it instead of a causal bias. Gradient
    does NOT flow to ``bias`` (see module docstring).

    ``causal`` assumes query position i is absolute position i (offset 0) —
    the training / prefill case. For cache decode at an offset, pass an
    explicit bias.
    """
    Q = q.shape[1]
    qt, kt, vt, bias, block_q, block_k, interpret, scale = _prep_block_inputs(
        q, k, v, bias, block_q, block_k, interpret, None
    )
    out = _flash(qt, kt, vt, bias, scale, block_q, block_k, causal, interpret)
    out = out[:, :, :Q, :]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)
