"""Ring attention: sequence-parallel exact attention over a mesh axis.

Long-context support beyond the reference (which truncates at
``seq_length: 512`` — SURVEY §5.7): activations are sharded along the
sequence dimension over the ``sp`` mesh axis; each device holds one query
block and the key/value blocks rotate around the ring via ``ppermute`` over
ICI, with flash-style online-softmax accumulation so the full [T, T] score
matrix never materializes. Memory per device is O(T/sp * T/sp) per step and
the K/V transfer overlaps with compute in XLA's pipeline.

Usable standalone via :func:`ring_attention_sharded` (a ``shard_map`` over
the mesh) or inside larger shard_mapped programs via :func:`ring_attention`
(expects per-device blocks, runs the collective loop).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from trlx_tpu.ops.attention import NEG_INF


def ring_attention(
    q: jax.Array,  # [B, Tq, H, D] local query block
    k: jax.Array,  # [B, Tk, H, D] local key block
    v: jax.Array,  # [B, Tk, H, D] local value block
    kv_mask: Optional[jax.Array] = None,  # [B, Tk] validity of local keys
    axis_name: str = "sp",
    causal: bool = True,
) -> jax.Array:
    """Exact attention with K/V ring rotation; call inside shard_map.

    Blocks are assumed laid out in sequence order across the axis: device i
    holds global positions ``[i*Tq, (i+1)*Tq)``.
    """
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = jax.lax.rsqrt(jnp.float32(D))

    q32 = q.astype(jnp.float32)
    q_pos = idx * Tq + jnp.arange(Tq)  # global query positions

    if kv_mask is None:
        kv_mask = jnp.ones((B, Tk), jnp.int32)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(i, carry):
        acc, m, l, k_blk, v_blk, mask_blk = carry
        # the k/v currently held were rotated i times: they originate from
        # device (idx - i) mod n
        src = (idx - i) % n
        k_pos = src * Tk + jnp.arange(Tk)

        logits = jnp.einsum(
            "bqhd,bkhd->bhqk", q32, k_blk.astype(jnp.float32)
        ) * scale
        bias = jnp.where(mask_blk[:, None, None, :] > 0, 0.0, NEG_INF)
        if causal:
            bias = bias + jnp.where(
                k_pos[None, :] <= q_pos[:, None], 0.0, NEG_INF
            )[None, None]
        logits = logits + bias

        # online softmax update
        blk_max = jnp.max(logits, axis=-1)  # [B, H, Tq]
        new_m = jnp.maximum(m, blk_max)
        correction = jnp.exp(m - new_m)
        p = jnp.exp(logits - new_m[..., None])  # [B, H, Tq, Tk]
        l = l * correction + jnp.sum(p, axis=-1)
        acc = acc * correction[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32)
        )

        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        mask_blk = jax.lax.ppermute(mask_blk, axis_name, perm)
        return acc, new_m, l, k_blk, v_blk, mask_blk

    # derive the accumulators from q so they carry q's varying-axes type
    # (shard_map requires loop carries to have consistent manual-axes vma)
    zero_bhqd = jnp.transpose(q32 * 0.0, (0, 2, 1, 3))  # [B, H, Tq, D]
    zero_bhq = zero_bhqd[..., 0]
    acc0 = zero_bhqd
    m0 = zero_bhq - jnp.inf
    l0 = zero_bhq
    acc, m, l, _, _, _ = jax.lax.fori_loop(
        0, n, step, (acc0, m0, l0, k, v, kv_mask)
    )
    out = acc / jnp.maximum(l[..., None], 1e-30)
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)  # [B, Tq, H, D]


def ring_attention_sharded(
    q: jax.Array,  # [B, T, H, D] global arrays
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    kv_mask: Optional[jax.Array] = None,  # [B, T]
    axis_name: str = "sp",
    batch_axes=("dp", "fsdp"),
    causal: bool = True,
) -> jax.Array:
    """shard_map wrapper: shards T over ``axis_name``, B over batch axes."""
    from jax import shard_map

    qkv_spec = P(batch_axes, axis_name, None, None)
    mask_spec = P(batch_axes, axis_name)

    fn = functools.partial(ring_attention, axis_name=axis_name, causal=causal)
    if kv_mask is None:
        kv_mask = jnp.ones(q.shape[:2], jnp.int32)
    return shard_map(
        fn,
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
        out_specs=qkv_spec,
    )(q, k, v, kv_mask)
