"""Ring attention: sequence-parallel exact attention over a mesh axis.

Long-context support beyond the reference (which truncates at
``seq_length: 512`` — SURVEY §5.7): activations are sharded along the
sequence dimension over the ``sp`` mesh axis; each device holds one query
block and the key/value blocks rotate around the ring via ``ppermute`` over
ICI, with flash-style online-softmax accumulation so the full [T, T] score
matrix never materializes. Memory per device is O(T/sp * T/sp) per step and
the K/V transfer overlaps with compute in XLA's pipeline.

Usable standalone via :func:`ring_attention_sharded` (a ``shard_map`` over
the mesh) or inside larger shard_mapped programs via :func:`ring_attention`
(expects per-device blocks, runs the collective loop).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from trlx_tpu.ops.attention import NEG_INF


def ring_attention(
    q: jax.Array,  # [B, Tq, H, D] local query block
    k: jax.Array,  # [B, Tk, H, D] local key block
    v: jax.Array,  # [B, Tk, H, D] local value block
    kv_mask: Optional[jax.Array] = None,  # [B, Tk] validity of local keys
    axis_name: str = "sp",
    causal: bool = True,
) -> jax.Array:
    """Exact attention with K/V ring rotation; call inside shard_map.

    Blocks are assumed laid out in sequence order across the axis: device i
    holds global positions ``[i*Tq, (i+1)*Tq)``.
    """
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = jax.lax.rsqrt(jnp.float32(D))

    q32 = q.astype(jnp.float32)
    q_pos = idx * Tq + jnp.arange(Tq)  # global query positions

    if kv_mask is None:
        kv_mask = jnp.ones((B, Tk), jnp.int32)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(i, carry):
        acc, m, l, k_blk, v_blk, mask_blk = carry
        # the k/v currently held were rotated i times: they originate from
        # device (idx - i) mod n
        src = (idx - i) % n
        k_pos = src * Tk + jnp.arange(Tk)

        logits = jnp.einsum(
            "bqhd,bkhd->bhqk", q32, k_blk.astype(jnp.float32)
        ) * scale
        logits = logits + _block_bias(mask_blk, q_pos, k_pos, causal)

        # online softmax update
        blk_max = jnp.max(logits, axis=-1)  # [B, H, Tq]
        new_m = jnp.maximum(m, blk_max)
        correction = jnp.exp(m - new_m)
        p = jnp.exp(logits - new_m[..., None])  # [B, H, Tq, Tk]
        l = l * correction + jnp.sum(p, axis=-1)
        acc = acc * correction[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32)
        )

        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        mask_blk = jax.lax.ppermute(mask_blk, axis_name, perm)
        return acc, new_m, l, k_blk, v_blk, mask_blk

    # derive the accumulators from q so they carry q's varying-axes type
    # (shard_map requires loop carries to have consistent manual-axes vma)
    zero_bhqd = jnp.transpose(q32 * 0.0, (0, 2, 1, 3))  # [B, H, Tq, D]
    zero_bhq = zero_bhqd[..., 0]
    acc0 = zero_bhqd
    m0 = zero_bhq - jnp.inf
    l0 = zero_bhq
    acc, m, l, _, _, _ = jax.lax.fori_loop(
        0, n, step, (acc0, m0, l0, k, v, kv_mask)
    )
    out = acc / jnp.maximum(l[..., None], 1e-30)
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)  # [B, Tq, H, D]


# ---------------------------------------------------------------------------
# Ring flash attention: blockwise (o, lse) accumulation + custom two-pass VJP
# ---------------------------------------------------------------------------


def _block_bias(mask_blk, q_pos, k_pos, causal):
    """[B, 1, Tq, Tk] additive bias from key validity + causal positions."""
    bias = jnp.where(mask_blk[:, None, None, :] > 0, 0.0, NEG_INF)
    if causal:
        bias = bias + jnp.where(
            k_pos[None, :] <= q_pos[:, None], 0.0, NEG_INF
        )[None, None]
    return bias


# None -> auto (TPU + size crossover); True/False -> force. Tests force True
# to run the ring+pallas integration in interpret mode on CPU.
_FORCE_PALLAS_BLOCKS = None


def _use_pallas_blocks(Tq: int, Tk: int) -> bool:
    """Per-device block sizes above which the pallas kernels take over the
    inner block computation on TPU (below, XLA's fused path wins — the same
    measured crossover as the dense dispatch)."""
    if _FORCE_PALLAS_BLOCKS is not None:
        return _FORCE_PALLAS_BLOCKS
    from trlx_tpu.ops.attention import FLASH_MIN_SEQ

    return min(Tq, Tk) >= FLASH_MIN_SEQ and jax.default_backend() == "tpu"


def _block_fwd(q, k_blk, v_blk, bias, scale):
    """Per-block attention with logsumexp.

    q [B, Tq, H, D]; k/v [B, Tk, H, D]; bias [B, 1, Tq, Tk].
    Returns (o [B, H, Tq, D] f32 — softmax-normalized within the block,
    lse [B, H, Tq] f32). Large blocks on TPU run the pallas flash kernel
    (the [Tq, Tk] score matrix stays in VMEM tiles).
    """
    if _use_pallas_blocks(q.shape[1], k_blk.shape[1]):
        from trlx_tpu.ops.flash_attention import flash_block_fwd

        o, lse = flash_block_fwd(q, k_blk, v_blk, bias, scale=scale)
        return o.astype(jnp.float32), lse
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32), k_blk.astype(jnp.float32)
    ) * scale + bias
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhqk,bkhd->bhqd", p / jnp.maximum(l, 1e-30),
                   v_blk.astype(jnp.float32))
    lse = (m + jnp.log(jnp.maximum(l, 1e-30)))[..., 0]
    return o, lse


def _block_bwd(q, k_blk, v_blk, bias, o, lse, do, delta, scale):
    """Per-block gradients against the *global* (combined) logsumexp.

    ``o``/``do``/``delta`` are the GLOBAL combined output, its cotangent,
    and ``rowsum(do*o)`` — shared by every block of a ring pass (the flash
    backward's delta term is global by definition). Layouts: q [B,Tq,H,D],
    k/v [B,Tk,H,D], o/do [B,H,Tq,D], lse/delta [B,H,Tq]. Returns
    (dq [B,Tq,H,D], dk, dv [B,Tk,H,D]) in f32. Large blocks on TPU run the
    pallas backward kernels.
    """
    if _use_pallas_blocks(q.shape[1], k_blk.shape[1]):
        from trlx_tpu.ops.flash_attention import flash_block_bwd

        return flash_block_bwd(q, k_blk, v_blk, bias, o, lse, do, scale=scale)
    q32 = q.astype(jnp.float32)
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q32, k_blk.astype(jnp.float32)
    ) * scale + bias
    p = jnp.exp(s - lse[..., None])  # global softmax weights
    dv = jnp.einsum("bhqk,bhqd->bkhd", p, do)
    dp = jnp.einsum("bhqd,bkhd->bhqk", do, v_blk.astype(jnp.float32))
    ds = p * (dp - delta[..., None]) * scale
    dq = jnp.einsum("bhqk,bkhd->bqhd", ds, k_blk.astype(jnp.float32))
    dk = jnp.einsum("bhqk,bqhd->bkhd", ds, q32)
    return dq, dk, dv


def _ring_fwd(q, k, v, kv_mask, axis_name, causal):
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = float(1.0 / (D ** 0.5))
    q_pos = idx * Tq + jnp.arange(Tq)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(i, carry):
        out, lse, k_blk, v_blk, mask_blk = carry
        src = (idx - i) % n
        k_pos = src * Tk + jnp.arange(Tk)
        bias = _block_bias(mask_blk, q_pos, k_pos, causal)
        o_i, lse_i = _block_fwd(q, k_blk, v_blk, bias, scale)

        # combine softmax-normalized block results by their logsumexp weights
        m_new = jnp.maximum(lse, lse_i)
        w_old = jnp.exp(lse - m_new)
        w_new = jnp.exp(lse_i - m_new)
        denom = jnp.maximum(w_old + w_new, 1e-30)
        out = (out * w_old[..., None] + o_i * w_new[..., None]) / denom[..., None]
        lse = m_new + jnp.log(denom)

        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        mask_blk = jax.lax.ppermute(mask_blk, axis_name, perm)
        return out, lse, k_blk, v_blk, mask_blk

    # zeros derived from q for consistent shard_map vma typing
    zero_bhqd = jnp.transpose(q.astype(jnp.float32) * 0.0, (0, 2, 1, 3))
    out0 = zero_bhqd
    lse0 = zero_bhqd[..., 0] - jnp.inf
    out, lse, _, _, _ = jax.lax.fori_loop(
        0, n, step, (out0, lse0, k, v, kv_mask)
    )
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype), lse


def _ring_bwd(q, k, v, kv_mask, out, lse, dout, axis_name, causal):
    """Second ring pass: recompute per-block softmax weights from the saved
    global logsumexp (exact — no stored score matrices) and accumulate dq
    locally while dk/dv ride the rotating buffers; after the full circle
    each block's gradients land back on its home device."""
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = float(1.0 / (D ** 0.5))
    q_pos = idx * Tq + jnp.arange(Tq)
    perm = [(i, (i + 1) % n) for i in range(n)]

    q32 = q.astype(jnp.float32)
    do = jnp.transpose(dout.astype(jnp.float32), (0, 2, 1, 3))  # [B,H,Tq,D]
    o32 = jnp.transpose(out.astype(jnp.float32), (0, 2, 1, 3))
    delta = jnp.sum(do * o32, axis=-1)  # [B, H, Tq]

    def step(i, carry):
        dq, k_blk, v_blk, mask_blk, dk_blk, dv_blk = carry
        src = (idx - i) % n
        k_pos = src * Tk + jnp.arange(Tk)
        bias = _block_bias(mask_blk, q_pos, k_pos, causal)
        dq_i, dk_i, dv_i = _block_bwd(
            q, k_blk, v_blk, bias, o32, lse, do, delta, scale
        )
        dq = dq + dq_i
        dk_blk = dk_blk + dk_i
        dv_blk = dv_blk + dv_i

        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        mask_blk = jax.lax.ppermute(mask_blk, axis_name, perm)
        dk_blk = jax.lax.ppermute(dk_blk, axis_name, perm)
        dv_blk = jax.lax.ppermute(dv_blk, axis_name, perm)
        return dq, k_blk, v_blk, mask_blk, dk_blk, dv_blk

    dq0 = q32 * 0.0
    dkv0 = jnp.zeros_like(k, dtype=jnp.float32)
    dq, _, _, _, dk, dv = jax.lax.fori_loop(
        0, n, step, (dq0, k, v, kv_mask, dkv0, jnp.zeros_like(dkv0))
    )
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def ring_flash_attention(q, k, v, kv_mask, axis_name="sp", causal=True):
    """Ring attention with flash-style memory: the backward pass recomputes
    block scores from the saved (output, logsumexp) instead of autodiff
    storing every rotation's [Tq, Tk] score matrix — per-device residual
    memory is O(Tq·D) rather than O(Tq·T_global). Same semantics/layout as
    :func:`ring_attention`; call inside shard_map."""
    out, _ = _ring_fwd(q, k, v, kv_mask, axis_name, causal)
    return out


def _rfa_fwd(q, k, v, kv_mask, axis_name, causal):
    out, lse = _ring_fwd(q, k, v, kv_mask, axis_name, causal)
    return out, (q, k, v, kv_mask, out, lse)


def _rfa_bwd(axis_name, causal, res, dout):
    q, k, v, kv_mask, out, lse = res
    dq, dk, dv = _ring_bwd(q, k, v, kv_mask, out, lse, dout, axis_name, causal)
    return dq, dk, dv, None


ring_flash_attention.defvjp(_rfa_fwd, _rfa_bwd)


def ring_attention_sharded(
    q: jax.Array,  # [B, T, H, D] global arrays
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    kv_mask: Optional[jax.Array] = None,  # [B, T]
    axis_name: str = "sp",
    batch_axes=("dp", "fsdp"),
    causal: bool = True,
    impl: str = "flash",  # "flash" (recompute bwd) | "naive" (autodiff)
) -> jax.Array:
    """shard_map wrapper: shards T over ``axis_name``, B over batch axes.

    ``impl="flash"`` (default) uses :func:`ring_flash_attention`, whose
    custom VJP recomputes block scores in a second ring pass — per-device
    residuals stay O(Tq·D) at any global length. ``impl="naive"`` keeps the
    autodiff path (stores each rotation's score panel; useful as a
    reference)."""
    from trlx_tpu.compat import shard_map

    qkv_spec = P(batch_axes, axis_name, None, None)
    mask_spec = P(batch_axes, axis_name)

    if impl not in ("flash", "naive"):
        raise ValueError(f"impl must be 'flash' or 'naive', got {impl!r}")
    base = ring_flash_attention if impl == "flash" else ring_attention

    def fn(q, k, v, m):  # custom_vjp requires positional args
        return base(q, k, v, m, axis_name, causal)
    if kv_mask is None:
        kv_mask = jnp.ones(q.shape[:2], jnp.int32)
    # pallas_call outputs carry no vma annotation, which trips shard_map's
    # varying-axes type check — disable it only when the pallas block path
    # will actually run; the pure-XLA paths (incl. impl="naive" at any
    # size) keep the safety check.
    sp = mesh.shape[axis_name]
    pallas_blocks = impl == "flash" and _use_pallas_blocks(
        q.shape[1] // sp, k.shape[1] // sp
    )
    return shard_map(
        fn,
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
        out_specs=qkv_spec,
        check_vma=not pallas_blocks,
    )(q, k, v, kv_mask)
