"""Jitted autoregressive sampling: prefill + ``lax.scan`` decode.

Replaces the reference's HF ``generate`` Python token loop
(``trlx/model/nn/ppo_models.py:620-622``; ILQL's hand-rolled loop
``ilql_models.py:257-327``) with one compiled XLA program:

- prompts are left-padded to a fixed query length Q, so the last prompt
  token always sits at buffer slot Q-1 and decode writes slots Q..Q+R-1 —
  static shapes, zero recompilation across batches;
- the decode loop is ``lax.scan`` over R steps carrying the KV cache;
- per-step behavior logprobs (under the *raw* logits, matching the
  training-time recompute — the reference likewise recomputes logprobs from
  unfiltered logits, `ppo_orchestrator.py:126-155`) and value estimates are
  emitted *during* decode, so the orchestrator's separate policy recompute
  forward (`ppo_orchestrator.py:126-131`) is folded into generation
  (SURVEY §7.1 design stance).

Sampling controls: temperature, top-k, top-p, greedy; eos early-finish per
sequence with pad fill (`ilql_models.py:314-325` semantics).
"""

from __future__ import annotations

import dataclasses
import math
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import flax.struct as struct
import jax
import jax.numpy as jnp

from trlx_tpu.utils import topk_mask


@dataclass(frozen=True)
class GenerationConfig:
    """Static generation parameters (hashable: safe as a jit static arg)."""

    max_new_tokens: int = 48
    # eos suppression (HF MinLengthLogitsProcessor semantics; without it a
    # policy can collapse into emitting eos immediately — a degenerate local
    # optimum the reference randomwalks config guards with `min_length: 2`):
    # - ``min_new_tokens``: suppress eos for the first k decode steps;
    # - ``min_length``: minimum *total* length. For causal LMs we count
    #   *real* (non-pad) prompt tokens per row — a deliberate divergence
    #   from HF's MinLengthLogitsProcessor, which counts the padded row
    #   width (input_ids.shape[-1]) and so under-suppresses short prompts
    #   in left-padded mixed-length batches. For seq2seq: decoder tokens
    #   incl. the start token, as HF counts.
    min_new_tokens: int = 0
    min_length: int = 0
    # HF-style total-length cap (prompt + generated for causal; decoder
    # tokens incl. start for seq2seq): sequences reaching it finish early
    # even though the compiled decode always runs max_new_tokens steps
    # (static shapes) — remaining steps emit pad with mask 0.
    max_length: int = 0
    temperature: float = 1.0
    top_k: int = 0  # 0 = disabled
    top_p: float = 1.0  # 1.0 = disabled
    do_sample: bool = True
    eos_token_id: int = 50256
    pad_token_id: int = 50256
    # seq2seq/forced-BOS support (the fork forces a Chinese BOS token,
    # `ppo_models.py:620-622`); -1 = disabled
    forced_bos_token_id: int = -1
    decoder_start_token_id: int = 0
    # Early-exit segmented decode (causal sampler): the R-step scan runs as
    # fixed segments of gcd(R, decode_segment_size) steps, each wrapped in a
    # lax.cond that skips the transformer apply once EVERY row has finished
    # — the compiled program keeps static shapes but stops paying the
    # per-token forward for all-pad tail steps (EOS-heavy workloads
    # otherwise burn the full max_new_tokens budget emitting pad). 0
    # disables segmentation (one monolithic scan). Segmented and monolithic
    # decode are bitwise-identical (tests/test_sampling.py).
    decode_segment_size: int = 8
    # Per-row RNG (docs/inference.md): the sampler's ``rng`` argument is a
    # [B, 2] array of per-row base keys instead of one batch key, and step
    # t of row b samples with ``fold_in(row_keys[b], t)`` — each row's
    # token sequence depends only on (its key, its logits), never on batch
    # composition or position. This is the contract that makes the
    # continuous-batching engine (which always samples per-row) per-row
    # token-identical to this fixed-batch sampler regardless of admission
    # order. Default off: the legacy one-key-per-step batch draw stays
    # bitwise-stable for existing runs.
    per_row_rng: bool = False

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "GenerationConfig":
        d = dict(d)
        # reference configs write HF's ``max_length`` (their gen budget;
        # `configs/ppo_config.yml` "LM max sample gen length") — map it to
        # the decode budget rather than silently dropping it. Note this
        # over-allocates: the compiled decode scans max_length steps (and
        # sizes the KV cache for them) even when long prompts eat most of
        # the total budget; the cap masks the surplus steps as pad. Set
        # max_new_tokens explicitly to bound decode work for long prompts.
        if "max_length" in d and "max_new_tokens" not in d:
            d["max_new_tokens"] = d["max_length"]
        known = {f.name for f in dataclasses.fields(cls)}
        d = {k: v for k, v in d.items() if k in known}
        # reference YAMLs write numeric fields as floats (``top_k: 0.0``,
        # `configs/ppo_gptj.yml`); coerce integral fields
        for name in ("max_new_tokens", "min_new_tokens", "min_length",
                     "max_length", "top_k",
                     "eos_token_id", "pad_token_id", "forced_bos_token_id",
                     "decoder_start_token_id", "decode_segment_size"):
            if name in d and d[name] is not None:
                d[name] = int(d[name])
        return cls(**d)


@struct.dataclass
class SampleOutput:
    """Rollout result, shapes [B, R]; all device-resident."""

    tokens: jax.Array  # sampled response tokens (pad after eos)
    response_mask: jax.Array  # 1 up to and including the eos token
    logprobs: jax.Array  # behavior logprobs under raw logits
    values: jax.Array  # value-head estimates at each decision point


def validate_gen_config(cfg: GenerationConfig, vocab_size, provided=None) -> None:
    """Fail loudly on token ids outside the model's vocab — an out-of-range
    ``forced_bos_token_id`` (e.g. the UL2 fork's Chinese BOS 21128 against a
    small from-scratch vocab) otherwise surfaces as NaNs deep in generation.
    No-op when the model config exposes no vocab size. When ``provided`` is
    given (the keys the user/tokenizer actually set), only those fields are
    checked — dataclass defaults (gpt2's eos 50256) must not crash a
    small-vocab from-scratch config that never set them.
    """
    if not vocab_size:
        return
    for name in ("eos_token_id", "pad_token_id", "forced_bos_token_id",
                 "decoder_start_token_id"):
        if provided is not None and name not in provided:
            continue
        tid = getattr(cfg, name)
        if tid is None or tid < 0:
            continue
        if tid >= vocab_size:
            raise ValueError(
                f"gen_kwargs {name}={tid} is outside the model vocab "
                f"(vocab_size={vocab_size}) — check that the generation "
                f"config matches the checkpoint/arch"
            )


def suppress_eos_before_min(
    logits: jax.Array,
    t: jax.Array,
    cfg: GenerationConfig,
    min_new: Optional[jax.Array] = None,
) -> jax.Array:
    """Mask the eos logit while ``t < min_new`` (HF MinLengthLogitsProcessor
    semantics; applied before top-k/top-p as HF does). ``min_new`` is the
    per-sequence [B] (or scalar) number of suppressed steps the caller
    derives from min_new_tokens/min_length; no-op when eos is unset."""
    if min_new is None or cfg.eos_token_id is None or cfg.eos_token_id < 0:
        return logits
    eos_col = (
        jnp.zeros((logits.shape[-1],), bool).at[cfg.eos_token_id].set(True)
    )
    active = jnp.asarray(t < min_new)
    if active.ndim == 0:
        active = active[None]
    return jnp.where(active[:, None] & eos_col[None, :], -jnp.inf, logits)


def concat_cols(a: jax.Array, b: jax.Array) -> jax.Array:
    """[B, Qa] ++ [B, Qb] along axis 1 via dynamic_update_slice.

    NOT jnp.concatenate: the masks this builds feed shard_map programs
    (pp decode) and committed-sharded buffers, and XLA's SPMD partitioner
    mis-lowers a concatenate operand on any mesh with a spare size>1
    axis — the same compiler-bug family as the sharded rollout-concat
    replica-sum (data/ppo_types.py::concat_rollouts) and the stage
    stacking (tools/pp_miscompile_repro.py). Shared by the fixed-batch
    sampler and the continuous engine's mask construction."""
    buf = jnp.zeros((a.shape[0], a.shape[1] + b.shape[1]), a.dtype)
    buf = jax.lax.dynamic_update_slice(buf, a, (0, 0))
    return jax.lax.dynamic_update_slice(buf, b.astype(a.dtype), (0, a.shape[1]))


def stack_cols(xs) -> jax.Array:
    """Stack [B] columns into [B, len(xs)] via dynamic_update_slice
    writes — NOT ``jnp.stack``, for the same SPMD mis-lowering reasons
    as :func:`concat_cols` (the verify step's per-column outputs are
    committed-sharded on the batch axis)."""
    first = xs[0]
    buf = jnp.zeros((first.shape[0], len(xs)), first.dtype)
    for j, x in enumerate(xs):
        buf = jax.lax.dynamic_update_slice(
            buf, x.astype(first.dtype)[:, None], (0, j)
        )
    return buf


def make_row_keys(phase_key: jax.Array, indices: jax.Array) -> jax.Array:
    """[N, 2] per-row base keys: ``fold_in(phase_key, index)`` per row.

    ``indices`` are the rows' global draw positions within the phase —
    the same prompt drawn at the same position gets the same key whether
    it decodes in the fixed batch or through the continuous engine's
    slots, which is the root of the two engines' per-row parity."""
    return jax.vmap(lambda i: jax.random.fold_in(phase_key, i))(
        jnp.asarray(indices, jnp.int32)
    )


def choose_tokens(
    gen_config: GenerationConfig,
    logits_last: jax.Array,  # [B, V] float32 raw logits
    t,  # scalar or [B] per-row decode step
    finished: jax.Array,  # [B] bool
    value_last: jax.Array,  # [B] float32
    n_real,  # [B] real prompt lengths (for the max_length cap)
    min_new=None,  # scalar/[B] eos-suppression horizon (None = off)
    key=None,  # batch mode: one key for the whole [B, V] draw
    row_keys=None,  # per-row mode: [B, 2] base keys, folded with t
):
    """One decode step's token selection — the kernel shared by the
    fixed-batch sampler and the continuous engine's ``decode_step``.

    Returns ``(token, live_i32, logprob, value_out, finished_next)`` with
    the fixed sampler's exact semantics: finished rows emit deterministic
    ``(pad, 0, 0.0, 0.0)``; the behavior logprob is taken under the RAW
    logits; ``finished_next`` folds in eos and the HF total-length cap.
    Exactly one of ``key`` / ``row_keys`` must be given when sampling.
    """
    if gen_config.forced_bos_token_id >= 0:
        forced = jnp.full(
            (logits_last.shape[0],), gen_config.forced_bos_token_id, jnp.int32
        )
    else:
        forced = None
    choice_logits = suppress_eos_before_min(logits_last, t, gen_config, min_new)
    if gen_config.do_sample:
        filtered = filter_logits(choice_logits, gen_config)
        if row_keys is not None:
            B = logits_last.shape[0]
            # the verify step calls this once per drafted column, so one
            # `row_keys` lineage feeds D+1 fold_ins in a single program —
            # each folds a DISTINCT step index t0+j (independent streams
            # by the fold constant), which the key-reuse dataflow rule
            # cannot prove from the jaxpr alone
            keys_t = jax.vmap(jax.random.fold_in)(  # tpu-lint: disable=key-reuse
                row_keys, jnp.broadcast_to(jnp.asarray(t, jnp.int32), (B,))
            )
            token = jax.vmap(
                lambda kk, lg: jax.random.categorical(kk, lg)
            )(keys_t, filtered)
        else:
            token = jax.random.categorical(key, filtered, axis=-1)
    else:
        token = jnp.argmax(choice_logits, axis=-1)
    token = token.astype(jnp.int32)
    if forced is not None:
        token = jnp.where(jnp.asarray(t) == 0, forced, token)
    token = jnp.where(finished, gen_config.pad_token_id, token)

    # behavior logprob under the *raw* logits: gather + logsumexp
    # (one [B] gather instead of materializing [B, V] log_softmax)
    logprob = (
        jnp.take_along_axis(logits_last, token[:, None], axis=-1)[:, 0]
        - jax.scipy.special.logsumexp(logits_last, axis=-1)
    )
    live = jnp.logical_not(finished)
    # finished rows emit deterministic zeros for logprob/value (these
    # slots are response_mask==0 everywhere downstream): the emissions
    # then depend only on `finished`, never on the post-finish
    # logits/values — which is what lets the segmented decode (and the
    # engine's recycled slots) skip/ignore stale state bitwise-safely.
    logprob = jnp.where(live, logprob, 0.0)
    value_out = jnp.where(live, value_last, 0.0)
    finished = jnp.logical_or(finished, token == gen_config.eos_token_id)
    if gen_config.max_length > 0:
        # HF total-length cap: prompt + generated >= max_length
        finished = jnp.logical_or(
            finished, n_real + jnp.asarray(t) + 1 >= gen_config.max_length
        )
    return token, live.astype(jnp.int32), logprob, value_out, finished


def accept_drafts(
    gen_config: GenerationConfig,
    logits_seq: jax.Array,  # [B, D, V] f32: column j-1 = logits after the
    #   anchor and the first j-1 draft tokens (predicts token t0 + j)
    values_seq: jax.Array,  # [B, D] f32 value estimates at those columns
    t0,  # [B] int32 decode step of the anchor token
    finished: jax.Array,  # [B] bool AFTER the anchor (its finished_next)
    accepted0: jax.Array,  # [B] bool — the anchor token was live
    n_real,  # [B] real prompt lengths
    draft: jax.Array,  # [B, D] int32 host-proposed tokens for t0+1..t0+D
    draft_len: jax.Array,  # [B] int32 valid draft columns (0..D)
    row_keys: jax.Array,  # [B, 2] per-row base keys
    min_new=None,
    budget: int = 0,  # R — tokens past it are never accepted
):
    """Longest-prefix draft acceptance — the speculative verify step's
    token kernel (docs/inference.md "Speculative decoding").

    Runs the EXACT one-token kernel (:func:`choose_tokens`, under the
    same ``fold_in(row_key, t0+j)`` per-row keys) at every drafted
    position and accepts draft ``j`` iff every earlier position was
    accepted and the target sample equals the draft token. Because the
    per-row RNG contract makes token ``t`` a pure function of
    (row key, logits at ``t``) and the accepted prefix reproduces the
    sequential loop's inputs position by position, accepted tokens are
    bitwise the tokens the one-token loop would have sampled — rejection
    never needs a rollback, only the refusal to accept what follows.

    Unrolled over the (small, static) draft width D so every column is
    literally a ``choose_tokens`` call — one parity surface, no scan
    re-association. Returns ``(tokens, accepted, logprobs, values,
    n_accepted, finished_next)`` with shapes [B, D] / [B]; ``accepted``
    is a contiguous int32 prefix mask per row.
    """
    B, D = draft.shape[0], draft.shape[1]
    acc_prev = jnp.asarray(accepted0, bool)
    fin = finished
    n_acc = jnp.zeros((B,), jnp.int32)
    toks, accs, lps, vals = [], [], [], []
    for j in range(1, D + 1):
        token, live, logprob, value_out, fin_next = choose_tokens(
            gen_config,
            logits_seq[:, j - 1],
            t0 + j,
            fin,
            values_seq[:, j - 1],
            n_real,
            min_new=min_new,
            row_keys=row_keys,
        )
        ok = (
            acc_prev
            & (live == 1)
            & (j <= draft_len)
            & (token == draft[:, j - 1])
            & (t0 + j < budget)
        )
        # finished advances only along the accepted prefix: a rejected
        # position's eos (if any) is re-sampled by a later step
        fin = jnp.where(ok, fin_next, fin)
        n_acc = n_acc + ok.astype(jnp.int32)
        acc_prev = ok
        toks.append(token)
        accs.append(ok.astype(jnp.int32))
        lps.append(logprob)
        vals.append(value_out)
    return (
        stack_cols(toks),
        stack_cols(accs),
        stack_cols(lps),
        stack_cols(vals),
        n_acc,
        fin,
    )


def filter_logits(logits: jax.Array, cfg: GenerationConfig) -> jax.Array:
    """Temperature / top-k / top-p filtering (float32 in, float32 out)."""
    if cfg.temperature != 1.0:
        logits = logits / cfg.temperature
    if cfg.top_k > 0:
        logits = topk_mask(logits, cfg.top_k)
    if cfg.top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep tokens until cumulative prob exceeds top_p (always >= 1 token)
        cutoff_mask = cum - probs < cfg.top_p
        kth = jnp.sum(cutoff_mask, axis=-1, keepdims=True)  # tokens kept
        threshold = jnp.take_along_axis(sorted_logits, kth - 1, axis=-1)
        logits = jnp.where(logits < threshold, -jnp.inf, logits)
    return logits


def make_sampler(
    apply_fn: Callable,
    init_cache_fn: Callable,
    gen_config: GenerationConfig,
    query_length: int,
    with_values: bool = True,
    cache_sharding=None,
):
    """Build a jittable ``(params, prompt_ids, prompt_mask, rng) ->
    SampleOutput`` closure.

    ``apply_fn(params, input_ids, attention_mask, position_ids, cache,
    cache_index)`` must return a dict with "logits", "cache" and (if
    ``with_values``) "values". ``init_cache_fn(batch, capacity)`` builds the
    KV buffers.

    ``cache_sharding`` (optional ``NamedSharding``): pins the KV buffers'
    layout — e.g. ``P((dp, fsdp), "sp")`` to shard the *capacity* axis over
    a sequence-parallel mesh axis, so long-context rollouts hold only
    ``cap / sp`` of the cache per device. The decode attention over the
    sharded cache is expressed normally; GSPMD inserts the cross-shard
    softmax reduction (the collective moves [B, H, cap] logits, head_dim
    times less than gathering the cache itself). Applied to the initial
    buffers and re-pinned on each step's updated cache so the constraint
    sticks through the scan carry.
    """
    Q = query_length
    R = gen_config.max_new_tokens
    cap = Q + R

    def pin_cache(cache):
        if cache_sharding is None:
            return cache
        return jax.tree_util.tree_map(
            lambda a: jax.lax.with_sharding_constraint(a, cache_sharding),
            cache,
        )
    # Optional fast-prefill contract: an apply_fn accepting ``last_only``
    # may skip LM-head/value computation for all but the final position.
    import inspect

    _prefill_kwargs = (
        {"last_only": True}
        if "last_only" in inspect.signature(apply_fn).parameters
        else {}
    )

    def sampler(params, prompt_ids, prompt_mask, rng) -> SampleOutput:
        B = prompt_ids.shape[0]
        n_real = jnp.sum(prompt_mask, axis=-1)  # [B]

        # eos-suppression horizon: min_length counts real prompt tokens +
        # generated (HF causal semantics)
        if gen_config.min_new_tokens > 0 or gen_config.min_length > 0:
            min_new = jnp.maximum(
                gen_config.min_new_tokens, gen_config.min_length - n_real
            )
        else:
            min_new = None

        cache = pin_cache(init_cache_fn(B, cap))
        # prefill: cache validity = prompt mask over slots [0, Q)
        pad_tail = jnp.zeros((B, R), dtype=prompt_mask.dtype)
        cache_mask = concat_cols(prompt_mask, pad_tail)
        positions = jnp.clip(jnp.cumsum(prompt_mask, axis=-1) - 1, 0, None)
        out = apply_fn(
            params,
            prompt_ids,
            attention_mask=cache_mask,
            position_ids=positions,
            cache=cache,
            cache_index=0,
            **_prefill_kwargs,
        )
        cache = pin_cache(out["cache"])
        logits_last = out["logits"][:, -1].astype(jnp.float32)  # [B, V]
        if with_values:
            value_last = out["values"][:, -1].astype(jnp.float32)
        else:
            value_last = jnp.zeros((B,), jnp.float32)

        slot_ids = jnp.arange(cap)[None, :]

        def step(carry, t):
            cache, logits_last, value_last, finished, rng = carry
            if gen_config.per_row_rng:
                # `rng` is the [B, 2] per-row base keys — folded with t
                # inside choose_tokens, never chained through the carry
                key, row_keys = None, rng
            else:
                rng, key = jax.random.split(rng)
                row_keys = None
            # token selection + behavior logprob: the kernel shared with
            # the continuous engine's decode_step (finished rows emit
            # deterministic (pad, 0, 0.0, 0.0) — see choose_tokens)
            token, live, logprob, value_out, finished = choose_tokens(
                gen_config, logits_last, t, finished, value_last, n_real,
                min_new=min_new, key=key, row_keys=row_keys,
            )

            ys = (token, live, logprob, value_out)

            # forward the sampled token at slot Q+t
            cache_mask_t = (slot_ids <= Q + t).astype(jnp.int32) * concat_cols(
                prompt_mask, jnp.ones((B, R), prompt_mask.dtype)
            )
            out = apply_fn(
                params,
                token[:, None],
                attention_mask=cache_mask_t,
                position_ids=(n_real + t)[:, None],
                cache=cache,
                cache_index=Q + t,
            )
            new_logits = out["logits"][:, 0].astype(jnp.float32)
            new_value = (
                out["values"][:, 0].astype(jnp.float32)
                if with_values
                else jnp.zeros((B,), jnp.float32)
            )
            return (pin_cache(out["cache"]), new_logits, new_value, finished, rng), ys

        if gen_config.max_length > 0:
            # prompts already at/over the total-length cap emit no tokens
            finished0 = n_real >= gen_config.max_length
        else:
            finished0 = jnp.zeros((B,), bool)
        carry0 = (cache, logits_last, value_last, finished0, rng)

        seg = (
            math.gcd(R, gen_config.decode_segment_size)
            if gen_config.decode_segment_size > 0
            else R
        )
        n_seg = R // seg
        if n_seg <= 1:
            # monolithic scan: every step runs the transformer apply
            _, (tokens, mask, logprobs, values) = jax.lax.scan(
                step, carry0, jnp.arange(R)
            )
        else:
            # Early-exit segmented decode: scan over n_seg segments of
            # `seg` steps; once every row is finished the segment's cond
            # takes the skip branch — no transformer apply, no cache
            # update. Bitwise-identical to the monolithic scan: finished
            # rows emit (pad, 0, 0.0, 0.0) regardless of branch, the RNG
            # carry advances by exactly one split per step in both
            # branches, and rows never un-finish, so the stale
            # cache/logits carried past a skipped segment are never read.
            def run_seg(carry, ts):
                return jax.lax.scan(step, carry, ts)

            def skip_seg(carry, ts):
                cache, logits_last, value_last, finished, rng = carry

                if not gen_config.per_row_rng:
                    # legacy batch keys chain through the carry: advance
                    # by exactly one split per skipped step so segmented
                    # and monolithic decode stay bitwise-identical.
                    # Per-row keys are fold_in(row_key, t) — stateless in
                    # t — so there is nothing to advance.
                    def skip_step(r, t):
                        return jax.random.split(r)[0], None

                    rng, _ = jax.lax.scan(skip_step, rng, ts)
                k = ts.shape[0]
                ys = (
                    jnp.full((k, B), gen_config.pad_token_id, jnp.int32),
                    jnp.zeros((k, B), jnp.int32),
                    jnp.zeros((k, B), jnp.float32),
                    jnp.zeros((k, B), jnp.float32),
                )
                return (cache, logits_last, value_last, finished, rng), ys

            def seg_body(carry, ts):
                return jax.lax.cond(
                    jnp.all(carry[3]), skip_seg, run_seg, carry, ts
                )

            _, (tokens, mask, logprobs, values) = jax.lax.scan(
                seg_body, carry0, jnp.arange(R).reshape(n_seg, seg)
            )
            tokens, mask, logprobs, values = (
                x.reshape(R, B) for x in (tokens, mask, logprobs, values)
            )
        return SampleOutput(
            tokens=tokens.T,
            response_mask=mask.T,
            logprobs=logprobs.T,
            values=values.T,
        )

    return sampler


def make_seq2seq_sampler(
    encode_fn: Callable,
    decode_fn: Callable,
    init_cross_kv_fn: Callable,
    init_cache_fn: Callable,
    gen_config: GenerationConfig,
    with_values: bool = True,
    cache_sharding=None,
):
    """Compiled encoder-decoder sampling (the fork's T5 ``generate`` path,
    `ppo_models.py:620-622`, as one XLA program).

    ``cache_sharding`` (optional ``NamedSharding``): shards the
    cross-attention K/V's *encoder length* axis (dim 1) — the long-context
    object for seq2seq rollouts — over a sequence-parallel mesh axis. The
    decoder self-attn cache (capacity = generation length + 1) stays
    replicated: it is short by construction.

    Encoder runs once; cross-attention K/V are precomputed per layer; the
    decoder scan feeds one token per step into a fixed-capacity self-attn
    cache. The decoder-start token occupies cache slot 0 (stripped from the
    response, as the reference strips it at `ppo_orchestrator.py:80`);
    ``forced_bos_token_id`` (the fork's Chinese BOS) is emitted at step 0
    when configured.

    - ``encode_fn(params, input_ids, attention_mask) -> encoder_hidden``
    - ``init_cross_kv_fn(params, encoder_hidden) -> cross_kv``
    - ``decode_fn(params, decoder_input_ids, encoder_mask, decoder_mask,
      cache, cache_index, cross_kv) -> {"logits", "values"?, "cache"}``
    - ``init_cache_fn(batch, capacity) -> decoder KV buffers``
    """
    R = gen_config.max_new_tokens
    cap = R + 1  # slot 0 = decoder start token

    def sampler(params, prompt_ids, prompt_mask, rng) -> SampleOutput:
        B = prompt_ids.shape[0]
        # min_length counts decoder tokens incl. the start token (HF
        # encoder-decoder semantics)
        if gen_config.min_new_tokens > 0 or gen_config.min_length > 0:
            min_new = jnp.maximum(
                gen_config.min_new_tokens, gen_config.min_length - 1
            )
        else:
            min_new = None
        encoder_hidden = encode_fn(params, prompt_ids, prompt_mask)
        cross_kv = init_cross_kv_fn(params, encoder_hidden)
        if cache_sharding is not None:
            cross_kv = jax.tree_util.tree_map(
                lambda a: jax.lax.with_sharding_constraint(a, cache_sharding),
                cross_kv,
            )
        cache = init_cache_fn(B, cap)
        slot_ids = jnp.arange(cap)[None, :]

        start = jnp.full((B, 1), gen_config.decoder_start_token_id, jnp.int32)
        out = decode_fn(
            params,
            start,
            encoder_mask=prompt_mask,
            decoder_mask=(slot_ids <= 0).astype(jnp.int32).repeat(B, 0),
            cache=cache,
            cache_index=0,
            cross_kv=cross_kv,
        )
        cache = out["cache"]
        logits_last = out["logits"][:, -1].astype(jnp.float32)
        value_last = (
            out["values"][:, -1].astype(jnp.float32)
            if with_values
            else jnp.zeros((B,), jnp.float32)
        )

        def step(carry, t):
            cache, logits_last, value_last, finished, rng = carry
            rng, key = jax.random.split(rng)

            choice_logits = suppress_eos_before_min(logits_last, t, gen_config, min_new)
            if gen_config.do_sample:
                filtered = filter_logits(choice_logits, gen_config)
                token = jax.random.categorical(key, filtered, axis=-1)
            else:
                token = jnp.argmax(choice_logits, axis=-1)
            token = token.astype(jnp.int32)
            if gen_config.forced_bos_token_id >= 0:
                token = jnp.where(
                    t == 0,
                    jnp.full((B,), gen_config.forced_bos_token_id, jnp.int32),
                    token,
                )
            token = jnp.where(finished, gen_config.pad_token_id, token)

            logprob = (
                jnp.take_along_axis(logits_last, token[:, None], axis=-1)[:, 0]
                - jax.scipy.special.logsumexp(logits_last, axis=-1)
            )
            live = jnp.logical_not(finished)
            finished = jnp.logical_or(finished, token == gen_config.eos_token_id)
            if gen_config.max_length > 0:
                # decoder tokens incl. the start token: (t+1 generated) + 1
                finished = jnp.logical_or(
                    finished, t + 2 >= gen_config.max_length
                )
            ys = (token, live.astype(jnp.int32), logprob, value_last)

            dec_mask = (slot_ids <= t + 1).astype(jnp.int32).repeat(B, 0)
            out = decode_fn(
                params,
                token[:, None],
                encoder_mask=prompt_mask,
                decoder_mask=dec_mask,
                cache=cache,
                cache_index=t + 1,
                cross_kv=cross_kv,
            )
            new_logits = out["logits"][:, 0].astype(jnp.float32)
            new_value = (
                out["values"][:, 0].astype(jnp.float32)
                if with_values
                else jnp.zeros((B,), jnp.float32)
            )
            return (out["cache"], new_logits, new_value, finished, rng), ys

        if gen_config.max_length > 0:
            finished0 = jnp.full((B,), 1 >= gen_config.max_length)
        else:
            finished0 = jnp.zeros((B,), bool)
        _, (tokens, mask, logprobs, values) = jax.lax.scan(
            step,
            (cache, logits_last, value_last, finished0, rng),
            jnp.arange(R),
        )
        return SampleOutput(
            tokens=tokens.T,
            response_mask=mask.T,
            logprobs=logprobs.T,
            values=values.T,
        )

    return sampler
