"""PPO math: config, GAE, clipped surrogate loss, KL controllers.

TPU-native re-design of the reference's ``PPOConfig`` RL math
(``trlx/model/nn/ppo_models.py:64-199``) and KL controllers (:26-58):

- The config is pure data (registered in the method registry); the math
  lives in jit-compiled functions taking it as a static argument.
- GAE's reversed-time Python loop (`ppo_models.py:128-135` — a per-timestep
  host loop in the reference) becomes a ``lax.scan`` with ``reverse=True``:
  one fused device program, no host round-trips, differentiable-free.
- Whitening / means are masked by the real response mask. (The reference
  feeds an all-ones mask so pad tokens leak into the loss —
  `accelerate_ppo_model.py:111-116`, SURVEY §8 — a bug we do not replicate.)
- KL controller updates (`ppo_models.py:26-58`) are pure
  ``(state, kl) -> state`` functions over a scalar carried in train state.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from trlx_tpu.data.method_configs import MethodConfig, register_method
from trlx_tpu.parallel.collectives import masked_mean, whiten


@register_method
@dataclass
class PPOConfig(MethodConfig):
    """PPO hyperparameters (reference `ppo_models.py:104-119`).

    :param ppo_epochs: optimization epochs per rollout batch.
    :param num_rollouts: rollouts collected per experience phase.
    :param chunk_size: prompts per generation chunk.
    :param init_kl_coef: starting KL penalty coefficient.
    :param target: adaptive-KL target (None -> fixed controller).
    :param horizon: adaptive-KL horizon.
    :param gamma / lam: GAE discounting.
    :param cliprange / cliprange_value: PPO clipping.
    :param vf_coef: value-loss weight.
    :param scale_reward: "running" | "ref" | "group" | None ("group" whitens scores within each same-prompt group; needs group_size >= 2).
    :param cliprange_reward: clip scores to +-this after scaling.
    :param gen_kwargs: generation params (max_new_tokens, top_k, top_p,
        temperature, do_sample).
    """

    name: str = "PPOConfig"
    ppo_epochs: int = 4
    num_rollouts: int = 128
    chunk_size: int = 128
    init_kl_coef: float = 0.2
    target: Optional[float] = 6.0
    horizon: int = 10000
    gamma: float = 1.0
    lam: float = 0.95
    cliprange: float = 0.2
    cliprange_value: float = 0.2
    vf_coef: float = 1.0
    # entropy-bonus weight (beyond parity; 0 = exact reference loss)
    ent_coef: float = 0.0
    # rollouts sampled per prompt (beyond parity; the orchestrator repeats
    # each chunk prompt this many times, contiguously). With > 1,
    # scale_reward "group" whitens scores within each same-prompt group.
    group_size: int = 1
    scale_reward: Optional[str] = None
    ref_mean: Optional[float] = None
    ref_std: Optional[float] = None
    cliprange_reward: float = 10.0
    gen_kwargs: Dict[str, Any] = field(
        default_factory=lambda: dict(max_new_tokens=48, top_k=0, top_p=1.0, do_sample=True)
    )


def policy_entropy(logits: jax.Array) -> jax.Array:
    """Per-position policy entropy H = logsumexp(l) - sum softmax(l)*l,
    with f32 accumulation. The ONE definition shared by the PPO
    trainers (entropy bonus + health stats) and ``ilql_loss``'s health
    entropy — a precision/masking fix here reaches every consumer."""
    l = logits.astype(jnp.float32)
    p = jax.nn.softmax(l, axis=-1)
    return jax.scipy.special.logsumexp(l, axis=-1) - jnp.sum(p * l, axis=-1)


def group_whiten(values, group_size: int):
    """Normalize within contiguous groups of ``group_size``:
    (v - group_mean) / (group_std + 1e-6). Works on host numpy arrays and
    traced jnp arrays alike (method-dispatch ops only) — the single
    definition of "group whitening" shared by GRPO advantages and PPO's
    ``scale_reward: "group"``."""
    grouped = values.reshape(-1, group_size)
    mean = grouped.mean(axis=1, keepdims=True)
    std = grouped.std(axis=1, keepdims=True)
    return ((grouped - mean) / (std + 1e-6)).reshape(-1)


def get_advantages_and_returns(
    values: jax.Array,  # [B, R]
    rewards: jax.Array,  # [B, R]
    mask: jax.Array,  # [B, R] 1 on real response tokens
    gamma: float,
    lam: float,
    use_whitening: bool = True,
) -> Tuple[jax.Array, jax.Array]:
    """GAE as a reversed ``lax.scan`` over time (reference
    `ppo_models.py:121-139` runs this loop in Python on host tensors).

    Positions beyond the response (mask==0) carry zero advantage; the
    next-step value is masked so episodes end at the last real token.
    """
    mask = mask.astype(values.dtype)
    values = values * mask
    rewards = rewards * mask

    next_values = jnp.concatenate([values[:, 1:], jnp.zeros_like(values[:, :1])], axis=1)
    next_mask = jnp.concatenate([mask[:, 1:], jnp.zeros_like(mask[:, :1])], axis=1)
    deltas = rewards + gamma * next_values * next_mask - values

    def scan_fn(carry, xs):
        delta_t, mask_t = xs
        adv = delta_t + gamma * lam * carry * mask_t
        return adv, adv

    # scan over time axis: transpose to [R, B]
    _, adv_rev = jax.lax.scan(
        scan_fn,
        jnp.zeros_like(deltas[:, 0]),
        (deltas.T, next_mask.T),
        reverse=True,
    )
    advantages = adv_rev.T * mask
    returns = advantages + values
    if use_whitening:
        advantages = whiten(advantages, mask) * mask
    return jax.lax.stop_gradient(advantages), jax.lax.stop_gradient(returns)


def ppo_loss(
    logprobs: jax.Array,  # [B, R] new policy logprobs of taken actions
    values: jax.Array,  # [B, R] new value predictions
    old_logprobs: jax.Array,  # [B, R] behavior logprobs
    old_values: jax.Array,  # [B, R] rollout-time values
    advantages: jax.Array,  # [B, R]
    returns: jax.Array,  # [B, R]
    mask: jax.Array,  # [B, R]
    cliprange: float,
    cliprange_value: float,
    vf_coef: float,
    ent_coef: float = 0.0,
    entropy: Optional[jax.Array] = None,  # [B, R] per-position policy entropy
    health: bool = False,
    health_ev: bool = True,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Clipped-surrogate PPO loss (reference `ppo_models.py:141-199`).

    Returns (scalar loss, stats dict). All means are masked over real
    response tokens; under a sharded batch the means are global (GSPMD).

    ``ent_coef``/``entropy`` add an optional entropy bonus (beyond parity —
    the reference has none): ``loss -= ent_coef * mean(entropy)``. Sparse
    terminal-reward tasks (randomwalks) can collapse into low-entropy local
    optima without it.

    ``health`` (``train.health.enabled``) fuses the training-dynamics
    scalars the run-health detectors consume into the stats dict —
    ``health/entropy`` (also meaningful at ``ent_coef=0``),
    ``health/log_ratio_max|min`` (ratio-explosion precursors), and the
    value-function explained variance (skipped when ``health_ev`` is
    False — GRPO's returns slot carries a placeholder). Pure extra
    *outputs*: nothing feeds back into the loss, so enabling health is
    bitwise-inert on training (pinned in tests/test_phase_overlap.py),
    and the scalars ride the step's existing stats transfer.
    """
    mask = mask.astype(values.dtype)
    n = jnp.maximum(jnp.sum(mask), 1.0)

    values_clipped = jnp.clip(
        values, old_values - cliprange_value, old_values + cliprange_value
    )
    vf_loss1 = (values - returns) ** 2
    vf_loss2 = (values_clipped - returns) ** 2
    vf_loss = 0.5 * jnp.sum(jnp.maximum(vf_loss1, vf_loss2) * mask) / n
    vf_clipfrac = jnp.sum((vf_loss2 > vf_loss1) * mask) / n

    log_ratio = (logprobs - old_logprobs) * mask
    # exp overflow guard: under mixed fsdp/tp meshes the recomputed
    # logprobs can drift far from the behavior logprobs; exp of an
    # unclamped log-ratio overflows to inf (then inf * 0 advantages mint
    # NaN). e^±30 is far outside the surrogate's clip band, so the clamp
    # never changes a finite loss value.
    ratio = jnp.exp(jnp.clip(log_ratio, -30.0, 30.0))
    # k3 estimator of KL(new || old) (reference `ppo_models.py:165-169`)
    approx_kl = jnp.sum((ratio - 1.0) - log_ratio) / n

    pg_loss1 = -advantages * ratio
    pg_loss2 = -advantages * jnp.clip(ratio, 1.0 - cliprange, 1.0 + cliprange)
    pg_loss = jnp.sum(jnp.maximum(pg_loss1, pg_loss2) * mask) / n
    pg_clipfrac = jnp.sum((pg_loss2 > pg_loss1) * mask) / n

    loss = pg_loss + vf_coef * vf_loss
    mean_entropy = jnp.zeros(())
    if entropy is not None:
        # also computed for the health stats at ent_coef=0; only the
        # bonus term below touches the loss
        mean_entropy = jnp.sum(entropy * mask) / n
    if ent_coef and entropy is not None:
        loss = loss - ent_coef * mean_entropy

    stats = {
        "losses/total_loss": loss,
        "losses/policy_loss": pg_loss,
        "losses/value_loss": vf_loss,
        "losses/entropy": mean_entropy,
        "policy/approx_kl": approx_kl,
        "policy/clipfrac": pg_clipfrac,
        "values/clipfrac": vf_clipfrac,
        "policy/ratio_mean": jnp.sum(ratio * mask) / n,
        "values/value_mean": masked_mean(values, mask),
        "returns/mean": masked_mean(returns, mask),
        "advantages/mean": masked_mean(advantages, mask),
    }
    if health:
        maskb = mask > 0
        if entropy is not None:
            stats["health/entropy"] = mean_entropy
        # masked extremes via finite fills (never ±inf: the fetched row
        # feeds EWMA state and the nan-precursor rule); >= 1 real token
        # per row is guaranteed by the response-budget check
        stats["health/log_ratio_max"] = jnp.max(
            jnp.where(maskb, log_ratio, -1e30)
        )
        stats["health/log_ratio_min"] = jnp.min(
            jnp.where(maskb, log_ratio, 1e30)
        )
        if health_ev:
            ret_mean = jnp.sum(returns * mask) / n
            err = returns - values
            err_mean = jnp.sum(err * mask) / n
            var_ret = jnp.sum(((returns - ret_mean) ** 2) * mask) / n
            var_err = jnp.sum(((err - err_mean) ** 2) * mask) / n
            stats["health/value_explained_var"] = 1.0 - var_err / jnp.maximum(
                var_ret, 1e-8
            )
    return loss, stats


def reward_health_stats(
    rewards: jax.Array,  # [B, R] per-token shaped rewards
    mask: jax.Array,  # [B, R]
) -> Dict[str, jax.Array]:
    """Per-sequence shaped-return distribution for the health stats
    pytree: mean/std plus q10/q50/q90 quantiles over the batch's
    KL-shaped returns. Device-side, riding the step's existing stats
    transfer; a collapsed ``reward_std`` is the reward-saturation
    detector's series. (For GRPO the rewards slot already holds
    group-whitened advantages — the quantiles then describe the
    advantage distribution, which is what its updates actually see.)"""
    seq = jnp.sum(rewards * mask.astype(rewards.dtype), axis=1)
    q = jnp.quantile(seq, jnp.asarray([0.1, 0.5, 0.9], seq.dtype))
    return {
        "health/reward_mean": jnp.mean(seq),
        "health/reward_std": jnp.std(seq),
        "health/reward_q10": q[0],
        "health/reward_q50": q[1],
        "health/reward_q90": q[2],
    }


# --- KL controllers (pure-state versions of `ppo_models.py:26-58`) ---


def adaptive_kl_update(
    kl_coef, current_kl, n_steps: int, target: float, horizon: int
):
    """Ziegler et al. proportional controller (`ppo_models.py:37-44`).

    Works on tracers (inside jit) and plain floats (the host training loop
    calls it once per minibatch — python math there, no device dispatch)."""
    if isinstance(kl_coef, jax.Array) or isinstance(current_kl, jax.Array):
        err = jnp.clip(current_kl / target - 1.0, -0.2, 0.2)
    else:
        err = min(max(current_kl / target - 1.0, -0.2), 0.2)
    return kl_coef * (1.0 + err * n_steps / horizon)


def kl_controller_update(
    config: PPOConfig, kl_coef, current_kl, n_steps: int
):
    """Dispatch adaptive vs fixed by ``config.target`` (None -> fixed,
    mirroring `accelerate_ppo_model.py:43-48`)."""
    if config.target is None:
        return kl_coef
    return adaptive_kl_update(kl_coef, current_kl, n_steps, config.target, config.horizon)
