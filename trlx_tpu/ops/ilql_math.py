"""ILQL math: config and loss (TD Q-learning + expectile V + CQL + AWAC).

Re-design of the reference ``ILQLConfig.loss``
(``trlx/model/nn/ilql_models.py:37-116``) as a pure jitted function. The
math is replicated exactly (SURVEY §8 flags the `Vnext * dones[:,1:]`
masking and CE-weighting subtleties): twin Q heads with min over *target*
networks, expectile value regression at parameter tau, a conservative
(CQL) cross-entropy term on Q logits, and an AWAC/behavior-cloning CE term
on the LM logits. The only structural change: padded actions are excluded
via an explicit ``actions_mask`` (the reference pads gather indices by
repeating the final index, silently double-counting the last action).

Target-Q Polyak sync (`ilql_models.py:161-181`) is :func:`polyak_update`
— an elementwise jitted tree op on (possibly sharded) params; no ZeRO
gather needed under GSPMD.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp

from trlx_tpu.data.ilql_types import ILQLBatch
from trlx_tpu.data.method_configs import MethodConfig, register_method

# Evaluation-decode defaults when a config omits gen_kwargs (reference
# hardcodes these in `accelerate_ilql_model.py:87-93`).
DEFAULT_ILQL_GEN_KWARGS: Dict[str, Any] = {
    "max_new_tokens": 48,
    "do_sample": True,
    "top_k": 20,
}


@register_method
@dataclass
class ILQLConfig(MethodConfig):
    """ILQL hyperparameters (reference `ilql_models.py:39-47`)."""

    name: str = "ILQLConfig"
    tau: float = 0.7
    gamma: float = 0.99
    cql_scale: float = 0.1
    awac_scale: float = 1.0
    alpha: float = 0.005
    steps_for_target_q_sync: int = 5
    betas: Tuple[float, ...] = (4.0,)
    two_qs: bool = True
    # generation params for evaluation decode (reference builds these in
    # `accelerate_ilql_model.py:87-93`). Defaults are declared here — not
    # hardcoded in the trainer — so a config diff shows the effective
    # sampling behavior; user-provided keys override individually.
    gen_kwargs: Dict[str, Any] = field(
        default_factory=lambda: dict(DEFAULT_ILQL_GEN_KWARGS)
    )

    @classmethod
    def from_dict(cls, config: Dict[str, Any]):
        if "betas" in config:
            config = dict(config, betas=tuple(config["betas"]))
        if "gen_kwargs" in config:
            # a bare `gen_kwargs:` YAML line parses as None
            config = dict(
                config,
                gen_kwargs={
                    **DEFAULT_ILQL_GEN_KWARGS,
                    **(config["gen_kwargs"] or {}),
                },
            )
        return super().from_dict(config)


def batch_gather(x: jax.Array, idx: jax.Array) -> jax.Array:
    """Gather along axis 1 with batched indices: x[b, idx[b, i], ...]."""
    return jnp.take_along_axis(
        x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)), axis=1
    )


def ilql_loss(
    logits: jax.Array,  # [B, T, V] LM logits
    qs: Tuple[jax.Array, ...],  # tuple of [B, A, V] Q-values at action states
    target_qs: Tuple[jax.Array, ...],  # same, from target heads
    vs: jax.Array,  # [B, S] state values
    batch: ILQLBatch,
    config: ILQLConfig,
    health: bool = False,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Reference `ilql_models.py:52-116`, masked for static-shape padding.

    ``health`` (``train.health.enabled``) fuses the Q-learning
    training-dynamics scalars into the stats dict — policy entropy over
    real tokens (entropy-collapse series), the masked Q extreme and TD
    error (value blow-up precursors). Extra outputs only: the loss
    arithmetic is untouched, so enabling health is bitwise-inert and
    the scalars ride the chunk's existing single stats transfer."""
    B, T, V = logits.shape
    A = batch.actions_ixs.shape[1]

    # the action token taken from state s_t is input_ids[:, 1:][actions_ixs]
    shifted = batch.input_ids[:, 1:]
    actions = jnp.take_along_axis(shifted, batch.actions_ixs, axis=1)  # [B, A]

    terminal_mask = (
        batch.dones[:, :-1].astype(jnp.float32) * batch.actions_mask.astype(jnp.float32)
    )  # [B, A]
    n_nonterminal = jnp.maximum(jnp.sum(terminal_mask), 1.0)

    Q = tuple(
        jnp.take_along_axis(q, actions[..., None], axis=-1)[..., 0] for q in qs
    )  # [B, A] each
    targetQ_each = tuple(
        jax.lax.stop_gradient(
            jnp.take_along_axis(tq, actions[..., None], axis=-1)[..., 0]
        )
        for tq in target_qs
    )
    targetQ = targetQ_each[0]
    for tq in targetQ_each[1:]:
        targetQ = jnp.minimum(targetQ, tq)

    V_cur = vs[:, :-1]  # [B, A] value of state s_t
    V_next = vs[:, 1:] * batch.dones[:, 1:].astype(vs.dtype)  # zero at terminals
    Q_target = batch.rewards + config.gamma * jax.lax.stop_gradient(V_next)

    loss_q = sum(
        jnp.sum(((Qi - Q_target) ** 2) * terminal_mask) / n_nonterminal for Qi in Q
    )

    diff = targetQ - V_cur
    loss_v = (
        jnp.sum(
            (
                (diff >= 0).astype(jnp.float32) * config.tau * diff**2
                + (diff < 0).astype(jnp.float32) * (1 - config.tau) * diff**2
            )
            * terminal_mask
        )
        / n_nonterminal
    )

    # CQL: push down Q mass off the dataset actions (CE of Q-logits vs actions)
    def ce(logits_, labels_):
        logp = jax.nn.log_softmax(logits_, axis=-1)
        return -jnp.take_along_axis(logp, labels_[..., None], axis=-1)[..., 0]

    loss_cql = sum(
        jnp.sum(ce(q, actions) * terminal_mask) / n_nonterminal for q in qs
    )

    # AWAC / behavior cloning on LM logits over all real tokens
    attn = batch.attention_mask[:, 1:].astype(jnp.float32)
    awac_ce = ce(logits[:, :-1], batch.input_ids[:, 1:])
    loss_awac = jnp.sum(awac_ce * attn) / jnp.maximum(jnp.sum(attn), 1.0)

    loss = loss_q + loss_v + config.cql_scale * loss_cql + config.awac_scale * loss_awac

    stats = {
        "losses/total_loss": loss,
        "losses/loss_q": loss_q,
        "losses/loss_v": loss_v,
        "losses/loss_cql": loss_cql,
        "losses/loss_awac": loss_awac,
        "values/q_mean": jnp.sum(Q[0] * terminal_mask) / n_nonterminal,
        "values/v_mean": jnp.sum(V_cur * terminal_mask) / n_nonterminal,
    }
    if health:
        # policy entropy over real next-token positions (the shared
        # helper, on the LM logits the AWAC term already computes CE
        # from)
        from trlx_tpu.ops.ppo_math import policy_entropy

        ent = policy_entropy(logits[:, :-1])
        n_attn = jnp.maximum(jnp.sum(attn), 1.0)
        stats["health/entropy"] = jnp.sum(ent * attn) / n_attn
        # finite fill (never ±inf: the fetched value feeds EWMA state);
        # >= 1 real action per batch is guaranteed by construction
        stats["health/q_max"] = jnp.max(
            jnp.where(terminal_mask > 0, Q[0], -1e30)
        )
        stats["health/td_error_mean"] = (
            jnp.sum(jnp.abs(Q[0] - Q_target) * terminal_mask) / n_nonterminal
        )
    return loss, stats


def polyak_update(params, target_params, alpha: float):
    """target <- alpha * params + (1-alpha) * target (`ilql_models.py:161-168`),
    as a jitted tree op; works unchanged on sharded params."""
    return jax.tree_util.tree_map(
        lambda p, t: alpha * p + (1.0 - alpha) * t, params, target_params
    )
