"""Attention core and mask/bias builders.

All attention in the framework funnels through :func:`dot_product_attention`
(SURVEY §2.9: "Pallas kernels only where XLA fusion is insufficient"): on
TPU, long sequences route to the Pallas flash kernel
(:mod:`trlx_tpu.ops.flash_attention` — blocked online softmax, causal tile
skipping, custom-VJP backward); short sequences and CPU stay on the XLA
einsum path, which XLA fuses well below the flash crossover point. Masks
are additive float biases built once per program by the helpers below —
models never branch on Python-level conditions inside jit.

Softmax runs in float32 regardless of compute dtype (bf16 logits lose
~3 decimal digits; the MXU matmuls stay bf16 where the FLOPs are).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e9  # large-negative mask value; avoids -inf NaN propagation in softmax

# Flash kernel dispatch: measured crossover on v5e — XLA wins below ~1k
# context (its fused softmax has no kernel-launch/transpose overhead), the
# pallas kernel wins above (2.4x fwd / 4x bwd at 4k). Settable for tests.
FLASH_MIN_SEQ = 1024


def causal_bias(q_len: int, kv_len: int, offset: int = 0, dtype=jnp.float32) -> jax.Array:
    """[1, 1, Q, K] additive bias: query i attends kv j iff j <= i + offset.

    ``offset`` is the absolute position of the first query token — used when
    decoding with a KV cache where queries sit at positions
    ``offset..offset+Q-1`` of a ``kv_len``-capacity buffer. A [B]-vector
    ``offset`` (rows decoding at different depths — the continuous-batching
    engine) yields a [B, 1, Q, K] bias instead.
    """
    off = jnp.asarray(offset)
    k_pos = jnp.arange(kv_len)[None, :]
    if off.ndim:
        q_pos = (
            jnp.arange(q_len)[None, :, None]
            + off.astype(jnp.int32)[:, None, None]
        )  # [B, Q, 1]
        mask = k_pos[None, :, :] <= q_pos
        return jnp.where(mask, 0.0, NEG_INF).astype(dtype)[:, None, :, :]
    q_pos = jnp.arange(q_len)[:, None] + off
    mask = k_pos <= q_pos
    return jnp.where(mask, 0.0, NEG_INF).astype(dtype)[None, None, :, :]


def padding_bias(attention_mask: jax.Array, dtype=jnp.float32) -> jax.Array:
    """[B, 1, 1, K] additive bias from a 0/1 key-validity mask."""
    return jnp.where(attention_mask[:, None, None, :] > 0, 0.0, NEG_INF).astype(dtype)


def combine_biases(*biases: Optional[jax.Array]) -> Optional[jax.Array]:
    out = None
    for b in biases:
        if b is None:
            continue
        out = b if out is None else out + b
    return out


def causal_dispatch(
    q_len: int,
    cache,
    cache_index,
    attention_mask: Optional[jax.Array],
):
    """Shared causal-mask dispatch for the causal-LM families.

    Without a KV cache the causal structure is returned as a flag (so the
    flash kernel can skip future key tiles in-kernel); with one, the
    offset-shifted causal mask must be an explicit bias tensor (the offset
    is traced). Returns ``(bias, causal_flag)`` for
    :func:`dot_product_attention`.

    With a cache, the MASK WIDTH is the attention view width: a caller
    that passes a validity mask narrower than the cache capacity attends
    over only the leading ``mask.shape[-1]`` logical positions
    (``models/gpt2.py::write_cache`` narrows the returned K/V view to the
    bias width). Every full-capacity caller is unchanged — the narrowed
    view is the chunked-prefill contract (docs/inference.md): prompt
    chunks never attend the decode region, whose masked columns carry
    exactly-zero softmax weight anyway.
    """
    pad = padding_bias(attention_mask) if attention_mask is not None else None
    if cache is None:
        return pad, True
    kv_len = (
        attention_mask.shape[-1]
        if attention_mask is not None
        else cache[0]["k"].shape[1]
    )
    offset = jnp.asarray(cache_index)
    if offset.ndim == 2:
        # [B, Q] per-column cache targets (the speculative verify step):
        # the query window is consecutive from each row's first target,
        # so the causal offset is the base column — rows whose window is
        # parked at the OOB sentinel get an over-wide bias exactly like
        # the one-token decode's idle-row ``capacity`` offset (their
        # outputs are discarded; the padding bias still applies)
        offset = offset[:, 0]
    return combine_biases(causal_bias(q_len, kv_len, offset=offset), pad), False


def dot_product_attention(
    q: jax.Array,  # [B, Q, H, D]
    k: jax.Array,  # [B, K, H, D]
    v: jax.Array,  # [B, K, H, D]
    bias: Optional[jax.Array] = None,  # [B or 1, 1 or H, Q, K] additive
    *,
    causal: bool = False,
    learned_bias: bool = False,
) -> jax.Array:
    """Multi-head attention; returns [B, Q, H, D].

    ``causal=True`` applies offset-0 causal masking (training / prefill) —
    prefer it over baking a causal term into ``bias``: the flash kernel then
    skips future key tiles instead of reading a [Q, K] mask from HBM.
    ``learned_bias=True`` declares that gradient must flow to ``bias`` (T5
    relative position bias) and pins the XLA path, since the flash kernel's
    VJP treats bias as constant.

    XLA path: logits and softmax in float32, output cast back to q.dtype;
    XLA fuses the scale/bias/softmax chain between the two MXU matmuls.
    """
    Q, K = q.shape[1], k.shape[1]
    if (
        not learned_bias
        and min(Q, K) >= FLASH_MIN_SEQ
        and jax.default_backend() == "tpu"
    ):
        from trlx_tpu.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, bias, causal=causal)

    if causal:
        bias = combine_biases(causal_bias(Q, K), bias)
    depth = q.shape[-1]
    scale = jax.lax.rsqrt(jnp.float32(depth))
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    weights = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", weights.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)
