"""Attention core and mask/bias builders.

All attention in the framework funnels through :func:`dot_product_attention`
so a Pallas flash/decode kernel can replace the XLA einsum path in one place
(SURVEY §2.9: "Pallas kernels only where XLA fusion is insufficient").
Masks are additive float biases built once per program by the helpers below —
models never branch on Python-level conditions inside jit.

Softmax runs in float32 regardless of compute dtype (bf16 logits lose
~3 decimal digits; the MXU matmuls stay bf16 where the FLOPs are).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e9  # large-negative mask value; avoids -inf NaN propagation in softmax


def causal_bias(q_len: int, kv_len: int, offset: int = 0, dtype=jnp.float32) -> jax.Array:
    """[1, 1, Q, K] additive bias: query i attends kv j iff j <= i + offset.

    ``offset`` is the absolute position of the first query token — used when
    decoding with a KV cache where queries sit at positions
    ``offset..offset+Q-1`` of a ``kv_len``-capacity buffer.
    """
    q_pos = jnp.arange(q_len)[:, None] + offset
    k_pos = jnp.arange(kv_len)[None, :]
    mask = k_pos <= q_pos
    return jnp.where(mask, 0.0, NEG_INF).astype(dtype)[None, None, :, :]


def padding_bias(attention_mask: jax.Array, dtype=jnp.float32) -> jax.Array:
    """[B, 1, 1, K] additive bias from a 0/1 key-validity mask."""
    return jnp.where(attention_mask[:, None, None, :] > 0, 0.0, NEG_INF).astype(dtype)


def combine_biases(*biases: Optional[jax.Array]) -> Optional[jax.Array]:
    out = None
    for b in biases:
        if b is None:
            continue
        out = b if out is None else out + b
    return out


def dot_product_attention(
    q: jax.Array,  # [B, Q, H, D]
    k: jax.Array,  # [B, K, H, D]
    v: jax.Array,  # [B, K, H, D]
    bias: Optional[jax.Array] = None,  # [B or 1, 1 or H, Q, K] additive
) -> jax.Array:
    """Standard multi-head attention; returns [B, Q, H, D].

    Logits and softmax in float32; output cast back to q.dtype. XLA fuses
    the scale/bias/softmax chain between the two MXU matmuls.
    """
    depth = q.shape[-1]
    scale = jax.lax.rsqrt(jnp.float32(depth))
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    weights = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", weights.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)
