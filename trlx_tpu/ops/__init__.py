"""Device-side ops: attention core, sampling, PPO/ILQL math.

This is where the reference's RL math (`trlx/model/nn/*.py`) and generation
loops live in TPU form — pure jit-compiled functions over arrays, no
framework objects on device.
"""

from trlx_tpu.ops.attention import dot_product_attention
from trlx_tpu.ops.ppo_math import (
    PPOConfig,
    get_advantages_and_returns,
    kl_controller_update,
    ppo_loss,
)
from trlx_tpu.ops.sampling import GenerationConfig, SampleOutput, make_sampler

__all__ = [
    "dot_product_attention",
    "PPOConfig",
    "get_advantages_and_returns",
    "ppo_loss",
    "kl_controller_update",
    "GenerationConfig",
    "SampleOutput",
    "make_sampler",
]
