"""Sweep -> wandb reporting (reference ``trlx/ray_tune/wandb.py``).

``log_trials`` replays trial records into wandb runs (`wandb.py:47-82`);
``create_report`` builds a programmatic W&B report — parallel coordinates,
parameter importance, per-metric scatter (`wandb.py:85-214`). Both are
no-ops when wandb isn't installed or is disabled.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List


def _wandb():
    if os.environ.get("WANDB_DISABLED", "") in ("1", "true"):
        return None
    try:
        import wandb

        return wandb
    except ImportError:
        return None


def log_trials(trials: List[Dict[str, Any]], tune_config: Dict[str, Any],
               project: str = "trlx_tpu-sweeps") -> None:
    """One wandb run per trial, config = params, summary = final result.
    Trials carrying a ``history`` list (per-step stat dicts, the analogue of
    the reference's per-trial ``result.json`` rows, `ray_tune/wandb.py:47-82`)
    are replayed step by step so line plots have real curves."""
    wandb = _wandb()
    if wandb is None:
        return
    for i, trial in enumerate(trials):
        run = wandb.init(
            project=project,
            name=f"trial-{i}",
            config=trial["params"],
            reinit=True,
            mode=os.environ.get("WANDB_MODE", "offline"),
        )
        for row in trial.get("history", ()):
            run.log(row)
        run.log(trial["result"])
        run.finish()


def create_report(project: str, param_space: Dict[str, Any],
                  metric: str, trials: List[Dict[str, Any]],
                  best: Dict[str, Any]) -> None:
    """Programmatic W&B report (requires wandb + the report API)."""
    wandb = _wandb()
    if wandb is None:
        return
    try:
        import wandb.apis.reports as wb
    except Exception:
        return
    report = wb.Report(
        project=project,
        title=f"Sweep report: {metric}",
        description=f"best params: {best.get('params')}",
    )
    pg = wb.PanelGrid(
        runsets=[wb.Runset(project=project)],
        panels=[
            wb.ParallelCoordinatesPlot(
                columns=[wb.PCColumn(f"c::{p}") for p in param_space]
                + [wb.PCColumn(metric)],
            ),
            wb.ParameterImportancePlot(with_respect_to=metric),
            wb.ScatterPlot(x="created", y=metric),
        ],
    )
    # per-metric line plots + best-config block (reference
    # `ray_tune/wandb.py:85-214`). Line plots only make sense when trials
    # replayed per-step history — single-point runs render nothing a
    # scatter doesn't.
    blocks = [pg]
    if any(t.get("history") for t in trials):
        metric_names = sorted(
            {k for t in trials for row in t.get("history", ()) for k in row}
            - {metric}
        )
        line_panels = [
            wb.LinePlot(x="_step", y=[m], smoothing_factor=0.5)
            for m in [metric, *metric_names][:12]
        ]
        blocks.append(
            wb.PanelGrid(runsets=[wb.Runset(project=project)], panels=line_panels)
        )
    blocks.append(wb.MarkdownBlock(text=f"**Best config**\n```\n{best}\n```"))
    report.blocks = blocks
    report.save()
