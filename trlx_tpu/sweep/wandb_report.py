"""Sweep -> wandb reporting (reference ``trlx/ray_tune/wandb.py``).

``log_trials`` replays trial records into wandb runs (`wandb.py:47-82`);
``create_report`` builds a programmatic W&B report — parallel coordinates,
parameter importance, per-metric scatter (`wandb.py:85-214`). Both are
no-ops when wandb isn't installed or is disabled.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List


def _wandb():
    if os.environ.get("WANDB_DISABLED", "") in ("1", "true"):
        return None
    try:
        import wandb

        return wandb
    except ImportError:
        return None


def log_trials(trials: List[Dict[str, Any]], tune_config: Dict[str, Any],
               project: str = "trlx_tpu-sweeps") -> None:
    """One wandb run per trial, config = params, summary = final result."""
    wandb = _wandb()
    if wandb is None:
        return
    for i, trial in enumerate(trials):
        run = wandb.init(
            project=project,
            name=f"trial-{i}",
            config=trial["params"],
            reinit=True,
            mode=os.environ.get("WANDB_MODE", "offline"),
        )
        run.log(trial["result"])
        run.finish()


def create_report(project: str, param_space: Dict[str, Any],
                  metric: str, trials: List[Dict[str, Any]],
                  best: Dict[str, Any]) -> None:
    """Programmatic W&B report (requires wandb + the report API)."""
    wandb = _wandb()
    if wandb is None:
        return
    try:
        import wandb.apis.reports as wb
    except Exception:
        return
    report = wb.Report(
        project=project,
        title=f"Sweep report: {metric}",
        description=f"best params: {best.get('params')}",
    )
    pg = wb.PanelGrid(
        runsets=[wb.Runset(project=project)],
        panels=[
            wb.ParallelCoordinatesPlot(
                columns=[wb.PCColumn(f"c::{p}") for p in param_space]
                + [wb.PCColumn(metric)],
            ),
            wb.ParameterImportancePlot(with_respect_to=metric),
            wb.ScatterPlot(x="created", y=metric),
        ],
    )
    report.blocks = [pg]
    report.save()
