"""Hyperparameter sweep subsystem (reference ``trlx/sweep.py`` +
``trlx/ray_tune/``).

Same YAML schema as the reference (`ray_tune/__init__.py:35-82`): a
``tune_config`` section (metric/mode/search_alg/scheduler/num_samples) plus
per-hyperparameter ``{strategy, values}`` entries covering the reference's
13 strategies. Two executors:

- **Ray Tune** when ray is importable — ``tune.Tuner`` with resources, as
  the reference (`sweep.py:24-33`);
- **built-in sequential executor** otherwise: random/grid search running
  trials in-process (each trial = one ``main(overrides) -> final stats``
  call), tracking the best config. The reference hard-requires ray; here
  sweeps degrade gracefully on a bare TPU host.
"""

from __future__ import annotations

import itertools
import math
import random
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

STRATEGIES = (
    "uniform",
    "quniform",
    "loguniform",
    "qloguniform",
    "randn",
    "qrandn",
    "randint",
    "qrandint",
    "lograndint",
    "qlograndint",
    "choice",
    "grid_search",
    "grid",
)


@dataclass
class ParamStrategy:
    """One hyperparameter's search strategy (`ray_tune/__init__.py:35-82`)."""

    name: str
    strategy: str
    values: List[Any]

    def __post_init__(self):
        if self.strategy not in STRATEGIES:
            raise ValueError(
                f"Unknown strategy {self.strategy!r} for {self.name!r}; "
                f"valid: {STRATEGIES}"
            )

    @property
    def is_grid(self) -> bool:
        return self.strategy in ("grid_search", "grid", "choice") and self.strategy != "choice"

    def grid_values(self) -> List[Any]:
        return list(self.values)

    def sample(self, rng: random.Random) -> Any:
        s, v = self.strategy, self.values
        if s == "uniform":
            return rng.uniform(v[0], v[1])
        if s == "quniform":
            return round(rng.uniform(v[0], v[1]) / v[2]) * v[2]
        if s == "loguniform":
            return math.exp(rng.uniform(math.log(v[0]), math.log(v[1])))
        if s == "qloguniform":
            x = math.exp(rng.uniform(math.log(v[0]), math.log(v[1])))
            return round(x / v[2]) * v[2]
        if s == "randn":
            return rng.gauss(v[0], v[1])
        if s == "qrandn":
            return round(rng.gauss(v[0], v[1]) / v[2]) * v[2]
        if s == "randint":
            return rng.randrange(int(v[0]), int(v[1]))
        if s == "qrandint":
            x = rng.randrange(int(v[0]), int(v[1]))
            q = int(v[2])
            return (x // q) * q
        if s == "lograndint":
            return int(math.exp(rng.uniform(math.log(v[0]), math.log(v[1]))))
        if s == "qlograndint":
            x = int(math.exp(rng.uniform(math.log(v[0]), math.log(v[1]))))
            q = int(v[2])
            return (x // q) * q
        if s in ("choice", "grid_search", "grid"):
            return rng.choice(list(v))
        raise AssertionError(s)

    def to_ray(self):
        from ray import tune

        s, v = self.strategy, self.values
        mapping: Dict[str, Callable] = {
            "uniform": lambda: tune.uniform(*v),
            "quniform": lambda: tune.quniform(*v),
            "loguniform": lambda: tune.loguniform(*v),
            "qloguniform": lambda: tune.qloguniform(*v),
            "randn": lambda: tune.randn(*v),
            "qrandn": lambda: tune.qrandn(*v),
            "randint": lambda: tune.randint(*v),
            "qrandint": lambda: tune.qrandint(*v),
            "lograndint": lambda: tune.lograndint(*v),
            "qlograndint": lambda: tune.qlograndint(*v),
            "choice": lambda: tune.choice(v),
            "grid_search": lambda: tune.grid_search(list(v)),
            "grid": lambda: tune.grid_search(list(v)),
        }
        return mapping[s]()


def get_param_space(config: Dict[str, Any]) -> Dict[str, ParamStrategy]:
    """YAML dict (minus ``tune_config``) -> param strategies
    (`ray_tune/__init__.py:4-87`)."""
    space = {}
    for name, spec in config.items():
        if name == "tune_config":
            continue
        space[name] = ParamStrategy(name, spec["strategy"], spec["values"])
    return space


def get_tune_config(config: Dict[str, Any]) -> Dict[str, Any]:
    """Normalize the ``tune_config`` section (`ray_tune/__init__.py:152-165`)."""
    tune_config = dict(config.get("tune_config", {}))
    tune_config.setdefault("mode", "max")
    tune_config.setdefault("metric", "reward/mean")
    tune_config.setdefault("num_samples", 10)
    return tune_config


def run_local_sweep(
    trainable: Callable[[Dict[str, Any]], Dict[str, Any]],
    param_space: Dict[str, ParamStrategy],
    tune_config: Dict[str, Any],
    seed: int = 0,
    log_fn: Optional[Callable[[str], None]] = print,
) -> Tuple[Dict[str, Any], List[Dict[str, Any]]]:
    """Built-in executor: grid over grid-strategies x random samples of the
    rest. Returns (best trial record, all trial records)."""
    rng = random.Random(seed)
    metric = tune_config["metric"]
    mode = tune_config["mode"]
    num_samples = int(tune_config["num_samples"])

    grid_params = {k: v for k, v in param_space.items() if v.is_grid}
    rand_params = {k: v for k, v in param_space.items() if not v.is_grid}

    if grid_params:
        grid_combos = [
            dict(zip(grid_params, combo))
            for combo in itertools.product(
                *(p.grid_values() for p in grid_params.values())
            )
        ]
    else:
        grid_combos = [{}]

    trials: List[Dict[str, Any]] = []
    for combo in grid_combos:
        for _ in range(num_samples if rand_params else 1):
            params = dict(combo)
            params.update({k: p.sample(rng) for k, p in rand_params.items()})
            result = trainable(dict(params)) or {}
            # a trainable may return per-step history under "history"
            # (replayed into wandb line plots, `wandb_report.log_trials`)
            history = result.pop("history", None) if isinstance(result, dict) else None
            record = {"params": params, "result": result}
            if history:
                record["history"] = history
            trials.append(record)
            if log_fn:
                log_fn(f"[sweep] trial {len(trials)}: {params} -> "
                       f"{metric}={result.get(metric)}")

    def key(t):
        v = t["result"].get(metric)
        if v is None:
            return -float("inf") if mode == "max" else float("inf")
        return v

    best = max(trials, key=key) if mode == "max" else min(trials, key=key)
    if log_fn:
        log_fn(f"[sweep] best: {best['params']} -> {best['result'].get(metric)}")
    return best, trials


def get_search_alg(tune_config: Dict[str, Any]):
    """Search algorithm by name (`ray_tune/__init__.py:90-124`):
    ``bayesopt`` / ``bohb`` / ``random`` (None). Raises if the named
    algorithm's optional dependency is missing, as the reference does."""
    name = (tune_config.get("search_alg") or "random").lower()
    if name in ("random", "", "none"):
        return None
    mode, metric = tune_config["mode"], tune_config["metric"]
    if name == "bayesopt":
        from ray.tune.search.bayesopt import BayesOptSearch

        return BayesOptSearch(metric=metric, mode=mode)
    if name == "bohb":
        from ray.tune.search.bohb import TuneBOHB

        return TuneBOHB(metric=metric, mode=mode)
    raise ValueError(f"Unknown search_alg: {name!r} (random | bayesopt | bohb)")


def get_scheduler(tune_config: Dict[str, Any]):
    """Trial scheduler by name (`ray_tune/__init__.py:127-149`):
    ``hyperband`` (ASHA early stopping), ``bohb`` (HyperBandForBOHB — the
    scheduler TuneBOHB requires), or ``fifo`` (None). ``search_alg: bohb``
    implies the bohb scheduler when none is named."""
    name = (tune_config.get("scheduler") or "").lower()
    if not name or name in ("fifo", "none"):
        # TuneBOHB is only valid with HyperBandForBOHB — pair automatically
        if (tune_config.get("search_alg") or "").lower() == "bohb":
            name = "bohb"
        else:
            return None
    if name == "hyperband":
        from ray.tune.schedulers import AsyncHyperBandScheduler

        return AsyncHyperBandScheduler()
    if name == "bohb":
        from ray.tune.schedulers.hb_bohb import HyperBandForBOHB

        return HyperBandForBOHB()
    raise ValueError(f"Unknown scheduler: {name!r} (fifo | hyperband | bohb)")


def run_ray_sweep(trainable, param_space, tune_config, num_cpus=4, num_gpus=0,
                  server_address=None):
    """Ray Tune executor (`sweep.py:21-49`); requires ray installed.
    ``server_address`` connects to a remote cluster via the Ray client
    (reference `sweep.py:87-90`: ``ray.init("ray://...")``)."""
    import ray
    from ray import tune

    if server_address:
        # client mode rejects non-default kwargs like ignore_reinit_error
        # (the reference likewise calls ray.init("ray://...") bare)
        ray.init(address=f"ray://{server_address}")
    else:
        ray.init(ignore_reinit_error=True)
    search_alg = get_search_alg(tune_config)
    # metric/mode go to exactly one place: a pre-configured searcher already
    # carries them, and Ray rejects receiving them twice
    metric_mode = (
        {} if search_alg is not None
        else {"metric": tune_config["metric"], "mode": tune_config["mode"]}
    )
    tuner = tune.Tuner(
        tune.with_resources(trainable, resources={"cpu": num_cpus, "gpu": num_gpus}),
        param_space={k: p.to_ray() for k, p in param_space.items()},
        tune_config=tune.TuneConfig(
            num_samples=tune_config["num_samples"],
            search_alg=search_alg,
            scheduler=get_scheduler(tune_config),
            **metric_mode,
        ),
    )
    results = tuner.fit()
    # explicit metric/mode: with a pre-configured searcher TuneConfig
    # carries neither, and a bare get_best_result() would raise
    best = results.get_best_result(
        metric=tune_config["metric"], mode=tune_config["mode"]
    )
    return best, results
