"""Sweep CLI: ``python -m trlx_tpu.sweep --config sweep.yml script.py``.

Reference ``trlx/sweep.py:52-113``: imports the user script's ``main`` as
the trainable (called with a dict of hyperparameter overrides; it applies
them via ``TRLConfig.update`` and returns final stats), builds the param
space from the sweep YAML, and runs trials — on Ray when available, else
the built-in sequential executor.
"""

from __future__ import annotations

import argparse
import importlib.util
import json
import os
import sys

import yaml

from trlx_tpu.sweep import (
    get_param_space,
    get_tune_config,
    run_local_sweep,
    run_ray_sweep,
)


def import_main(script_path: str):
    """Import the user script's ``main`` (`sweep.py:106-110`)."""
    spec = importlib.util.spec_from_file_location("sweep_script", script_path)
    module = importlib.util.module_from_spec(spec)
    sys.path.insert(0, os.path.dirname(os.path.abspath(script_path)))
    spec.loader.exec_module(module)
    if not hasattr(module, "main"):
        raise ValueError(f"{script_path} must define main(overrides: dict)")
    return module.main


def cli(argv=None):
    parser = argparse.ArgumentParser(description="trlx_tpu hyperparameter sweep")
    parser.add_argument("script", help="training script defining main(overrides)")
    parser.add_argument("--config", required=True, help="sweep YAML")
    parser.add_argument("--num-cpus", type=int, default=4)
    parser.add_argument("--num-gpus", type=int, default=0)
    parser.add_argument(
        "--server-address",
        default=None,
        help="remote Ray cluster address (host:port), connected via ray://",
    )
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument(
        "--output", default="sweep_results.json", help="trial records output"
    )
    parser.add_argument(
        "--local",
        action="store_true",
        help="force the built-in executor even if ray is installed",
    )
    args = parser.parse_args(argv)

    with open(args.config) as f:
        sweep_config = yaml.safe_load(f)
    param_space = get_param_space(sweep_config)
    tune_config = get_tune_config(sweep_config)
    trainable = import_main(args.script)

    use_ray = not args.local
    if use_ray:
        try:
            import ray  # noqa: F401
        except ImportError:
            if args.server_address:
                raise SystemExit(
                    "--server-address requires ray to be installed "
                    "(pip install ray[tune])"
                )
            use_ray = False
    elif args.server_address:
        raise SystemExit("--server-address conflicts with --local")

    if use_ray:
        best, results = run_ray_sweep(
            trainable, param_space, tune_config, args.num_cpus, args.num_gpus,
            server_address=args.server_address,
        )
        print(f"best config: {best.config}")
    else:
        best, trials = run_local_sweep(
            trainable, param_space, tune_config, seed=args.seed
        )
        with open(args.output, "w") as f:
            json.dump({"best": best, "trials": trials}, f, indent=2, default=float)
        try:
            # reference tune_function does both: replay trials into wandb
            # runs, then assemble the programmatic report (`sweep.py:36-47`);
            # one resolved project for both, or the report's runsets would
            # query a project the runs were never logged to
            from trlx_tpu.sweep.wandb_report import create_report, log_trials

            project = os.environ.get("WANDB_PROJECT", "trlx_tpu-sweeps")
            log_trials(trials, tune_config, project=project)
            create_report(
                project,
                param_space,
                tune_config.get("metric", "reward/mean"),
                trials,
                best,
            )
        except Exception as e:
            # reporting is best-effort, but never silently: the sweep
            # result (best config + trials json) is already on disk
            print(f"wandb reporting failed: {type(e).__name__}: {e}",
                  file=sys.stderr)
    return best


if __name__ == "__main__":
    cli()
