"""Tune-ready standalone train functions (reference
``trlx/ray_tune/train_funcs.py:10-32``): each takes a flat hyperparameter
dict (one sweep trial), merges it into the base config, trains, and returns
the final stats dict for the sweep executor to rank.

Usable directly as the trainable for ``run_local_sweep`` / ``run_ray_sweep``
or via ``python -m trlx_tpu.sweep --config ... examples/ppo_sentiments.py``
(which wraps the example's ``main``).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional


def ppo_randomwalks_train(params: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """PPO on the synthetic randomwalks task — the fast sweep smoke target
    (the reference's CI-speed example, `examples/randomwalks/`)."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
    from examples.randomwalks import main

    return main(params)


def ppo_sentiments_train(params: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """PPO sentiments trainable (`ray_tune/train_funcs.py:10-32`) — requires
    local gpt2-imdb + sentiment checkpoint paths via env/config."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
    from examples.ppo_sentiments import main

    return main(params)
