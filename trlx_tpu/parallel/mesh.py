"""Device mesh construction and axis conventions.

The TPU-native replacement for the reference's distributed substrate
(HF Accelerate -> torch.distributed/NCCL/DeepSpeed; SURVEY §2.9). All
parallelism in this framework is expressed as sharding over one
``jax.sharding.Mesh`` with three named axes:

- ``dp``   — pure data parallel: params replicated, batch sharded
             (reference: Accelerate DDP, `accelerate_base_model.py:38`).
- ``fsdp`` — ZeRO-style fully-sharded data parallel: batch sharded *and*
             params/optimizer state sharded (reference: DeepSpeed ZeRO
             stages, `configs/deepspeed_configs/default_configs.yml`).
- ``tp``   — tensor parallel: hidden/head dimensions sharded (reference has
             only dormant scaffolding for this, `ppo_models.py:310-312`).

Gradient sync, global statistics, and param gathers all become XLA
collectives over ICI inserted automatically by GSPMD from these shardings —
there is no explicit NCCL-equivalent call-site in the framework.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_DP = "dp"
AXIS_FSDP = "fsdp"
AXIS_TP = "tp"
AXIS_SP = "sp"  # sequence/context parallel (ring attention, ops/ring_attention.py)
AXIS_PP = "pp"  # pipeline parallel (GPipe microbatching, parallel/pipeline.py)
AXIS_EP = "ep"  # expert parallel (switch MoE routing, parallel/moe.py)
# Batch axes: data is sharded over both dp and fsdp mesh axes.
BATCH_AXES = (AXIS_DP, AXIS_FSDP)


def make_mesh(
    mesh_config: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a ``Mesh`` from ``{"dp": -1, "fsdp": 1, "tp": 1}`` axis sizes.

    Exactly one axis may be -1, meaning "all remaining devices". Multi-host
    TPU slices work transparently: ``jax.devices()`` enumerates the global
    device set after ``jax.distributed.initialize``.
    """
    mesh_config = dict(mesh_config or {})
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)

    sizes = {
        AXIS_DP: mesh_config.get(AXIS_DP, -1),
        AXIS_FSDP: mesh_config.get(AXIS_FSDP, 1),
        AXIS_TP: mesh_config.get(AXIS_TP, 1),
        AXIS_SP: mesh_config.get(AXIS_SP, 1),
        AXIS_PP: mesh_config.get(AXIS_PP, 1),
        AXIS_EP: mesh_config.get(AXIS_EP, 1),
    }
    unknown = set(mesh_config) - set(sizes)
    if unknown:
        raise ValueError(f"Unknown mesh axes: {sorted(unknown)}")

    wildcard = [k for k, v in sizes.items() if v == -1]
    if len(wildcard) > 1:
        raise ValueError(f"At most one mesh axis may be -1, got {wildcard}")
    fixed = int(np.prod([v for v in sizes.values() if v != -1]))
    if wildcard:
        if n % fixed != 0:
            raise ValueError(
                f"{n} devices not divisible by fixed axes product {fixed}"
            )
        sizes[wildcard[0]] = n // fixed
    elif fixed != n:
        raise ValueError(f"Mesh {sizes} needs {fixed} devices, have {n}")

    shape = (
        sizes[AXIS_DP], sizes[AXIS_FSDP], sizes[AXIS_TP], sizes[AXIS_SP],
        sizes[AXIS_PP], sizes[AXIS_EP],
    )
    device_array = np.asarray(devices).reshape(shape)
    return Mesh(
        device_array, (AXIS_DP, AXIS_FSDP, AXIS_TP, AXIS_SP, AXIS_PP, AXIS_EP)
    )


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for [B, ...] data arrays: batch split over dp x fsdp."""
    return NamedSharding(mesh, P(BATCH_AXES))


def stacked_batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for [n_mb, B, ...] stacked-minibatch arrays: the scan axis is
    replicated, the batch axis splits over dp x fsdp (each scan slice then
    matches :func:`batch_sharding`)."""
    return NamedSharding(mesh, P(None, BATCH_AXES))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def local_batch_size(mesh: Mesh, global_batch_size: int) -> int:
    """Per-shard batch size; validates divisibility (reference computes
    global batch via WORLD_SIZE, `trlx.py:44`)."""
    n = mesh.shape[AXIS_DP] * mesh.shape[AXIS_FSDP]
    if global_batch_size % n != 0:
        raise ValueError(
            f"global batch {global_batch_size} not divisible by {n} data shards"
        )
    return global_batch_size // n
