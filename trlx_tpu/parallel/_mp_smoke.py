"""Multi-process smoke worker: one rank of a 2-process sharded PPO step.

The reference's launch story is multi-process by construction
(``accelerate launch``, `README.md:35-40`; startup barrier across ranks,
`accelerate_base_model.py:38-41`; WORLD_SIZE batch math, `trlx/trlx.py:44`).
This worker proves the TPU-native equivalent actually executes:
``parallel/distributed.py::initialize`` wires N CPU processes into one JAX
runtime (the same ``jax.distributed`` control plane a TPU pod uses), every
rank builds the SAME global mesh over all N×local devices, and one sharded
PPO train step runs SPMD across processes — the collectives GSPMD inserts
for the dp/fsdp/tp axes ride the cross-process transport.

Run as::

    python -m trlx_tpu.parallel._mp_smoke <coordinator> <num_procs> <rank>

with ``XLA_FLAGS=--xla_force_host_platform_device_count=K`` in the env
(each rank contributes K virtual CPU devices). Launched by
``tests/test_multiprocess.py`` and by the driver's ``dryrun_multichip``.
"""

from __future__ import annotations

import sys


def main(coordinator: str, num_processes: int, process_id: int) -> None:
    import jax

    # the env's sitecustomize may force-select a TPU platform via
    # jax.config.update at interpreter startup (outranking JAX_PLATFORMS);
    # switch back before the first backend touch — same recipe as
    # __graft_entry__._dryrun_multichip_body
    jax.config.update("jax_platforms", "cpu")

    from trlx_tpu.parallel.distributed import (
        barrier,
        broadcast_host_value,
        initialize,
        is_main_process,
    )

    initialize(coordinator, num_processes, process_id)
    assert jax.process_count() == num_processes, jax.process_count()
    assert jax.process_index() == process_id, jax.process_index()
    n_local = len(jax.local_devices())
    n_global = len(jax.devices())
    assert n_global == num_processes * n_local, (n_global, n_local)

    # startup barrier across ranks (reference `accelerate_base_model.py:40`)
    barrier("startup")

    # host-value broadcast: every rank must end up with rank 0's value
    value = broadcast_host_value(1234 if process_id == 0 else -1)
    assert int(value) == 1234, value

    import jax.numpy as jnp
    import numpy as np

    from trlx_tpu.data.configs import TRLConfig
    from trlx_tpu.data.ppo_types import PPORolloutBatch
    from trlx_tpu.parallel.mesh import batch_sharding
    from trlx_tpu.utils.loading import get_trainer

    # global mesh over every device of every process: dp=2 x fsdp=2 x tp=2
    # for 8 devices — dp/fsdp collectives cross the process boundary
    tp = 2 if n_global % 2 == 0 else 1
    fsdp = 2 if n_global % 4 == 0 else 1
    dp = n_global // (tp * fsdp)
    B, Q, R = max(dp * fsdp * 2, 8), 8, 6
    config = TRLConfig.from_dict(
        {
            "model": {
                "model_type": "gpt2",
                "model_arch": {
                    "vocab_size": 256,
                    "n_positions": 32,
                    "n_embd": 64,
                    "n_layer": 2,
                    "n_head": 4,
                },
            },
            "train": {
                "seq_length": Q,
                "batch_size": B,
                "mesh": {"dp": dp, "fsdp": fsdp, "tp": tp},
                "dtype": "float32",
            },
            "method": {
                "name": "PPOConfig",
                "num_rollouts": B,
                "chunk_size": B,
                "gen_kwargs": {
                    "max_new_tokens": R,
                    "do_sample": True,
                    "eos_token_id": 254,
                    "pad_token_id": 255,
                },
            },
        }
    )
    trainer = get_trainer("PPOTrainer")(config, reward_fn=lambda **kw: [0.0])
    assert trainer.mesh.devices.size == n_global

    # identical host inputs on every rank (SPMD: same program, same data;
    # jit shards them onto the global batch sharding)
    rng = np.random.default_rng(0)
    prompt_ids = jnp.asarray(rng.integers(1, 250, size=(B, Q)), jnp.int32)
    prompt_mask = jnp.ones((B, Q), jnp.int32)

    out = trainer.sample(prompt_ids, prompt_mask)
    ref_lp = trainer.score_ref(
        prompt_ids, prompt_mask, out.tokens, out.response_mask
    )
    rewards = trainer.compute_rewards(
        out.logprobs, ref_lp, out.response_mask, np.zeros((B,), np.float32)
    )
    mb = jax.device_put(
        PPORolloutBatch(
            query_tokens=prompt_ids,
            query_mask=prompt_mask,
            response_tokens=out.tokens,
            response_mask=out.response_mask,
            logprobs=out.logprobs,
            values=out.values,
            rewards=rewards,
        ),
        batch_sharding(trainer.mesh),
    )
    trainer.state, stats = trainer._train_step_jit(trainer.state, mb)
    jax.block_until_ready(trainer.state.params)
    # total_loss is replicated -> addressable on every rank
    loss = float(stats["losses/total_loss"])
    assert np.isfinite(loss), loss

    # pipeline-parallel leg (round 4): the GPipe schedule's ppermute hops
    # must ride the cross-PROCESS transport, not just intra-process ICI.
    # One device from EACH process forms a pp=2 mesh (the canonical
    # dp-major mesh would place pp pairs within a process), a 2-stage
    # pipeline runs a stacked linear stage, and the result must equal the
    # local composition of both stages.
    from trlx_tpu.parallel.mesh import make_mesh
    from trlx_tpu.parallel.pipeline import pipeline_apply

    d0, d1 = jax.devices()[0], jax.devices()[n_local]
    assert d0.process_index != d1.process_index, (d0, d1)
    pp_mesh = make_mesh({"dp": 1, "pp": 2}, devices=[d0, d1])
    stage_w = jnp.stack(
        [jnp.eye(16) * 2.0, jnp.eye(16) + 0.5]
    )  # [S=2, 16, 16]
    xb = jnp.asarray(rng.normal(size=(4, 16)), jnp.float32)

    pp_out = pipeline_apply(
        lambda p, h: h @ p, stage_w, xb, pp_mesh, num_microbatches=2
    )
    expected = np.asarray(xb @ stage_w[0] @ stage_w[1])
    got = np.asarray(pp_out.addressable_shards[0].data)
    np.testing.assert_allclose(got, expected, rtol=1e-5)

    barrier("done")
    if is_main_process():
        print(
            f"mp_smoke ok: procs={num_processes} devices={n_global} "
            f"mesh dp={dp} fsdp={fsdp} tp={tp} "
            f"(+cross-process pp=2 ppermute) loss={loss:.4f}",
            flush=True,
        )


if __name__ == "__main__":
    main(sys.argv[1], int(sys.argv[2]), int(sys.argv[3]))
