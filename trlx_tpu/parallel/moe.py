"""Expert parallelism: top-1 (switch) MoE routing over an ``ep`` mesh axis.

Beyond the reference (SURVEY §2.9: expert parallel "NO ... not required") —
provided as the ``ep`` counterpart of the pipeline/sequence primitives so
the mesh covers every major parallelism axis. TPU-native design: tokens are
sharded over ``ep``, experts are sharded over ``ep`` (leading [E] axis of
the stacked expert params), and dispatch/return ride two ``all_to_all``
collectives over ICI — the switch-transformer layout.

Semantics (Switch Transformer, top-1):
- router logits ``x @ router_w`` pick one expert per token; the gate is the
  softmax probability of the chosen expert (router gradients flow through
  the gate product);
- fixed per-device/per-expert capacity ``ceil(capacity_factor * N_local /
  E)``; tokens over capacity are dropped (their combined output is zero —
  callers keep the residual connection outside, as switch layers do);
- everything is static-shaped: position-in-expert comes from a cumulative
  sum, dispatch/combine are scatter/gather into [E, C, D] buffers.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P


def moe_apply(
    expert_fn: Callable,  # (expert_params, tokens [n, D]) -> [n, D]
    stacked_expert_params,  # leaves [E, ...]
    x: jax.Array,  # [N, D] tokens, sharded over `axis_name`
    router_w: jax.Array,  # [D, E] router weights (replicated)
    mesh: Mesh,
    axis_name: str = "ep",
    capacity_factor: float = 1.25,
    batch_axes: tuple = (),
) -> jax.Array:
    """Route each token through its top-1 expert; returns [N, D].

    ``E`` (leading dim of the expert params) must be divisible by the ``ep``
    axis size. Dropped (over-capacity) tokens return zeros.

    ``batch_axes``: extra mesh axes the token dim is *also* sharded over
    (e.g. ``("dp", "fsdp")`` inside a training step) — each data-parallel
    group then runs its own expert exchange, with the ``all_to_all`` riding
    only the ``ep`` axis. Without it, tokens are treated as replicated over
    those axes (every device would redo the full batch).
    """
    E = jax.tree_util.tree_leaves(stacked_expert_params)[0].shape[0]
    ep = mesh.shape[axis_name]
    if E % ep:
        raise ValueError(f"{E} experts not divisible by ep={ep}")
    N = x.shape[0]
    n_shards = ep * int(np.prod([mesh.shape[a] for a in batch_axes]))
    if N % n_shards:
        raise ValueError(f"{N} tokens not divisible by {n_shards} shards")
    n_loc = N // n_shards
    C = int(np.ceil(capacity_factor * n_loc / E))  # per (device, expert)

    def local(params, x, router_w):
        # x: [n_loc, D] local tokens; params leaves: [E/ep, ...]
        # routing in float32: near-tied logits must argmax identically to
        # any dense-execution twin of this layer regardless of x.dtype
        logits = x.astype(jnp.float32) @ router_w.astype(jnp.float32)
        probs = jax.nn.softmax(logits, axis=-1)
        expert = jnp.argmax(probs, axis=-1)  # [n_loc]
        gate = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]

        onehot = jax.nn.one_hot(expert, E, dtype=jnp.int32)  # [n_loc, E]
        pos = jnp.cumsum(onehot, axis=0) - onehot  # tokens ahead, same expert
        pos = jnp.sum(pos * onehot, axis=-1)  # [n_loc]
        keep = pos < C

        # dispatch buffers [E, C, D]; dropped tokens never written
        dispatch = jnp.zeros((E, C) + x.shape[1:], x.dtype)
        dispatch = dispatch.at[
            jnp.where(keep, expert, 0), jnp.where(keep, pos, 0)
        ].add(jnp.where(keep[:, None], x, 0.0))

        # to expert owners: [E, C, D] -> [E/ep, ep*C, D]
        inbox = jax.lax.all_to_all(
            dispatch, axis_name, split_axis=0, concat_axis=1, tiled=True
        )
        outbox = jax.vmap(expert_fn)(params, inbox)  # [E/ep, ep*C, D]
        # back to token owners: [E, C, D]
        returned = jax.lax.all_to_all(
            outbox, axis_name, split_axis=1, concat_axis=0, tiled=True
        )

        y = returned[jnp.where(keep, expert, 0), jnp.where(keep, pos, 0)]
        y = jnp.where(keep[:, None], y, 0.0)
        return (y.astype(jnp.float32) * gate[:, None]).astype(x.dtype)

    from trlx_tpu.compat import shard_map

    param_specs = jax.tree_util.tree_map(
        lambda _: P(axis_name), stacked_expert_params
    )
    tok_spec = P((*batch_axes, axis_name)) if batch_axes else P(axis_name)
    return shard_map(
        local,
        mesh=mesh,
        in_specs=(param_specs, tok_spec, P()),
        out_specs=tok_spec,
    )(stacked_expert_params, x, router_w)
