"""Parallelism layer: mesh, partitioning, distributed statistics.

The reference's layer-1 distributed substrate (Accelerate/NCCL/DeepSpeed,
SURVEY §1) rebuilt on ``jax.sharding`` + GSPMD. See ``mesh.py`` for the axis
conventions, ``partition.py`` for param sharding (ZeRO/TP equivalents), and
``collectives.py`` for global statistics.
"""

from trlx_tpu.parallel.mesh import (
    AXIS_DP,
    AXIS_FSDP,
    AXIS_TP,
    BATCH_AXES,
    batch_sharding,
    local_batch_size,
    make_mesh,
    replicated,
)
from trlx_tpu.parallel.partition import (
    PartitionRuleError,
    make_partition_specs,
    make_shardings,
    shard_params,
    validate_rules,
)
from trlx_tpu.parallel.collectives import (
    RunningMoments,
    flatten_dict,
    logprobs_from_logits,
    masked_mean,
    masked_var,
    whiten,
)

__all__ = [
    "AXIS_DP",
    "AXIS_FSDP",
    "AXIS_TP",
    "BATCH_AXES",
    "make_mesh",
    "batch_sharding",
    "replicated",
    "local_batch_size",
    "PartitionRuleError",
    "make_partition_specs",
    "make_shardings",
    "shard_params",
    "validate_rules",
    "RunningMoments",
    "whiten",
    "masked_mean",
    "masked_var",
    "logprobs_from_logits",
    "flatten_dict",
]
