"""Distributed statistics: whitening, masked moments, running reward scaling.

TPU-native re-design of the reference's ``trlx/utils/modeling.py``:
- ``get_global_statistics`` (:9-21) / ``whiten`` (:24-34): the reference does
  explicit ``dist.all_reduce`` of sum/count. Here the math is plain jnp
  reductions inside jitted programs — when inputs are sharded over the mesh's
  batch axes, GSPMD lowers the reductions to ICI all-reduces automatically,
  so the "distributed" and single-device code paths are the same function.
- ``RunningMoments`` (:72-104): host-side Chan-style parallel update of
  running reward mean/std, used for ``scale_reward="running"``. Kept
  bit-faithful to the reference's update equations (SURVEY §7.3 warns reward
  scaling changes training dynamics otherwise).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def masked_mean(x: jax.Array, mask: Optional[jax.Array] = None, axis=None) -> jax.Array:
    if mask is None:
        return jnp.mean(x, axis=axis)
    mask = mask.astype(x.dtype)
    return jnp.sum(x * mask, axis=axis) / jnp.maximum(jnp.sum(mask, axis=axis), 1.0)


def masked_var(
    x: jax.Array, mask: Optional[jax.Array] = None, mean: Optional[jax.Array] = None
) -> jax.Array:
    if mean is None:
        mean = masked_mean(x, mask)
    centered = x - mean
    return masked_mean(centered * centered, mask)


def whiten(
    x: jax.Array,
    mask: Optional[jax.Array] = None,
    shift_mean: bool = True,
    eps: float = 1e-8,
) -> jax.Array:
    """Normalize to unit variance (and zero mean unless ``shift_mean=False``).

    Matches reference ``whiten`` semantics (`modeling.py:24-34`) including the
    ``shift_mean=False`` variant used on advantages... (the reference defaults
    True in GAE, `ppo_models.py:137`). Statistics are global across the
    sharded batch automatically under jit.

    The ``+ eps`` under the ``rsqrt`` is load-bearing, not cosmetic: a
    fully-masked (or constant) batch drives ``var`` to 0 and an eps-free
    rsqrt to inf. The NaN-flow engine (``trlx_tpu.analysis.nan_flow``)
    proves this guard from the mask's 0/1 input contract — removing the
    eps fails ``tpu-lint`` with `nan-unguarded` in CI.
    """
    mean = masked_mean(x, mask)
    var = masked_var(x, mask, mean)
    whitened = (x - mean) * jax.lax.rsqrt(var + eps)
    if not shift_mean:
        whitened = whitened + mean
    return whitened


def logprobs_from_logits(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Log-prob of ``labels`` under ``logits`` (`modeling.py:37-41`).

    Computed as gather(log_softmax) — XLA fuses this; no materialized
    full-vocab log tensor survives fusion.
    """
    logp = jax.nn.log_softmax(logits, axis=-1)
    return jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]


class RunningMoments:
    """Running mean/std of reward scalars across rollout chunks.

    Host-side state (two floats + count), updated per chunk with the parallel
    variance combination the reference uses (`modeling.py:83-104`). In
    multi-host runs the per-host batch stats are combined via
    ``jax.experimental.multihost_utils`` before the update; single-host this
    is a no-op.
    """

    def __init__(self):
        self.mean = 0.0
        self.std = 1.0
        self.var = 1.0
        self.count = 1e-24

    def update(self, xs: np.ndarray) -> Tuple[float, float]:
        """Update from a batch; returns (batch_mean, batch_std)."""
        xs = np.asarray(xs, dtype=np.float64)
        xs_count = xs.size
        xs_mean = float(xs.mean())
        xs_var = float(xs.var())

        if jax.process_count() > 1:  # combine across hosts over DCN
            from jax.experimental import multihost_utils

            stats = multihost_utils.process_allgather(
                np.array([xs_mean * xs_count, xs_var * xs_count, xs_count])
            )
            total = stats.sum(axis=0)
            xs_count = float(total[2])
            xs_mean = float(total[0] / xs_count)
            # within-host var average; cross-host mean spread folded below
            xs_var = float(total[1] / xs_count)

        delta = xs_mean - self.mean
        tot_count = self.count + xs_count

        new_sum = xs_var * xs_count
        old_sum = self.var * self.count + delta**2 * self.count * xs_count / tot_count
        tot_sum = old_sum + new_sum

        self.mean += delta * xs_count / tot_count
        self.var = tot_sum / tot_count
        # Bessel correction, as reference (`modeling.py:101-102`)
        self.std = float(np.sqrt(self.var * tot_count / max(tot_count - 1, 1)))
        self.count = tot_count

        return xs_mean, float(np.sqrt(xs_var * xs_count / max(xs_count - 1, 1)))


def flatten_dict(d: dict, parent_key: str = "", sep: str = "/") -> dict:
    """Flatten nested stat dicts for logging (`modeling.py:44-57`)."""
    items = {}
    for k, v in d.items():
        key = parent_key + sep + k if parent_key else k
        if isinstance(v, dict):
            items.update(flatten_dict(v, key, sep))
        else:
            items[key] = v
    return items
