"""Parameter partitioning: path-pattern rules -> ``PartitionSpec`` trees.

The GSPMD replacement for DeepSpeed ZeRO param sharding and for tensor
parallelism (SURVEY §2.9). A model family ships a list of
``(path_pattern, PartitionSpec)`` rules naming which logical dims ride the
``tp`` axis; anything not matched falls back to FSDP auto-sharding (largest
divisible dim over ``fsdp``) or replication. Because sharding is declared on
the param pytree and passed to ``jax.jit``, XLA inserts all-gathers /
reduce-scatters automatically — the "ZeRO-3 GatheredParameters" pattern
(`ilql_models.py:170-181`) has no analogue here; sharded params are used
directly.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from trlx_tpu.parallel.mesh import AXIS_FSDP, AXIS_TP

# A rule: (regex matched against "/"-joined param path, PartitionSpec)
Rules = Sequence[Tuple[str, P]]


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def _auto_fsdp_spec(shape: Tuple[int, ...], fsdp_size: int, taken_axes) -> P:
    """Shard the largest divisible dim over fsdp; replicate if none fits."""
    if fsdp_size <= 1 or not shape:
        return P(*taken_axes) if taken_axes else P()
    order = sorted(range(len(shape)), key=lambda i: -shape[i])
    spec = list(taken_axes) + [None] * (len(shape) - len(taken_axes))
    for i in order:
        if spec[i] is None and shape[i] % fsdp_size == 0:
            spec[i] = AXIS_FSDP
            break
    while spec and spec[-1] is None:
        spec.pop()
    return P(*spec)


def make_partition_specs(
    params: Any,
    mesh: Mesh,
    rules: Optional[Rules] = None,
    min_shard_size: int = 2**14,
) -> Any:
    """Produce a PartitionSpec pytree matching ``params``.

    Matching order: first rule whose regex matches the param path wins and
    contributes its tp placement; the fsdp axis is then layered onto the
    largest still-unsharded divisible dim (ZeRO-equivalent). Params smaller
    than ``min_shard_size`` elements stay replicated (biases, layernorms).
    """
    rules = list(rules or [])
    fsdp = mesh.shape[AXIS_FSDP]
    tp = mesh.shape[AXIS_TP]

    def spec_for(path, leaf):
        shape = tuple(getattr(leaf, "shape", ()) or ())
        name = _path_str(path)
        base: List = [None] * len(shape)
        for pattern, pspec in rules:
            if re.search(pattern, name):
                for i, ax in enumerate(pspec):
                    if ax is not None and i < len(shape):
                        # Apply the rule's axis (tp, ep, ...) only if that
                        # axis exists with size > 1 and divides the dim.
                        n_ax = dict(mesh.shape).get(ax, 1)
                        if n_ax > 1 and shape[i] % n_ax == 0:
                            base[i] = ax
                break
        size = 1
        for s in shape:
            size *= s
        if fsdp > 1 and size >= min_shard_size:
            order = sorted(range(len(shape)), key=lambda i: -shape[i])
            for i in order:
                if base[i] is None and shape[i] % fsdp == 0:
                    base[i] = AXIS_FSDP
                    break
        while base and base[-1] is None:
            base.pop()
        return P(*base)

    return jax.tree_util.tree_map_with_path(spec_for, params)


def make_shardings(
    params: Any, mesh: Mesh, rules: Optional[Rules] = None, **kw
) -> Any:
    """PartitionSpec tree -> NamedSharding tree for jit in/out shardings."""
    specs = make_partition_specs(params, mesh, rules, **kw)
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_params(params: Any, shardings: Any) -> Any:
    """Place a param pytree onto the mesh per ``shardings``."""
    return jax.device_put(params, shardings)
