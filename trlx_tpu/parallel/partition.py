"""Parameter partitioning: path-pattern rules -> ``PartitionSpec`` trees.

The GSPMD replacement for DeepSpeed ZeRO param sharding and for tensor
parallelism (SURVEY §2.9). A model family ships a list of
``(path_pattern, PartitionSpec)`` rules naming which logical dims ride the
``tp`` axis; anything not matched falls back to FSDP auto-sharding (largest
divisible dim over ``fsdp``) or replication. Because sharding is declared on
the param pytree and passed to ``jax.jit``, XLA inserts all-gathers /
reduce-scatters automatically — the "ZeRO-3 GatheredParameters" pattern
(`ilql_models.py:170-181`) has no analogue here; sharded params are used
directly.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from trlx_tpu.parallel.mesh import AXIS_FSDP, AXIS_TP

# A rule: (regex matched against "/"-joined param path, PartitionSpec)
Rules = Sequence[Tuple[str, P]]


class PartitionRuleError(ValueError):
    """A partition rule produced an invalid placement for a param.

    Raised at spec-construction time (i.e. when a family's rules are first
    applied to a param tree) instead of silently replicating the tensor:
    an axis name the mesh doesn't have, or a sharded dim the axis size
    doesn't divide, is a configuration bug — on the real slice topology it
    would either crash at jit time or quietly drop the intended sharding.
    """


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def _auto_fsdp_spec(shape: Tuple[int, ...], fsdp_size: int, taken_axes) -> P:
    """Shard the largest divisible dim over fsdp; replicate if none fits."""
    if fsdp_size <= 1 or not shape:
        return P(*taken_axes) if taken_axes else P()
    order = sorted(range(len(shape)), key=lambda i: -shape[i])
    spec = list(taken_axes) + [None] * (len(shape) - len(taken_axes))
    for i in order:
        if spec[i] is None and shape[i] % fsdp_size == 0:
            spec[i] = AXIS_FSDP
            break
    while spec and spec[-1] is None:
        spec.pop()
    return P(*spec)


def make_partition_specs(
    params: Any,
    mesh: Mesh,
    rules: Optional[Rules] = None,
    min_shard_size: int = 2**14,
    validate: bool = True,
) -> Any:
    """Produce a PartitionSpec pytree matching ``params``.

    Matching order: first rule whose regex matches the param path wins and
    contributes its tp placement; the fsdp axis is then layered onto the
    largest still-unsharded divisible dim (ZeRO-equivalent). Params smaller
    than ``min_shard_size`` elements stay replicated (biases, layernorms).

    With ``validate`` (the default), a matching rule that names a mesh
    axis the mesh doesn't have, or targets a dim the axis size doesn't
    divide, raises :class:`PartitionRuleError` naming the offending param
    path — instead of silently leaving the tensor replicated. Two
    placements still degrade silently by design: an axis of size 1
    (a tp rule on a tp=1 mesh is a no-op, not a bug) and a spec entry
    beyond the leaf's rank (optimizer-state trees contain rank-0
    placeholder leaves — ``optax.MaskedNode`` — on rule-matching paths).
    """
    rules = list(rules or [])
    fsdp = mesh.shape[AXIS_FSDP]
    tp = mesh.shape[AXIS_TP]
    mesh_sizes = dict(mesh.shape)

    def spec_for(path, leaf):
        shape = tuple(getattr(leaf, "shape", ()) or ())
        name = _path_str(path)
        base: List = [None] * len(shape)
        for pattern, pspec in rules:
            if re.search(pattern, name):
                for i, ax in enumerate(pspec):
                    if ax is not None and i < len(shape):
                        if validate and ax not in mesh_sizes:
                            raise PartitionRuleError(
                                f"partition rule {pattern!r} names mesh "
                                f"axis {ax!r} for param {name!r}, but the "
                                f"mesh axes are {sorted(mesh_sizes)}"
                            )
                        # Apply the rule's axis (tp, ep, ...) only when the
                        # axis is active (size > 1); axis size 1 is a no-op.
                        n_ax = mesh_sizes.get(ax, 1)
                        if n_ax > 1:
                            if shape[i] % n_ax != 0:
                                if validate:
                                    raise PartitionRuleError(
                                        f"partition rule {pattern!r} shards "
                                        f"dim {i} of param {name!r} (shape "
                                        f"{shape}) over axis {ax!r} of size "
                                        f"{n_ax}, which does not divide "
                                        f"{shape[i]}"
                                    )
                            else:
                                base[i] = ax
                break
        size = 1
        for s in shape:
            size *= s
        if fsdp > 1 and size >= min_shard_size:
            order = sorted(range(len(shape)), key=lambda i: -shape[i])
            for i in order:
                if base[i] is None and shape[i] % fsdp == 0:
                    base[i] = AXIS_FSDP
                    break
        while base and base[-1] is None:
            base.pop()
        return P(*base)

    return jax.tree_util.tree_map_with_path(spec_for, params)


def validate_rules(params: Any, mesh: Mesh, rules: Optional[Rules]) -> None:
    """Raise :class:`PartitionRuleError` if any rule produces an invalid
    placement for ``params`` on ``mesh`` (see :func:`make_partition_specs`).

    ``params`` may be a tree of arrays or of ``ShapeDtypeStruct``s — only
    shapes are read, so families can validate at registration/startup time
    against ``jax.eval_shape`` output without materializing weights.
    """
    make_partition_specs(params, mesh, rules, validate=True)


def make_shardings(
    params: Any, mesh: Mesh, rules: Optional[Rules] = None, **kw
) -> Any:
    """PartitionSpec tree -> NamedSharding tree for jit in/out shardings."""
    specs = make_partition_specs(params, mesh, rules, **kw)
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_params(params: Any, shardings: Any) -> Any:
    """Place a param pytree onto the mesh per ``shardings``."""
    return jax.device_put(params, shardings)
