"""Multi-host runtime: process init, barriers, host-side data exchange.

The TPU-pod replacement for the reference's process bootstrap
(``accelerate launch`` + WORLD_SIZE/LOCAL_RANK env + startup
``dist.barrier``, `accelerate_base_model.py:40-41`, SURVEY §2.9): one
process per host, ``jax.distributed.initialize`` wires the DCN control
plane, and all device-side collectives ride ICI automatically via GSPMD.
Host-side sync points use ``jax.experimental.multihost_utils``.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax

_initialized = False


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Initialize the multi-host runtime (no-op single-process).

    Must run before anything touches the XLA backend —
    ``jax.distributed.initialize`` rejects a process whose backend is
    already live, so the multi-host probe here uses *environment only*
    (``TPU_WORKER_HOSTNAMES`` on pods; ``JAX_COORDINATOR_ADDRESS`` /
    ``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID`` elsewhere), never
    ``jax.devices()``/``jax.process_count()``. On TPU pods all arguments
    are auto-detected from the TPU metadata.
    """
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    explicit = coordinator_address is not None
    on_tpu_pod = os.environ.get("TPU_WORKER_HOSTNAMES", "").count(",") > 0
    if explicit or on_tpu_pod:
        kwargs = {}
        if explicit:
            kwargs = dict(
                coordinator_address=coordinator_address,
                num_processes=num_processes
                or int(os.environ.get("JAX_NUM_PROCESSES", 1)),
                process_id=process_id or int(os.environ.get("JAX_PROCESS_ID", 0)),
            )
        jax.distributed.initialize(**kwargs)
        _initialized = True


def barrier(name: str = "sync") -> None:
    """Cross-host barrier (reference startup barrier,
    `accelerate_base_model.py:40-41`)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


def is_main_process() -> bool:
    """Rank-0 gating for logging/IO (reference ``is_main_process``)."""
    return jax.process_index() == 0


def broadcast_host_value(value: Any):
    """Broadcast a host-side python value from process 0 (used for e.g.
    host-RNG-derived decisions that must agree across hosts)."""
    if jax.process_count() <= 1:
        return value
    from jax.experimental import multihost_utils

    return multihost_utils.broadcast_one_to_all(value)
