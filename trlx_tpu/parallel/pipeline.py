"""Pipeline parallelism: GPipe-style microbatched stage execution.

Beyond the reference (SURVEY §2.9: pipeline parallel "NO ... not required
for parity; optional") — provided as a first-class mesh primitive so deep
models can shard *layers* over a ``pp`` axis when tensor parallelism alone
runs out of headroom. TPU-native design: every pp device runs the same
compiled program inside ``shard_map``; activations hop to the next stage via
``ppermute`` over ICI each tick, and the schedule (GPipe: S + M - 1 ticks
for S stages x M microbatches; interleaved: v·S + M - 1 cheaper ticks) is a
``lax.fori_loop`` with masked writes — no host control flow.

The primitive is deliberately model-agnostic: ``stage_fn(stage_params, h)
-> h`` with shape-preserving activations, stage params stacked on a leading
[S] axis (sharded over ``pp``). Autodiff works through the schedule
(``ppermute`` transposes to the inverse permutation), so this composes with
training, not just inference.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P


def spmd_stack(*xs):
    """``jnp.stack(xs, axis=0)`` built from ``dynamic_update_slice`` writes.

    XLA's SPMD partitioner mis-lowers a ``concatenate``/``stack`` whose
    output feeds a ``shard_map`` with a ``P("pp")`` in_spec on any mesh
    with a second size>1 axis: each stage reads wrong slices of the
    stacked operand (jit-only; eager is exact). Same compiler-bug family
    as the sharded rollout-concat replica-sum
    (``data/ppo_types.py::concat_rollouts``); minimal standalone repro +
    the workaround A/B in ``tools/pp_miscompile_repro.py``. Every
    stage-stacking path MUST build its [S]-leading arrays through this
    helper, never ``jnp.stack``/``jnp.concatenate``."""
    first = xs[0]
    buf = jnp.zeros((len(xs),) + first.shape, first.dtype)
    for i, x in enumerate(xs):
        buf = jax.lax.dynamic_update_slice(
            buf, x.astype(first.dtype)[None], (i,) + (0,) * first.ndim
        )
    return buf


def stack_stage_params(params_list):
    """Stack per-stage param pytrees on a leading [S] axis (shard over pp)."""
    return jax.tree_util.tree_map(spmd_stack, *params_list)


def stack_stage_params_interleaved(chunk_trees, stages: int, virtual: int):
    """[v*S] per-chunk param trees -> leaves [S, v, ...]: chunk
    ``c = lap*S + d`` goes to device d, lap ``lap`` (round-robin layer
    placement for the interleaved schedule)."""
    device_trees = []
    for d in range(stages):
        laps = [chunk_trees[lap * stages + d] for lap in range(virtual)]
        device_trees.append(jax.tree_util.tree_map(spmd_stack, *laps))
    return stack_stage_params(device_trees)


def pipeline_span_layer_units(S: int, M: int, L: int, v: int = 1) -> int:
    """Schedule span in single-layer compute units (layer cost = 1).

    GPipe (v=1): ``(S + M - 1)`` ticks of ``L/S`` layers. Interleaved
    (v>1): ``(v*S + M - 1)`` ticks of ``L/(v*S)`` layers — the fill/drain
    bubble shrinks by ~v because each tick is v× cheaper while the steady
    term stays M*L/S. Per-device efficiency: ``M / (S + (M-1)/v)`` vs
    GPipe's ``M / (S + M - 1)``."""
    chunk = L // (S * v)
    return (v * S + M - 1) * chunk


def pipeline_apply(
    stage_fn: Callable,
    stacked_params,
    x: jax.Array,  # [B, ...] activations entering stage 0
    mesh: Mesh,
    axis_name: str = "pp",
    num_microbatches: int = 2,
    batch_axes=("dp", "fsdp"),
    aux=None,
    virtual_stages: int = 1,
    capture_stage: int = None,
    capture_only: bool = False,
) -> jax.Array:
    """Run ``x`` through S pipeline stages with M microbatches.

    ``stacked_params`` leaves are [S, ...] (stage-major) with S equal to the
    ``pp`` axis size (one stage per device); stage s applies
    ``stage_fn(params[s], h)``. ``num_microbatches`` must divide the
    *per-batch-shard* size ``x.shape[0] / (dp*fsdp)``. Returns activations
    after the last stage, with the same sharding as ``x``.

    ``aux`` (optional): a pytree of batch-leading [B, ...] arrays carried
    alongside the activations — e.g. an attention bias. Each stage receives
    the microbatch slice matching the activations it is processing, as a
    third argument: ``stage_fn(params, h, aux_mb)``. Unlike ``h``, aux does
    not travel over the wire (every device holds its batch shard).

    ``virtual_stages=v > 1`` runs the interleaved schedule: stacked_params
    leaves are [S, v, L/(S·v)-chunk, ...] (chunk c = ℓ·S + d lives on
    device d, lap ℓ — `stack_stage_params_interleaved`) and the span drops
    from ``(S+M-1)`` ticks of L/S layers to ``(v·S+M-1)`` ticks of
    L/(v·S) layers (:func:`pipeline_span_layer_units`). Differentiable
    like the GPipe path (the backward is the mirrored schedule). Requires
    ``M <= S`` and is train-only (no cache support).
    """
    # One schedule implementation: the cache-less path is the cached path
    # with an empty cache pytree, and the interleaved schedule is the same
    # tick with lap-indexed chunk params (round-3 reviews: hand-synced
    # copies of the pipeline tick invite silent divergence).
    if aux is None:
        def adapted(p, h, _aux, _cache, _idx):
            return stage_fn(p, h), {}
    else:
        def adapted(p, h, aux_m, _cache, _idx):
            return stage_fn(p, h, aux_m), {}

    res = pipeline_apply_cached(
        adapted, stacked_params, x, {}, 0, mesh,
        axis_name=axis_name, num_microbatches=num_microbatches,
        batch_axes=batch_axes, aux=aux, virtual_stages=virtual_stages,
        capture_stage=capture_stage, capture_only=capture_only,
    )
    if capture_stage is None:
        return res[0]
    return res[0], res[2]  # (out — INVALID if capture_only, capture)


def pipeline_apply_cached(
    stage_fn: Callable,
    stacked_params,
    x: jax.Array,  # [B, T, ...] activations entering stage 0
    cache,  # leaves [L, B, C, ...]: layer-major KV buffers, L sharded over pp
    cache_index,
    mesh: Mesh,
    axis_name: str = "pp",
    num_microbatches: int = 2,
    batch_axes=("dp", "fsdp"),
    aux=None,
    virtual_stages: int = 1,
    capture_stage: int = None,
    capture_only: bool = False,
    static_cache=None,
    capture_all: bool = False,
):
    """The pipeline schedule — one implementation for all three uses:
    cache-less train forward (via :func:`pipeline_apply`), rollout decode
    with STAGE-RESIDENT KV caches, and the interleaved train schedule
    (``virtual_stages > 1``, cache-less only).

    ``capture_all=True`` (v=1, cache-less): EVERY device additionally
    saves the activation entering its own stage for each microbatch and
    the schedule returns it as a third output shaped ``[S, M, B/M, ...]``
    sharded ``P(pp, None, batch)`` — the residuals of the rematerialized
    pipeline backward (:func:`pipeline_apply_remat`), which stores only
    stage INPUTS instead of letting autodiff save every layer's
    internals across the whole schedule.

    ``static_cache`` (optional): a READ-ONLY stage-resident tree with the
    same layer-major ``[L, B, ...]`` layout and ``P(pp, batch)`` sharding
    as ``cache`` — e.g. precomputed seq2seq cross-attention K/V. It is
    microbatch-sliced like the cache and handed to ``stage_fn`` as an
    extra argument before ``cache_index`` (signature becomes
    ``stage_fn(params, h, aux_mb, cache_mb, static_mb, cache_index)``)
    but never written back.

    ``capture_stage=k`` additionally collects the activation ENTERING stage
    k for every microbatch (the hydra shared-trunk branch point — the
    boundary between stage k-1 and k) and returns it as a third output
    ``[B, ...]`` shaped like ``x``. v=1 only. With ``capture_only=True``
    the schedule stops after tick ``k + M - 1`` (the last microbatch's
    arrival at stage k) — the first output is then INVALID (stages >= k
    never ran to completion); callers take only the capture.

    ``cache`` leaves are layer-major ``[L, B, C, ...]`` sharded ``P(pp,
    batch_axes)`` — each device permanently holds the KV buffers of its own
    stage's ``L/S`` layers (plus its dp/fsdp batch shard), so a pp mesh
    shards rollout *memory and compute* instead of replicating the full
    model per device. Each tick, the active stage reads/writes only the
    microbatch rows it is processing; writes at inactive (bubble) ticks are
    masked back to the old values.

    ``stage_fn(stage_params, h, aux_mb, stage_cache_mb, cache_index) ->
    (h, new_stage_cache_mb)`` where ``stage_cache_mb`` leaves are
    ``[L/S, b_mb, C, ...]``.

    Interleaved tick math (v > 1): microbatch m enters chunk 0 at tick m
    and advances one chunk per tick, so chunk c of m runs at tick m + c on
    device c mod S. With M <= S each device sees at most one live (m, c)
    per tick (m ≡ t - d (mod S) has one solution in [0, M)), every
    activation is consumed the tick after it arrives, and the single ring
    wire buffer suffices; the lap (= c // S) selects which of the device's
    v param chunks runs. The v = 1 indexing (m = t - idx, no mod) also
    covers M > S, which the mod form cannot — hence the branch.

    Returns ``(out, new_cache)`` with the same shardings as ``(x, cache)``.
    """
    S = mesh.shape[axis_name]
    M = num_microbatches
    v = virtual_stages
    if capture_all:
        if capture_stage is not None or v > 1:
            raise NotImplementedError(
                "capture_all (remat residuals) is v=1 and exclusive with "
                "capture_stage"
            )
        if jax.tree_util.tree_leaves(cache):
            raise NotImplementedError(
                "capture_all is for the cache-less train schedule"
            )
    if capture_stage is not None:
        if v > 1:
            raise NotImplementedError(
                "capture_stage (hydra branch point) is not available on "
                "the interleaved schedule: the stage boundary is not a "
                "single device's input there"
            )
        if not (0 <= capture_stage < S):
            raise ValueError(
                f"capture_stage={capture_stage} outside [0, {S})"
            )
    if v > 1:
        if M > S:
            raise ValueError(
                f"interleaved schedule requires num_microbatches <= pp "
                f"stages ({M} > {S}): with M > S two microbatches collide "
                f"on one device in the same tick; drop virtual_stages or "
                f"microbatches"
            )
        if jax.tree_util.tree_leaves(cache):
            raise NotImplementedError(
                "interleaved schedule is train-only: the stage-resident "
                "KV cache layout is contiguous stage-major (v=1)"
            )
        for leaf in jax.tree_util.tree_leaves(stacked_params):
            if leaf.shape[0] != S or leaf.shape[1] != v:
                raise ValueError(
                    f"interleaved stage params must be [S={S}, v={v}, ...]; "
                    f"got leaf {leaf.shape}"
                )
    else:
        for leaf in jax.tree_util.tree_leaves(stacked_params):
            if leaf.shape[0] != S:
                raise ValueError(
                    f"stacked stage params have leading dim {leaf.shape[0]} "
                    f"but the {axis_name!r} axis has {S} devices (one stage "
                    f"per device); extra stages would be silently dropped"
                )
    for leaf in jax.tree_util.tree_leaves((cache, static_cache)):
        if leaf.shape[0] % S:
            raise ValueError(
                f"cache layer dim {leaf.shape[0]} must divide pp={S}"
            )
    # mesh.shape is host metadata, not a tracer; the int() is trace-static
    n_batch_shards = int(np.prod([mesh.shape[a] for a in batch_axes]))  # tpu-lint: disable=host-scalar-cast
    B_local = x.shape[0] // n_batch_shards
    if x.shape[0] % n_batch_shards or B_local % M:
        raise ValueError(
            f"batch {x.shape[0]} must divide into {n_batch_shards} shards of "
            f"{M} microbatches"
        )

    def local(params, x, cache, static, cache_index, aux):
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        idx = jax.lax.axis_index(axis_name)
        n = jax.lax.psum(1, axis_name)
        b = x.shape[0]
        bm = b // M
        mbs = x.reshape((M, bm) + x.shape[1:]).astype(x.dtype)
        aux_mbs = jax.tree_util.tree_map(
            lambda a: a.reshape((M, a.shape[0] // M) + a.shape[1:]), aux
        )

        perm = [(i, (i + 1) % n) for i in range(n)]
        pp_zero = (0.0 * jax.lax.axis_index(axis_name)).astype(x.dtype)
        buf0 = jnp.zeros_like(mbs[0]) + pp_zero
        outs0 = jnp.zeros_like(mbs) + pp_zero

        want_caps = capture_stage is not None or capture_all

        def tick(t, carry):
            # caps rides the carry only when a capture is requested — the
            # hot paths (train forward, per-token decode) carry no dead
            # buffer
            if want_caps:
                buf, outs, cache, caps = carry
            else:
                (buf, outs, cache), caps = carry, None
            if v > 1:
                m = (t - idx) % n
                c = t - m  # chunk index; c ≡ idx (mod n) by construction
                lap = jnp.clip(c // n, 0, v - 1)
                active = jnp.logical_and(
                    m < M, jnp.logical_and(c >= 0, c < v * n)
                )
                is_first = c == 0
                is_last = c == v * n - 1
                chunk_params = jax.tree_util.tree_map(
                    lambda p: jax.lax.dynamic_index_in_dim(
                        p, lap, axis=0, keepdims=False
                    ),
                    params,
                )
            else:
                m = t - idx
                active = jnp.logical_and(m >= 0, m < M)
                is_first = idx == 0
                is_last = idx == n - 1
                chunk_params = params
            m_c = jnp.clip(m, 0, M - 1)
            h_in = jnp.where(is_first, mbs[m_c], buf)
            if capture_all:
                # every device saves its own stage's input (remat residual)
                caps = jnp.where(active, caps.at[m_c].set(h_in), caps)
            elif capture_stage is not None:
                # the activation ENTERING stage k (the hydra branch point)
                caps = jnp.where(
                    jnp.logical_and(active, idx == capture_stage),
                    caps.at[m_c].set(h_in),
                    caps,
                )
            aux_m = jax.tree_util.tree_map(lambda a: a[m_c], aux_mbs)
            mb_slice = lambda c_: jax.lax.dynamic_slice_in_dim(
                c_, m_c * bm, bm, axis=1
            )
            old_mb = jax.tree_util.tree_map(mb_slice, cache)
            if static_cache is None:
                h_out, new_mb = stage_fn(
                    chunk_params, h_in, aux_m, old_mb, cache_index
                )
            else:
                static_mb = jax.tree_util.tree_map(mb_slice, static)
                h_out, new_mb = stage_fn(
                    chunk_params, h_in, aux_m, old_mb, static_mb, cache_index
                )
            # bubble ticks compute on garbage: mask their cache writes
            new_mb = jax.tree_util.tree_map(
                lambda nk, ok: jnp.where(active, nk.astype(ok.dtype), ok),
                new_mb, old_mb,
            )
            cache = jax.tree_util.tree_map(
                lambda c_, nk: jax.lax.dynamic_update_slice_in_dim(
                    c_, nk, m_c * bm, axis=1
                ),
                cache, new_mb,
            )
            outs = jnp.where(
                jnp.logical_and(active, is_last),
                outs.at[m_c].set(h_out),
                outs,
            )
            wire = jnp.where(active, h_out, buf * 0.0)
            buf = jax.lax.ppermute(wire, axis_name, perm)
            if not want_caps:
                return buf, outs, cache
            return buf, outs, cache, caps

        n_ticks = v * S + M - 1
        if capture_stage is not None and capture_only:
            # last microbatch reaches stage k at tick k + M - 1
            n_ticks = capture_stage + M
        if not want_caps:
            _, outs, cache = jax.lax.fori_loop(
                0, n_ticks, tick, (buf0, outs0, cache)
            )
        else:
            caps0 = jnp.zeros_like(mbs) + pp_zero
            _, outs, cache, caps = jax.lax.fori_loop(
                0, n_ticks, tick, (buf0, outs0, cache, caps0)
            )
        outs = jnp.where(idx == n - 1, outs, jnp.zeros_like(outs))
        outs = jax.lax.psum(outs, axis_name)
        if not want_caps:
            return outs.reshape(x.shape), cache
        if capture_all:
            # per-device stage residuals: [1, M, bm, ...] -> global
            # [S, M, B/M, ...] under P(pp, None, batch)
            return outs.reshape(x.shape), cache, caps[None]
        caps = jnp.where(idx == capture_stage, caps, jnp.zeros_like(caps))
        caps = jax.lax.psum(caps, axis_name)
        return outs.reshape(x.shape), cache, caps.reshape(x.shape)

    from trlx_tpu.compat import shard_map

    # Stage params enter shard_map sharded over pp ONLY: each device holds
    # its stage's L/S layers *fully materialized* for the loop's duration —
    # any fsdp sharding on these params is all-gathered at this boundary.
    # That is a deliberate memory/simplicity trade: keeping fsdp inside the
    # loop would need a per-layer all_gather in the stage scan (gather one
    # layer, compute, free) to avoid holding the gathered stage anyway.
    # So pp here shards *compute and params across stages*; combine with
    # fsdp to shard the *other* stages' memory, not the resident stage's.
    param_specs = jax.tree_util.tree_map(
        lambda _: P(axis_name), stacked_params
    )
    x_spec = P(batch_axes)
    cache_specs = jax.tree_util.tree_map(
        lambda _: P(axis_name, batch_axes), cache
    )
    aux_specs = jax.tree_util.tree_map(lambda _: P(batch_axes), aux)
    if capture_all:
        out_specs = (x_spec, cache_specs, P(axis_name, None, batch_axes))
    elif capture_stage is not None:
        out_specs = (x_spec, cache_specs, x_spec)
    else:
        out_specs = (x_spec, cache_specs)
    static_specs = jax.tree_util.tree_map(
        lambda _: P(axis_name, batch_axes), static_cache
    )
    return shard_map(
        local,
        mesh=mesh,
        in_specs=(param_specs, x_spec, cache_specs, static_specs, P(), aux_specs),
        out_specs=out_specs,
    )(stacked_params, x, cache, static_cache, cache_index, aux)


def _partition_inexact(tree):
    """Split a pytree into (inexact, other) halves with ``None`` sentinels.

    The remat backward differentiates through the stage recompute; int/bool
    leaves (rotary position_ids in aux, gpt_neo's local-band flags in the
    stage tree) have no cotangent — ``jax.vjp`` hands back float0 arrays
    that neither accumulate nor pass a dtype cast. They are carried to the
    recompute via closure instead and get float0 zeros at the custom_vjp
    boundary."""
    inexact = lambda x: jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact)
    fpart = jax.tree_util.tree_map(lambda x: x if inexact(x) else None, tree)
    opart = jax.tree_util.tree_map(lambda x: None if inexact(x) else x, tree)
    return fpart, opart


def _combine_inexact(fpart, opart):
    """Inverse of :func:`_partition_inexact` (None sentinels as leaves)."""
    return jax.tree_util.tree_map(
        lambda f, o: o if f is None else f,
        fpart,
        opart,
        is_leaf=lambda x: x is None,
    )


def _insert_float0(cotangents_f, primals):
    """Fill a partitioned cotangent tree back to the primal structure,
    with float0 zeros (the required custom_vjp cotangent for non-inexact
    primal inputs) at the ``None`` positions."""
    return jax.tree_util.tree_map(
        lambda c, p: np.zeros(np.shape(p), jax.dtypes.float0)
        if c is None
        else c,
        cotangents_f,
        primals,
        is_leaf=lambda x: x is None,
    )


def pipeline_apply_remat(
    stage_fn: Callable,
    stacked_params,
    x: jax.Array,
    mesh: Mesh,
    axis_name: str = "pp",
    num_microbatches: int = 2,
    batch_axes=("dp", "fsdp"),
    aux=None,
) -> jax.Array:
    """:func:`pipeline_apply` with a REMATERIALIZED, hand-scheduled
    backward (the memory half of 1F1B — the part that matters; the bubble
    spans of GPipe-fwd+bwd and 1F1B are equal at 2(S+M-1) ticks).

    Autodiff through the fori_loop schedule saves every tick's stage
    internals (all L/S layers' activations per microbatch) for the whole
    span. Here the forward saves ONLY each stage's input activation per
    microbatch (``capture_all``), and the custom backward re-runs the
    mirrored schedule: at each reverse tick the active device RECOMPUTES
    its stage forward from the saved input under ``jax.vjp`` and applies
    the arriving cotangent — param grads accumulate per stage, activation
    cotangents hop backward over the inverse ``ppermute`` ring, aux
    cotangents (shared bias tensors) accumulate across stages via psum.
    Peak residual memory drops from O(span · per-layer internals) to
    O(M stage inputs) per device + one stage's recompute working set.

    v=1, cache-less, train-schedule only. Non-inexact leaves (int32
    rotary position_ids in aux, gpt_neo's bool band flags in the stage
    tree) ride to the recompute via closure and receive float0
    cotangents at the custom_vjp boundary (round 5). Gradient parity vs
    the autodiffed schedule is pinned in
    ``tests/test_pipeline_parallel.py`` and, per causal family,
    ``tests/test_pp_integration.py::
    test_pp_remat_matches_autodiff_nonfloat_leaves``.
    """
    S = mesh.shape[axis_name]
    M = num_microbatches
    aux_dict = {} if aux is None else aux
    has_aux = bool(jax.tree_util.tree_leaves(aux_dict))
    x_dtype = x.dtype  # static metadata only — bwd must not touch outer tracers

    def call_stage(p, h, a):
        return stage_fn(p, h, a) if has_aux else stage_fn(p, h)

    def fwd_schedule(params, xx, a, capture):
        def adapted(p, h, aux_m, _cache, _idx):
            return call_stage(p, h, aux_m), {}

        return pipeline_apply_cached(
            adapted, params, xx, {}, 0, mesh,
            axis_name=axis_name, num_microbatches=M,
            batch_axes=batch_axes, aux=a if has_aux else None,
            capture_all=capture,
        )

    @jax.custom_vjp
    def run(params, xx, a):
        return fwd_schedule(params, xx, a, capture=False)[0]

    def run_fwd(params, xx, a):
        out, _, saves = fwd_schedule(params, xx, a, capture=True)
        return out, (params, saves, a)

    def run_bwd(res, g):
        params, saves, a = res

        def local_bwd(params, saves, a, g):
            params = jax.tree_util.tree_map(lambda p: p[0], params)
            saves = saves[0]  # [M, bm, ...] — this stage's inputs
            idx = jax.lax.axis_index(axis_name)
            n = jax.lax.psum(1, axis_name)
            b = g.shape[0]
            bm = b // M
            g_mbs = g.reshape((M, bm) + g.shape[1:]).astype(g.dtype)
            aux_mbs = jax.tree_util.tree_map(
                lambda t: t.reshape((M, t.shape[0] // M) + t.shape[1:]), a
            )
            # differentiate only the inexact leaves — int/bool leaves
            # (rotary position_ids, gpt_neo band flags) ride to the
            # recompute via closure and take no cotangent
            params_f, params_o = _partition_inexact(params)
            aux_f, aux_o = _partition_inexact(aux_mbs)
            inv_perm = [(i, (i - 1) % n) for i in range(n)]
            pp_zero = (0.0 * idx).astype(g.dtype)
            buf0 = jnp.zeros_like(g_mbs[0]) + pp_zero
            dxs0 = jnp.zeros_like(g_mbs) + pp_zero
            # accumulator inits derive from the data (0*value keeps every
            # varying-axis annotation: params vary over pp, aux over the
            # batch axes + pp via the idx marker) — synthesized zeros are
            # axis-invariant and shard_map rejects the loop carry
            dp0 = jax.tree_util.tree_map(
                lambda p: (0.0 * p).astype(
                    jnp.promote_types(p.dtype, jnp.float32)
                ),
                params_f,
            )
            da0 = jax.tree_util.tree_map(
                lambda t: (0.0 * t).astype(
                    jnp.promote_types(t.dtype, jnp.float32)
                )
                + (0.0 * idx),
                aux_f,
            )

            def tick(r, carry):
                buf, dxs, dparams, daux = carry
                # stage idx handled microbatch m forward at tick m + idx;
                # its cotangent arrives in mirrored order at r = m + (n-1-idx)
                m = r - (n - 1 - idx)
                active = jnp.logical_and(m >= 0, m < M)
                m_c = jnp.clip(m, 0, M - 1)
                gbar = jnp.where(idx == n - 1, g_mbs[m_c], buf)
                aux_m_f = jax.tree_util.tree_map(lambda t: t[m_c], aux_f)
                aux_m_o = jax.tree_util.tree_map(lambda t: t[m_c], aux_o)
                h_in = saves[m_c]
                _, vjp_fn = jax.vjp(
                    lambda pf, h, af: call_stage(
                        _combine_inexact(pf, params_o),
                        h,
                        _combine_inexact(af, aux_m_o),
                    ),
                    params_f,
                    h_in,
                    aux_m_f,
                )
                dp, dh, da = vjp_fn(gbar.astype(g.dtype))
                # where, not multiply-by-flag: a nan computed on a bubble
                # tick's garbage must not poison the accumulator (0*nan)
                dparams = jax.tree_util.tree_map(
                    lambda acc, d: acc
                    + jnp.where(active, d.astype(acc.dtype), 0.0),
                    dparams, dp,
                )
                daux = jax.tree_util.tree_map(
                    lambda acc, d: acc.at[m_c].add(
                        jnp.where(active, d.astype(acc.dtype), 0.0)
                    ),
                    daux, da,
                )
                dxs = jnp.where(
                    jnp.logical_and(active, idx == 0),
                    dxs.at[m_c].set(dh.astype(dxs.dtype)),
                    dxs,
                )
                wire = jnp.where(active, dh.astype(buf.dtype), buf * 0.0)
                buf = jax.lax.ppermute(wire, axis_name, inv_perm)
                return buf, dxs, dparams, daux

            _, dxs, dparams, daux = jax.lax.fori_loop(
                0, S + M - 1, tick, (buf0, dxs0, dp0, da0)
            )
            # each data shard saw only its rows of every microbatch, so its
            # dparams is a PARTIAL batch sum — reduce over the batch axes
            # (autodiff gets this psum from shard_map's transpose; omitting
            # it here left the out_spec claiming a replication that did not
            # hold, and check_rep=False silently shipped one shard's
            # partial: stage grads were wrong on any dp/fsdp > 1 mesh)
            dparams = jax.tree_util.tree_map(
                lambda d: jax.lax.psum(d, batch_axes), dparams
            )
            dxs = jnp.where(idx == 0, dxs, jnp.zeros_like(dxs))
            dxs = jax.lax.psum(dxs, axis_name)
            # aux is shared by every stage: total cotangent sums over pp
            daux = jax.lax.psum(daux, axis_name)
            a_f_full, _ = _partition_inexact(a)
            daux = jax.tree_util.tree_map(
                lambda t, orig: t.reshape((t.shape[0] * t.shape[1],) + t.shape[2:])
                .astype(orig.dtype),
                daux, a_f_full,
            )
            dparams = jax.tree_util.tree_map(
                lambda d, p: d[None].astype(p.dtype), dparams, params_f
            )
            return dparams, dxs.reshape(g.shape), daux

        from trlx_tpu.compat import HAS_CHECK_VMA, shard_map

        param_specs = jax.tree_util.tree_map(lambda _: P(axis_name), params)
        x_spec = P(batch_axes)
        aux_specs = jax.tree_util.tree_map(lambda _: P(batch_axes), a)
        # cotangent outputs exist only for the inexact leaves; the int/bool
        # leaves get float0 zeros outside the shard_map
        params_f_outer, _ = _partition_inexact(params)
        aux_f_outer, _ = _partition_inexact(a)
        dparams, dx, daux = shard_map(
            local_bwd,
            mesh=mesh,
            in_specs=(
                param_specs, P(axis_name, None, batch_axes), aux_specs, x_spec
            ),
            out_specs=(
                jax.tree_util.tree_map(lambda _: P(axis_name), params_f_outer),
                x_spec,
                jax.tree_util.tree_map(lambda _: P(batch_axes), aux_f_outer),
            ),
            # dx/daux are psum'd inside local_bwd; newer jax's vma pass
            # infers that replication, 0.4.x's check_rep cannot and rejects
            # the out_specs — keep the check only where it can succeed
            check_vma=None if HAS_CHECK_VMA else False,
        )(params, saves, a, g)
        return (
            _insert_float0(dparams, params),
            dx.astype(x_dtype),
            _insert_float0(daux, a),
        )

    run.defvjp(run_fwd, run_bwd)
    return run(stacked_params, x, aux_dict)
