"""PPO rollout data types as JAX pytrees.

Re-design of the reference's per-sample ``PPORLElement`` / batched
``PPORLBatch`` (``trlx/data/ppo_types.py:7-57``). Where the reference keeps a
Python list of per-sample CPU tensors and pads at collate time
(`ppo_pipeline.py:39-66`), the TPU design keeps rollouts *batched and
device-resident with static shapes* from the moment they are produced:
queries are left-padded to a fixed query length and responses right-padded to
a fixed response length, so every downstream jitted program sees one shape and
compiles once.
"""

from __future__ import annotations

import flax.struct as struct
import jax
import jax.numpy as jnp


@struct.dataclass
class PPORolloutBatch:
    """A batch of PPO experience, all arrays device-resident.

    Shapes: B = batch, Q = max query length, R = max response length.

    :param query_tokens: [B, Q] int32, left-padded prompts (reference
        flip-pads queries, `ppo_pipeline.py:41-46`).
    :param query_mask: [B, Q] 1 where real prompt tokens.
    :param response_tokens: [B, R] int32, right-padded sampled responses.
    :param response_mask: [B, R] 1 where real response tokens (up to and
        including eos).
    :param logprobs: [B, R] behavior-policy log-probs of response tokens.
    :param values: [B, R] value estimates at each response position.
    :param rewards: [B, R] per-token rewards: -kl_coef*(logp-ref_logp) with
        the scalar score added at the last real token
        (`ppo_orchestrator.py:163-167`).
    """

    query_tokens: jax.Array
    query_mask: jax.Array
    response_tokens: jax.Array
    response_mask: jax.Array
    logprobs: jax.Array
    values: jax.Array
    rewards: jax.Array

    @property
    def batch_size(self) -> int:
        return self.query_tokens.shape[0]

    def __len__(self) -> int:
        return self.batch_size

    def select(self, idx: jax.Array) -> "PPORolloutBatch":
        """Gather a sub-batch by integer indices (for minibatch sampling)."""
        return jax.tree_util.tree_map(lambda x: x[idx], self)


def concat_rollouts(batches) -> PPORolloutBatch:
    """Concatenate rollout batches along the batch axis.

    Implemented as ``dynamic_update_slice`` writes into a fresh buffer,
    NOT ``jnp.concatenate``: on any mesh with a size>1 axis absent from
    the chunks' batch sharding (tp/sp/pp/ep), XLA's SPMD partitioner
    mis-lowers concatenate of the committed-sharded chunk arrays into a
    *sum over the replica axis* — token ids double (11+11=22), masks
    become 2, and the out-of-vocab embed lookups then fill NaN (jax
    0.4.x; eager and jitted concat both reproduce). This was the root
    cause of the fsdp/tp PPO "NaN within a few steps" divergence: the
    first buffer concat corrupted every minibatch. dynamic_update_slice
    resolves the same input shardings correctly; the sanitizer replay
    (``python -m trlx_tpu.analysis --sanitize``) localizes regressions
    of this class to the first NaN-minting equation.
    """
    batches = list(batches)
    if len(batches) == 1:
        return batches[0]

    def cat(*xs):
        total = sum(x.shape[0] for x in xs)
        out = jnp.zeros((total,) + xs[0].shape[1:], xs[0].dtype)
        offset = 0
        for x in xs:
            out = jax.lax.dynamic_update_slice(
                out, x, (offset,) + (0,) * (x.ndim - 1)
            )
            offset += x.shape[0]
        return out

    return jax.tree_util.tree_map(cat, *batches)
