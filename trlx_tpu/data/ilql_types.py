"""ILQL data types as JAX pytrees.

Re-design of ``trlx/data/ilql_types.py:7-49`` (``ILQLElement`` /
``ILQLBatch``): same fields — tokens, attention mask, per-action rewards,
state/action gather indices, dones — but batched, padded to static shapes,
and device-resident so the ILQL loss is one jitted program.
"""

from __future__ import annotations

import flax.struct as struct
import jax


@struct.dataclass
class ILQLBatch:
    """A batch of offline ILQL experience.

    Shapes: B = batch, T = padded sequence length, A = padded number of
    actions (generated tokens), S = A + 1 states.

    :param input_ids: [B, T] int32 token ids (prompt + response).
    :param attention_mask: [B, T] 1 on real tokens.
    :param rewards: [B, A] per-action rewards (terminal-only placement with
        normalized returns, `offline_orchestrator.py:63-68`).
    :param states_ixs: [B, S] indices into T of state positions.
    :param actions_ixs: [B, A] indices into T of action positions.
    :param dones: [B, S] 0/1 terminal flags per state.
    :param actions_mask: [B, A] 1 on real (non-padding) actions. TPU addition:
        the reference encodes padding by repeating the final index; a mask is
        explicit and keeps reductions exact under static shapes.
    """

    input_ids: jax.Array
    attention_mask: jax.Array
    rewards: jax.Array
    states_ixs: jax.Array
    actions_ixs: jax.Array
    dones: jax.Array
    actions_mask: jax.Array

    def __len__(self) -> int:
        return self.input_ids.shape[0]

    def select(self, idx: jax.Array) -> "ILQLBatch":
        import jax.tree_util as jtu

        return jtu.tree_map(lambda x: x[idx], self)
