"""YAML -> nested dataclass config system.

Re-design of the reference config system (``trlx/data/configs.py:10-190``):
same three-section schema (``model`` / ``train`` / ``method``), same recursive
override merge with unknown-key detection (`merge` :10-21, `update` :179-190),
same method dispatch through the method registry (:153). TPU-specific
additions: a ``train.mesh`` axis spec (data/fsdp/tensor parallel sizes), a
compute ``dtype``, and a from-scratch ``model.model_arch`` override so tiny
synthetic tasks (randomwalks) need no checkpoint.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass, field, fields
from typing import Any, Dict, List, Optional, Tuple

import yaml

from trlx_tpu.data.method_configs import MethodConfig, get_method


def merge(base: Dict, update: Dict, updated: set) -> Dict:
    """Recursively merge ``update`` into ``base``, recording touched keys."""
    for k, v in base.items():
        if k in update and isinstance(v, dict):
            base[k] = merge(v, update[k], updated)
            updated.add(k)
        elif k in update:
            base[k] = update[k]
            updated.add(k)
    return base


def _from_dict_strict(cls, config: Dict[str, Any]):
    known = {f.name for f in fields(cls)}
    unknown = set(config) - known
    if unknown:
        raise ValueError(f"Unknown keys for {cls.__name__}: {sorted(unknown)}")
    return cls(**config)


@dataclass
class ModelConfig:
    """Which policy model to train.

    :param model_path: HF checkpoint directory for weight conversion, or empty
        for from-scratch init via ``model_arch``.
    :param tokenizer_path: HF tokenizer path (host-side only).
    :param model_type: architecture family registered in
        ``trlx_tpu.models``: ``"gpt2"`` (causal LM) or ``"t5"`` (seq2seq).
    :param num_layers_unfrozen: train only the top-k transformer blocks
        (reference `configs.py:42`); -1 trains everything. Also (by
        default) sizes the hydra shared-trunk frozen reference branch for
        PPO.
    :param ref_branch_layers: depth of the hydra frozen KL-reference
        branch, decoupled from freezing. In the reference as shipped the
        PPO freezing block is commented out (`accelerate_base_model.py:
        55-69`) — `num_layers_unfrozen` ONLY sizes the hydra branch
        (`ppo_models.py:525-536`) while the policy trains all layers; this
        key expresses that workload (e.g. ``num_layers_unfrozen: 0`` +
        ``ref_branch_layers: 2``). ``None`` (default) follows
        ``num_layers_unfrozen`` when positive; ``0`` forces the full-copy
        reference.
    :param model_arch: from-scratch architecture overrides (n_layer, n_embd,
        n_head, vocab_size, n_positions, ...) when no checkpoint is given.
    """

    model_path: str = ""
    tokenizer_path: str = ""
    model_type: str = "gpt2"
    num_layers_unfrozen: int = -1
    ref_branch_layers: Optional[int] = None
    model_arch: Dict[str, Any] = field(default_factory=dict)

    @property
    def resolved_ref_branch_layers(self) -> int:
        """Hydra branch depth actually in effect (0 = full-copy ref)."""
        if self.ref_branch_layers is not None:
            return self.ref_branch_layers
        return max(self.num_layers_unfrozen, 0)

    @classmethod
    def from_dict(cls, config: Dict[str, Any]):
        return _from_dict_strict(cls, config)


@dataclass
class TrainConfig:
    """Training loop + distributed layout configuration.

    Core fields mirror the reference ``TrainConfig`` (`configs.py:49-127`);
    ``mesh`` / ``dtype`` / ``param_dtype`` are TPU-native additions.

    :param mesh: device-mesh axis sizes ``{"dp": -1, "fsdp": 1, "tp": 1}``;
        -1 consumes all remaining devices on that axis. dp = pure data
        parallel (replicated params), fsdp = ZeRO-style fully sharded data
        parallel (param/opt-state sharding, the DeepSpeed-stage equivalent),
        tp = tensor parallel.
    """

    total_steps: int = 10000
    seq_length: int = 64
    epochs: int = 100
    batch_size: int = 16

    lr_init: float = 1.0e-4
    lr_target: float = 1.0e-4
    opt_betas: Tuple[float, float] = (0.9, 0.95)
    opt_eps: float = 1.0e-8
    weight_decay: float = 1.0e-6
    grad_clip: float = 1.0
    # Storage dtype for BOTH Adam moments ("float32" | "bfloat16"). bf16
    # halves the optimizer's resident bytes and its per-step HBM read+write
    # (measured ~24% of the bench train step at f32); stores use stochastic
    # rounding so sub-resolution EMA increments ((1-b2)·g²) still
    # accumulate. Update math stays f32. See trainer/common.py.
    adam_moment_dtype: str = "float32"

    checkpoint_interval: int = 10000
    eval_interval: int = 100
    log_interval: int = 1

    pipeline: str = "PromptPipeline"
    orchestrator: str = "PPOOrchestrator"
    trainer: str = "PPOTrainer"

    checkpoint_dir: str = "ckpts"
    # restore train state + loop counters from checkpoint_dir before
    # training (reference Ray-resume path, `accelerate_base_model.py:232-240`)
    resume_from_checkpoint: bool = False
    # write checkpoints on Orbax's background thread: the train loop resumes
    # as soon as device arrays are snapshotted to host buffers
    async_checkpoint: bool = False
    # failure detection (beyond the reference, SURVEY §5.3 "none"): abort
    # with a clear error when the fetched loss stats go non-finite, instead
    # of silently training on NaNs. Checked wherever stats already cross to
    # host (every fused pass / ILQL chunk; log steps on the stepwise path).
    detect_anomalies: bool = True
    # Run-health monitoring (telemetry/health.py, docs/observability.md):
    # {"enabled": true, "on_error": "warn"|"dump"|"abort", "window": ...,
    #  "detectors": {"kl-spike": {"zmax": ...}, ...}, "disable": [...]}.
    # With enabled, each trainer's jitted step fuses training-dynamics
    # scalars (entropy at ent_coef=0, log-ratio extremes, value explained
    # variance, reward quantiles) into its stats pytree — riding the
    # existing per-step transfer — and streaming detectors (kl-spike,
    # entropy-collapse, ratio-explosion, grad-spike, reward-saturation,
    # nan-precursor) watch the fetched rows on host. Bitwise-inert on
    # training (tests/test_phase_overlap.py). Default off: the jitted
    # programs stay byte-identical to a pre-health build.
    health: Dict[str, Any] = field(default_factory=dict)
    # dump one flight-recorder forensics JSON (telemetry/flight_recorder.py)
    # at the END of exactly phase N, on demand — crash dumps need no flag;
    # requires health.enabled
    flight_dump_phase: Optional[int] = None
    # Run ledger & live watching (telemetry/run_ledger.py,
    # docs/observability.md "Run ledger"): with a directory set, the run
    # mirrors each flight-recorder phase record into
    # <run_dir>/phases.jsonl (the `python -m trlx_tpu.telemetry --watch
    # <run_dir>` feed; live rows require health.enabled, which drives the
    # phase records) and the learn() epilogue appends a RunManifest to
    # <run_dir>/manifest.json plus the ledger JSONL ($TRLX_RUN_LEDGER, or
    # <run_dir>/ledger.jsonl). Default off: nothing is written.
    run_dir: Optional[str] = None
    # Fault tolerance (trlx_tpu/resilience, docs/resilience.md):
    # {"enabled": true, "max_restarts": 2, "resume_on_preemption": true,
    #  "preempt_signals": ["SIGTERM", "SIGINT"], "restart_delay_s": 0.0,
    #  "retry": {"max_attempts": ..., "base_delay_s": ...},
    #  "chaos": [{"site": ..., "mode": ..., "phase": ..., "count": ...}]}.
    # With enabled, api.train runs under the resilience supervisor: a
    # SIGTERM/SIGINT drains gracefully at the next phase boundary
    # (emergency atomic checkpoint + flight dump, exit code 75), and
    # retriable failures (transient I/O, HealthAbort, preemption) restart
    # from the latest good checkpoint within a bounded restart budget.
    # Default off: no signal handlers are installed and nothing changes.
    resilience: Dict[str, Any] = field(default_factory=dict)
    project_name: str = "trlx_tpu"
    run_name: str = ""
    seed: int = 1000

    mesh: Dict[str, int] = field(default_factory=lambda: {"dp": -1, "fsdp": 1, "tp": 1})
    # GPipe microbatches per batch shard when the mesh has a pp axis > 1
    # (must divide batch_size / (dp * fsdp)); see models/pp_runner.py
    pp_microbatches: int = 2
    # Interleaved virtual stages per pp device for the TRAIN schedule
    # (Megatron-style): each device holds v round-robin layer chunks, the
    # fill/drain bubble shrinks ~v x at the cost of v x more ppermute hops.
    # Requires pp_microbatches <= pp and n_layer % (pp * v) == 0; decode
    # keeps the plain stage-major schedule (the stage-resident KV layout
    # is contiguous). See parallel/pipeline.py::pipeline_span_layer_units.
    pp_virtual_stages: int = 1
    # Rematerialized pipeline backward for the TRAIN schedule (the memory
    # half of 1F1B — the bubble spans of GPipe-fwd+bwd and 1F1B are equal):
    # the forward saves only each stage's input per microbatch and the
    # custom backward recomputes stages under jax.vjp on the mirrored
    # schedule, instead of autodiff saving every tick's layer internals.
    # Cuts the update's peak activation memory (measured via XLA
    # memory_analysis in tests/test_pipeline_parallel.py); costs one extra
    # stage forward per backward (the standard remat trade). v=1 only;
    # exact grad parity vs the autodiffed schedule is pinned in tests.
    pp_remat: bool = False
    # Compute the PPO update's response logprobs in chunks of this many
    # positions (0 = off): the LM head + log-softmax + gather run per
    # chunk under jax.checkpoint, so the [B, R, vocab] f32 logits buffer
    # — the train step's largest intermediate, ~5 HBM crossings
    # (bench_train_audit.py bytes_split) — never materializes at full
    # width; the backward recomputes each chunk's logits (one extra head
    # matmul). Must divide gen max_new_tokens. Measured-neutral guardrail:
    # only enable where an A/B shows a win (ab in bench_train_audit.py);
    # entropy-bonus runs (ent_coef) fall back to the full buffer.
    logprob_chunk: int = 0
    dtype: str = "bfloat16"
    param_dtype: str = "float32"
    # Serve the rollout phase (sampler + frozen-ref scoring) a one-time
    # compute-dtype copy of the master params instead of the f32 masters.
    # Bit-identical outputs: every op already casts params to the compute
    # dtype per use; leaves that genuinely compute in f32 (value-head fc2,
    # MoE router logits) are excluded. Measured ~neutral on the single-chip
    # bench (ab_rollout_cast.py: sampler 1.02x, ref scoring 0.92x — XLA
    # hoists the loop-invariant f32->bf16 weight conversion out of the
    # decode scan, so per-token reads were already bf16); kept default-on
    # for the halved frozen-ref HBM residency and because on an fsdp mesh
    # the compute-dtype copy halves rollout param all-gather volume.
    # Causal families only — the seq2seq trainer keeps f32 (T5's RMSNorm
    # scales / relative bias are consumed at f32).
    rollout_param_cast: bool = True

    # Rollout engine selection (docs/inference.md): {"engine": "fixed" |
    # "continuous", "slots": ..., "admit_width": ..., "harvest_width":
    # ..., "block_size": ..., "per_row_rng": ...} — parsed into
    # trlx_tpu.inference.RolloutEngineConfig. "continuous" replaces the
    # fixed-batch segmented-scan sampler on the collect path with the
    # slot-admission decode loop over a paged KV cache
    # (trlx_tpu/inference/engine.py): prompts are admitted into vacated
    # decode slots the step after a row emits eos, and completed
    # rollouts stream into the buffer in fixed-width harvest groups.
    # Per-row token-identical to the fixed sampler under per-row RNG
    # (tests/test_inference_engine.py). Causal PPO-family trainers only
    # (no pp mesh axis, no grouped/GRPO sampling yet); "fixed" is the
    # default and the parity baseline. "prefill_chunk" (> 0) runs the
    # engine's admission prefill as need-gated block-aligned prompt
    # chunks (skips leading pad + prefix-pool-covered blocks; bitwise
    # vs the monolithic program — docs/inference.md "Chunked prefill"),
    # and "prefill_chunks_per_pump" bounds chunk forwards per serving
    # pump (stall-free admission under bursts).
    rollout: Dict[str, Any] = field(default_factory=dict)
    # Multi-tenant serving tier (trlx_tpu/serving, docs/serving.md),
    # parsed into trlx_tpu.serving.ServingConfig and consumed by
    # InferenceServer only (training ignores it): {"tenants": {...},
    # "slo_classes": {...}, "prefix_cache_blocks": N, "stream_buffer": N,
    # "aging_half_ms": ...}. prefix_cache_blocks > 0 turns on
    # cross-request shared-prefix KV (the engine gains a shared block
    # pool); tenants/slo_classes type the QoS scheduler's admission.
    serving: Dict[str, Any] = field(default_factory=dict)

    # Span-tracer tuning (trlx_tpu/telemetry, docs/observability.md):
    # {"ring_size": N} — capacity of the bounded span ring. Per-request
    # serving traces (request_trace.py) multiply span volume, so a
    # high-traffic InferenceServer deployment raises this; the
    # TRLX_TELEMETRY_RING env var overrides. Default {} keeps the
    # built-in ring (tracer.DEFAULT_RING_SIZE).
    telemetry: Dict[str, Any] = field(default_factory=dict)

    # Asynchronous actor–learner PPO (docs/async_pipeline.md):
    # {"enabled": true, "staleness_window": 1, "actor_fraction": 1.0} —
    # parsed into trlx_tpu.trainer.async_rl.AsyncRLConfig. With enabled
    # (requires rollout.engine: continuous), the phase barrier between
    # collect and train is removed: actors stream version-tagged
    # rollouts through the stream store while the learner consumes
    # planned minibatches as they land and pushes refreshed weights to
    # the actors MID-GENERATION, bounded by staleness_window (the
    # version-lag guard defers consumption that would exceed it; the
    # staleness-breach health detector is the circuit-breaker).
    # staleness_window: 0 is the bitwise-serial degenerate mode — the
    # async schedule is then bit-identical to the serial same-plan
    # phase (tests/test_async_rl.py). actor_fraction < 1 places the
    # engine on its own device subset (the single-process rehearsal of
    # multi-host actor/learner placement). Default off: nothing changes.
    async_rl: Dict[str, Any] = field(default_factory=dict)

    # Streamed collect→train phase overlap (PPO-family trainers;
    # docs/async_pipeline.md): the behavior policy is snapshotted once per
    # phase, rollout chunks land incrementally in the streaming buffer, and
    # epoch-1 minibatch updates are dispatched as soon as each planned
    # minibatch's rollouts exist — while later chunks are still decoding.
    # Exactly on-policy (every rollout samples from the frozen snapshot;
    # behavior logprobs are recorded at decode time) and bitwise-identical
    # to running the same schedule serially (tests/test_phase_overlap.py).
    # NOTE the streamed UPDATE SCHEDULE itself differs from the legacy
    # fused/stepwise one (and from the torch reference): epoch-MAJOR
    # (epoch 1 over arrival-block minibatches, then epochs 2..E over
    # fresh global permutations) instead of minibatch-major (each
    # shuffled minibatch repeated ppo_epochs times consecutively). Both
    # are standard PPO; reproducing a pre-overlap run exactly requires
    # phase_overlap: false. Passes with a mid-pass eval/checkpoint
    # boundary, a total_steps cutoff, or an active profiler fall back to
    # the legacy fused/stepwise paths automatically. False disables
    # streaming entirely (legacy schedule everywhere).
    phase_overlap: bool = True

    # when set, every collected rollout chunk is appended (one JSON line per
    # sample: query/response text + raw score) to rollouts_<iter>.jsonl here
    rollout_logging_dir: Optional[str] = None
    # write a jax.profiler trace of the first ~10 optimizer steps here
    # (SURVEY §5.1: timing stats + optional jax.profiler integration).
    # With profile_phase set, this is instead the output directory of the
    # single-phase window (and streaming stays enabled).
    profile_dir: Optional[str] = None
    # dump one xplane trace for EXACTLY phase N (one collect→train pair)
    # into profile_dir (default "profiles"): a programmatic jax.profiler
    # window opened before phase N's collection dispatches and closed at
    # its phase boundary — see telemetry/profiler.py, docs/observability.md
    profile_phase: Optional[int] = None
    tags: List[str] = field(default_factory=list)

    @classmethod
    def from_dict(cls, config: Dict[str, Any]):
        if "opt_betas" in config:
            config = dict(config, opt_betas=tuple(config["opt_betas"]))
        return _from_dict_strict(cls, config)


@dataclass
class TRLConfig:
    """Top-level config: ``model`` + ``train`` + ``method`` sections."""

    model: ModelConfig
    train: TrainConfig
    method: MethodConfig

    @classmethod
    def load_yaml(cls, yml_fp: str) -> "TRLConfig":
        with open(yml_fp) as f:
            config = yaml.safe_load(f)
        return cls.from_dict(config)

    @classmethod
    def from_dict(cls, config: Dict[str, Any]) -> "TRLConfig":
        return cls(
            model=ModelConfig.from_dict(config.get("model", {})),
            train=TrainConfig.from_dict(config.get("train", {})),
            method=get_method(config["method"]["name"]).from_dict(
                {k: v for k, v in config["method"].items()}
            ),
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "model": asdict(self.model),
            "train": asdict(self.train),
            "method": self.method.to_dict(),
        }

    def update(self, **kwargs) -> None:
        """Apply flat or nested overrides; raise on keys that match nothing.

        Accepts both nested dicts (``{"train": {"lr_init": 1e-5}}``) and flat
        dotted/bare keys (``lr_init=1e-5``) as the reference's sweep merge
        does (`configs.py:179-190`).
        """
        updates = set()
        sections = {"model": self.model, "train": self.train, "method": self.method}
        for k, v in kwargs.items():
            if k in sections and isinstance(v, dict):
                unknown = set(v) - set(sections[k].__dict__)
                if unknown:
                    raise ValueError(
                        f"Unknown config keys in {k!r}: {sorted(unknown)}"
                    )
                merge(sections[k].__dict__, v, updates)
                updates.add(k)
            elif "." in k:
                section_name, _, field = k.partition(".")
                section = sections.get(section_name)
                if section is not None and hasattr(section, field):
                    setattr(section, field, v)
                    updates.add(k)
            else:
                for section in sections.values():
                    if hasattr(section, k):
                        setattr(section, k, v)
                        updates.add(k)
                        break
        rest = set(kwargs) - updates
        if rest:
            raise ValueError(f"Unknown config keys: {sorted(rest)}")

    def __str__(self):
        import json

        return "TRLConfig:\n" + json.dumps(self.to_dict(), indent=2)
