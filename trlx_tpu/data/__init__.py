"""Data types & configuration layer (reference: ``trlx/data/``).

Contains the YAML config system, the method-config registry, and the
PPO/ILQL experience pytrees. General prompt batch types mirror the
reference's ``accelerate_base_datatypes.py:8-68``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Iterable, List

import flax.struct as struct
import jax

from trlx_tpu.data.configs import ModelConfig, TrainConfig, TRLConfig
from trlx_tpu.data.method_configs import MethodConfig, get_method, register_method


@dataclass
class GeneralElement:
    """Arbitrary data element (reference `data/__init__.py:8-15`)."""

    data: Any
    metadata: dict = field(default_factory=dict)


@dataclass
class RLElement:
    """State/action/reward triple (reference `data/__init__.py:18-31`)."""

    state: Any = None
    action: Any = None
    reward: float = 0.0


@dataclass
class SimElement:
    """Simulacra-style content/preference pair (reference
    `data/__init__.py:34-47`)."""

    content: Any = None
    preference: Any = None


@struct.dataclass
class PromptBatch:
    """Tokenized prompt batch, left-padded to a fixed length.

    Replaces the reference's ``PromptElement``/``PromptBatch``
    (`accelerate_base_datatypes.py:8-35`): text stays host-side in the
    pipeline; this pytree carries only the device arrays.
    """

    input_ids: jax.Array  # [B, Q] int32, left-padded
    attention_mask: jax.Array  # [B, Q]

    def __len__(self) -> int:
        return self.input_ids.shape[0]


__all__ = [
    "TRLConfig",
    "ModelConfig",
    "TrainConfig",
    "MethodConfig",
    "get_method",
    "register_method",
    "GeneralElement",
    "RLElement",
    "PromptBatch",
]
