"""Method-config registry: string name -> RL-method config class.

Re-design of the reference registry (``trlx/data/method_configs.py:9-56``).
Method configs here are *pure-data* dataclasses; the RL math they parameterize
(GAE, PPO loss, ILQL loss) lives in ``trlx_tpu/ops`` as jit-compiled functions
taking the config as a static argument — keeping device code functional
instead of attaching loss methods to config objects.
"""

from __future__ import annotations

from dataclasses import dataclass, field, fields
from typing import Any, Callable, Dict

# name (lowercase, no underscores) -> method config class
_METHODS: Dict[str, type] = {}


def register_method(name: str | type = None):
    """Decorator registering a method config class under a string key."""

    def register_class(cls, key: str):
        _METHODS[key] = cls
        setattr(mod, key, cls)
        return cls

    import sys

    mod = sys.modules[__name__]

    if isinstance(name, type):
        cls = name
        return register_class(cls, cls.__name__.lower())

    def wrap(cls):
        return register_class(cls, (name or cls.__name__).lower())

    return wrap


def get_method(name: str) -> type:
    """Look up a method config class by its registered (case-insensitive) name."""
    key = name.lower()
    if key not in _METHODS:
        # built-in methods register on import (reference does the same via
        # `trlx/utils/loading.py:1-16` import-time registration)
        import trlx_tpu.ops.ilql_math  # noqa: F401
        import trlx_tpu.ops.ppo_math  # noqa: F401
        import trlx_tpu.trainer.grpo_trainer  # noqa: F401  (GRPOConfig)
    if key in _METHODS:
        return _METHODS[key]
    raise ValueError(f"Unknown method config: {name!r}. Registered: {sorted(_METHODS)}")


@dataclass
class MethodConfig:
    """Base config for an RL method.

    :param name: registry key used by YAML `method.name` dispatch.
    """

    name: str = ""

    @classmethod
    def from_dict(cls, config: Dict[str, Any]):
        known = {f.name for f in fields(cls)}
        unknown = set(config) - known
        if unknown:
            raise ValueError(
                f"Unknown keys for {cls.__name__}: {sorted(unknown)}"
            )
        return cls(**config)

    def to_dict(self) -> Dict[str, Any]:
        from dataclasses import asdict

        return asdict(self)
