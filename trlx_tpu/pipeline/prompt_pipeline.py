"""Prompt pipeline: text (or pre-tokenized) prompts -> fixed-shape batches.

Re-design of the reference ``PromptPipeline`` + ``DataCollatorForRLUL2``
(``trlx/pipeline/offline_pipeline.py:14-54``): prompts are tokenized and
**left-padded to a fixed query length once at construction** (the reference
re-tokenizes to max_length 512 per collate). Left-padding puts the last
prompt token at a fixed slot, which the jitted sampler requires
(`ops/sampling.py`). Ground-truth responses (the fork's ``response_gt``
carried through batches for the reward fn) ride along as host strings.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np

from trlx_tpu.data import PromptBatch
from trlx_tpu.pipeline import BasePipeline, register_datapipeline


def left_pad(seqs: Sequence[Sequence[int]], length: int, pad_id: int):
    """Left-pad token id lists to ``length``; truncates from the left (keeps
    the most recent tokens, as the reference tokenizer truncation does)."""
    ids = np.full((len(seqs), length), pad_id, dtype=np.int32)
    mask = np.zeros((len(seqs), length), dtype=np.int32)
    for i, s in enumerate(seqs):
        s = list(s)[-length:]
        if s:
            ids[i, -len(s):] = s
            mask[i, -len(s):] = 1
    return ids, mask


@register_datapipeline
class PromptPipeline(BasePipeline):
    """Holds (prompt, optional response_gt) pairs, pre-tokenized.

    :param prompts: list of strings, or list of token-id lists (synthetic
        tasks with no text tokenizer, e.g. randomwalks).
    :param max_prompt_length: fixed query length Q.
    :param tokenizer: object with ``encode``/``decode``/``pad_token_id``;
        required when prompts are strings.
    :param response_gt: optional ground-truth responses (fork's tsv pairs,
        `trlx/trlx.py:46-54` — here a proper argument, hack removed).
    """

    def __init__(
        self,
        prompts: Union[List[str], List[List[int]]],
        max_prompt_length: int,
        tokenizer=None,
        response_gt: Optional[List[str]] = None,
    ):
        if response_gt is not None and len(response_gt) != len(prompts):
            raise ValueError("response_gt length must match prompts")
        self.tokenizer = tokenizer
        self.prompts_text: List[Optional[str]] = []
        token_lists: List[List[int]] = []
        for p in prompts:
            if isinstance(p, str):
                if tokenizer is None:
                    raise ValueError("string prompts require a tokenizer")
                token_lists.append(list(tokenizer.encode(p)))
                self.prompts_text.append(p)
            else:
                token_lists.append(list(p))
                self.prompts_text.append(None)
        pad_id = getattr(tokenizer, "pad_token_id", 0) or 0
        self.input_ids, self.attention_mask = left_pad(
            token_lists, max_prompt_length, pad_id
        )
        # Pre-decode token-list prompts once (from the padded/truncated
        # arrays, so the text matches what the model sees) — the rollout
        # loop otherwise re-detokenizes every chunk, stalling the device.
        for i, text in enumerate(self.prompts_text):
            if text is None:
                ids = self.input_ids[i][self.attention_mask[i] > 0]
                if tokenizer is not None:
                    # match trainer.decode_queries exactly
                    self.prompts_text[i] = tokenizer.decode(
                        ids, skip_special_tokens=True
                    )
                else:
                    self.prompts_text[i] = " ".join(map(str, ids.tolist()))
        self.response_gt = list(response_gt) if response_gt is not None else None
        # real (non-pad) token counts per prompt — trainers use these to
        # validate/bound the decode budget against gen max_length without
        # a device fetch (the mask is host numpy here)
        self.prompt_lengths = self.attention_mask.sum(axis=1)

    @property
    def min_prompt_tokens(self) -> int:
        return int(self.prompt_lengths.min()) if len(self) else 0

    @property
    def max_prompt_tokens(self) -> int:
        return int(self.prompt_lengths.max()) if len(self) else 0

    def __len__(self) -> int:
        return len(self.input_ids)

    def __getitem__(self, i: int):
        return self.input_ids[i], self.attention_mask[i]

    def create_loader(
        self,
        batch_size: int,
        shuffle: bool = False,
        seed: int = 0,
        drop_last: bool = True,
    ) -> Iterable[Tuple[PromptBatch, Dict[str, Any]]]:
        """Yield (PromptBatch, meta) where meta carries host-side strings.

        Batches are always full-size (smaller trailing batches would trigger
        recompilation); with ``drop_last=False`` the tail batch is padded by
        repeating earlier rows and marked via ``meta["n_real"]``.
        """
        n = len(self)
        order = np.arange(n)
        if shuffle:
            np.random.default_rng(seed).shuffle(order)

        batches = []
        for start in range(0, n, batch_size):
            idx = order[start : start + batch_size]
            n_real = len(idx)
            if n_real < batch_size:
                if drop_last:
                    continue
                fill = order[np.arange(batch_size - n_real) % n]
                idx = np.concatenate([idx, fill])
            batches.append((idx, n_real))

        def gen():
            for idx, n_real in batches:
                batch = PromptBatch(
                    input_ids=jnp.asarray(self.input_ids[idx]),
                    attention_mask=jnp.asarray(self.attention_mask[idx]),
                )
                meta = {
                    "n_real": n_real,
                    "prompts_text": [self.prompts_text[i] for i in idx],
                    "response_gt": (
                        [self.response_gt[i] for i in idx]
                        if self.response_gt is not None
                        else None
                    ),
                }
                yield batch, meta

        return gen()
