"""Pipelines & rollout stores (reference layer 4, ``trlx/pipeline/``).

``BasePipeline`` (`trlx/pipeline/__init__.py:15-47`) was a torch Dataset;
here a pipeline is a plain host-side container that yields *fixed-shape,
device-ready* batches — padding happens once at construction, not per
collate, so every jitted consumer compiles exactly once.

``BaseRolloutStore`` (`trlx/pipeline/__init__.py:50-98`) kept Python lists
of CPU tensors; the PPO equivalent here (`ppo_buffer.py`) is a
device-resident pytree of batched arrays (SURVEY §7.1 design stance).
"""

from __future__ import annotations

import sys
from abc import ABC, abstractmethod
from typing import Any, Callable, Dict, Iterable

_DATAPIPELINES: Dict[str, type] = {}


def register_datapipeline(name=None):
    """Decorator registering a pipeline class (reference
    `pipeline/__init__.py:12-34`)."""

    def register_class(cls, key: str):
        _DATAPIPELINES[key] = cls
        setattr(sys.modules[__name__], key, cls)
        return cls

    if isinstance(name, type):
        return register_class(name, name.__name__.lower())

    def wrap(cls):
        return register_class(cls, (name or cls.__name__).lower())

    return wrap


def get_datapipeline(name: str) -> type:
    key = name.lower()
    if key not in _DATAPIPELINES:
        import trlx_tpu.pipeline.prompt_pipeline  # noqa: F401
    if key in _DATAPIPELINES:
        return _DATAPIPELINES[key]
    raise ValueError(
        f"Unknown pipeline: {name!r}. Registered: {sorted(_DATAPIPELINES)}"
    )


class BasePipeline(ABC):
    """A dataset of prompts; yields device-ready batches."""

    @abstractmethod
    def __len__(self) -> int: ...

    @abstractmethod
    def create_loader(self, batch_size: int, shuffle: bool = False) -> Iterable:
        """Yield batches; each batch is (PromptBatch, host_metadata dict)."""
        ...


class BaseRolloutStore(ABC):
    """Experience storage consumed by a trainer's optimization loop."""

    @abstractmethod
    def push(self, exps) -> None: ...

    @abstractmethod
    def __len__(self) -> int: ...

    @abstractmethod
    def create_loader(self, batch_size: int, shuffle: bool = False) -> Iterable: ...
