"""Device-resident PPO rollout buffer.

Replaces the reference ``PPORolloutStorage`` (``trlx/pipeline/ppo_pipeline.py
:11-68``) — a Python list of per-sample CPU tensors flip-padded at collate —
with an append-of-batches pytree that never leaves the device: rollout
chunks arrive already batched/padded from the jitted sampler, minibatch
sampling is a device-side gather, and experience feeds the jitted train step
with zero host round-trips (SURVEY §7.1).
"""

from __future__ import annotations

from typing import Iterator, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from trlx_tpu.data.ppo_types import PPORolloutBatch, concat_rollouts
from trlx_tpu.pipeline import BaseRolloutStore


class PPORolloutBuffer(BaseRolloutStore):
    """Accumulates fixed-shape rollout chunks; serves shuffled minibatches."""

    def __init__(self):
        self._chunks: List[PPORolloutBatch] = []
        self._full: Optional[PPORolloutBatch] = None

    def push(self, batch: PPORolloutBatch) -> None:
        self._chunks.append(batch)
        self._full = None

    def clear_history(self) -> None:
        """Drop all experience (on-policy refresh, `ppo_pipeline.py:25-26`)."""
        self._chunks = []
        self._full = None

    @property
    def full(self) -> PPORolloutBatch:
        if self._full is None:
            if not self._chunks:
                raise ValueError("rollout buffer is empty")
            self._full = (
                self._chunks[0]
                if len(self._chunks) == 1
                else concat_rollouts(self._chunks)
            )
        return self._full

    def __len__(self) -> int:
        return sum(c.batch_size for c in self._chunks)

    def create_loader(
        self,
        batch_size: int,
        shuffle: bool = True,
        seed: int = 0,
        sharding=None,
    ) -> Iterator[PPORolloutBatch]:
        """Yield minibatches as device-side gathers of the full buffer.

        Indices are generated on host (cheap, shapes static); the gather and
        everything downstream stay on device. ``sharding`` (typically the
        mesh batch sharding) commits each minibatch's placement so the jitted
        train step sees its declared in_sharding.
        """
        full = self.full
        n = full.batch_size
        order = np.arange(n)
        if shuffle:
            np.random.default_rng(seed).shuffle(order)
        for start in range(0, n - batch_size + 1, batch_size):
            idx = jnp.asarray(order[start : start + batch_size])
            mb = full.select(idx)
            if sharding is not None:
                mb = jax.device_put(mb, sharding)
            yield mb

    def stacked_minibatches(
        self,
        batch_size: int,
        shuffle: bool = True,
        seed: int = 0,
        sharding=None,
        repeat: int = 1,
    ) -> PPORolloutBatch:
        """All minibatches of one buffer pass as a single [n_mb*repeat, B,
        ...] pytree — the input of the fused (one-dispatch) train phase,
        scanned on device instead of dispatched per minibatch.

        ``repeat`` duplicates each minibatch consecutively (PPO's
        ``ppo_epochs`` inner updates on the same minibatch), which keeps the
        fused phase a flat scan of one train-step body — far cheaper to
        compile than a nested/unrolled loop. ``sharding`` should be the
        mesh's ``stacked_batch_sharding`` so each scan slice lands with the
        train step's expected batch sharding.
        """
        full = self.full
        n = full.batch_size
        n_mb = n // batch_size
        if n_mb == 0:
            raise ValueError(f"buffer smaller than one minibatch ({n} < {batch_size})")
        order = np.arange(n)
        if shuffle:
            np.random.default_rng(seed).shuffle(order)
        idx = order[: n_mb * batch_size].reshape(n_mb, 1, batch_size)
        idx = np.broadcast_to(idx, (n_mb, repeat, batch_size)).reshape(
            n_mb * repeat, batch_size
        )
        mbs = full.select(jnp.asarray(idx))  # leaves gain a leading dim
        if sharding is not None:
            mbs = jax.device_put(mbs, sharding)
        return mbs
