"""Device-resident PPO rollout buffer.

Replaces the reference ``PPORolloutStorage`` (``trlx/pipeline/ppo_pipeline.py
:11-68``) — a Python list of per-sample CPU tensors flip-padded at collate —
with an append-of-batches pytree that never leaves the device: rollout
chunks arrive already batched/padded from the jitted sampler, minibatch
sampling is a device-side gather, and experience feeds the jitted train step
with zero host round-trips (SURVEY §7.1).

Two accumulation modes:

- **chunk mode** (default): chunks append to a list; the full buffer is
  materialized lazily via :func:`~trlx_tpu.data.ppo_types.concat_rollouts`.
- **stream mode** (:meth:`PPORolloutBuffer.begin_stream`): rows land
  incrementally in a preallocated device store via ``dynamic_update_slice``
  writes (NEVER ``jnp.concatenate`` of committed-sharded chunks — the XLA
  SPMD mis-lowering documented in ``concat_rollouts``), so minibatches can
  be gathered *while collection is still running*. This is the substrate of
  the overlapped collect→train phase (docs/async_pipeline.md): the trainer
  dispatches epoch-1 PPO updates as soon as each planned minibatch's
  constituent rollouts have landed.

:class:`StreamPlan` fixes the entire phase's minibatch permutation up front
from the (known) total rollout count, so the overlapped and serial schedules
consume bitwise-identical minibatch slices in the same order — the
overlap is purely a dispatch reordering, never a data reordering.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Iterator, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger(__name__)

from trlx_tpu.data.ppo_types import PPORolloutBatch, concat_rollouts
from trlx_tpu.pipeline import BaseRolloutStore


@dataclass(frozen=True)
class StreamPlan:
    """The full update schedule of one streamed collect→train phase,
    computed before the first rollout lands.

    Epoch 1 minibatches are the *arrival blocks*: minibatch ``k`` is rows
    ``[k·B, (k+1)·B)`` in landing order, dispatchable the moment
    ``(k+1) * batch_size`` rollouts exist — maximal collect/train
    overlap. No within-block shuffle: a minibatch gradient is invariant
    to row order inside the batch, so the randomness of epoch-1
    minibatch composition comes entirely from the pipeline's shuffled
    prompt draw (arrival order IS a random draw). Epochs 2..ppo_epochs
    each use a fresh *global* permutation (all rows are available by
    then) and run as one fused scan after collection.

    Both the overlapped and the serial execution of a phase follow this
    same plan, which is what makes them bitwise-comparable.
    """

    total: int  # rollouts the schedule covers (n_minibatches * batch_size)
    batch_size: int
    ppo_epochs: int
    epoch1: np.ndarray  # [n_minibatches, batch_size] row indices
    residual: np.ndarray  # [n_minibatches * (ppo_epochs-1), batch_size]

    @property
    def n_minibatches(self) -> int:
        return self.epoch1.shape[0]

    @property
    def n_updates(self) -> int:
        return self.n_minibatches * self.ppo_epochs

    def rows_needed(self, k: int) -> int:
        """Rollouts that must have landed before epoch-1 minibatch ``k``
        (0-based) can be dispatched."""
        return (k + 1) * self.batch_size

    def ready(self, k: int, landed: int) -> bool:
        return landed >= self.rows_needed(k)


def make_stream_plan(
    total: int, batch_size: int, ppo_epochs: int, seed: int = 0
) -> StreamPlan:
    """Build the phase schedule for ``total`` rollouts (extra rows a
    non-dividing final chunk over-collects are stored but not scheduled)."""
    n_mb = total // batch_size
    if n_mb < 1:
        raise ValueError(
            f"stream plan needs at least one minibatch "
            f"({total} rollouts < batch_size {batch_size})"
        )
    rng = np.random.default_rng(seed)
    n_sched = n_mb * batch_size
    epoch1 = np.arange(n_sched).reshape(n_mb, batch_size)
    residual = (
        np.stack(
            [rng.permutation(n_sched) for _ in range(ppo_epochs - 1)]
        ).reshape(n_mb * (ppo_epochs - 1), batch_size)
        if ppo_epochs > 1
        else np.zeros((0, batch_size), np.int64)
    )
    return StreamPlan(
        total=n_sched,
        batch_size=batch_size,
        ppo_epochs=ppo_epochs,
        epoch1=epoch1,
        residual=residual,
    )


def _alloc_store(chunk: PPORolloutBatch, capacity: int) -> PPORolloutBatch:
    """Fresh zero store of ``capacity`` rows with ``chunk``'s trailing
    shapes/dtypes."""
    return jax.tree_util.tree_map(
        lambda x: jnp.zeros((capacity,) + x.shape[1:], x.dtype), chunk
    )


def _write_rows(
    store: PPORolloutBatch, chunk: PPORolloutBatch, offset: int
) -> PPORolloutBatch:
    """Write ``chunk``'s rows into ``store`` at ``offset`` via
    ``dynamic_update_slice`` (resolves committed chunk shardings correctly
    on every mesh — see ``concat_rollouts`` for why concatenate must not
    be used here)."""
    return jax.tree_util.tree_map(
        lambda s, x: jax.lax.dynamic_update_slice(
            s, x.astype(s.dtype), (offset,) + (0,) * (x.ndim - 1)
        ),
        store,
        chunk,
    )


def land_rows(
    store: PPORolloutBatch, chunk: PPORolloutBatch, offset
) -> PPORolloutBatch:
    """The stream store's landing program: one fused, **store-donating**
    write of a rollout chunk at a dynamic ``offset``. Same
    ``dynamic_update_slice`` discipline as :func:`_write_rows` (bitwise-
    identical results), but jitted with the store donated so each
    landing updates the existing buffers in place instead of allocating
    a fresh full-capacity store per chunk — the store is the collect
    phase's largest host-loop allocation, and under the async
    actor–learner schedule landings and train steps interleave on the
    same HBM high-water mark. ``offset`` is a device scalar so every
    landing of a phase shares ONE compiled program (a python-int offset
    would bake a program per landing position). Traced by the analysis
    harness as ``ppo.versioned_land`` — the device half of the
    version-tagged landing (the version column itself is host-side
    plan metadata, like the minibatch indices)."""
    return _write_rows(store, chunk, offset)


_land_rows_jit = jax.jit(land_rows, donate_argnums=(0,))


class PPORolloutBuffer(BaseRolloutStore):
    """Accumulates fixed-shape rollout chunks; serves shuffled minibatches."""

    def __init__(self):
        self._chunks: List[PPORolloutBatch] = []
        self._full: Optional[PPORolloutBatch] = None
        self._store: Optional[PPORolloutBatch] = None  # stream-mode store
        self._capacity = 0
        self._landed = 0
        self._streaming = False
        # host-side behavior-version tag per landed row (async
        # actor–learner, docs/async_pipeline.md): plan metadata like the
        # minibatch indices — never crosses to device, so the staleness
        # guard's comparisons are plain host ints (no host-branch hazard)
        self._row_versions: Optional[np.ndarray] = None
        self._chunk_versions: List[np.ndarray] = []

    def begin_stream(self, capacity: int) -> None:
        """Switch to incremental stream mode for the coming phase.

        ``capacity`` is the planned rollout total; a final chunk that
        overshoots it grows the store. Requires an empty buffer (the
        stream is a whole phase; call :meth:`clear_history` first)."""
        if len(self):
            raise ValueError(
                "begin_stream on a non-empty buffer — clear_history() "
                "the previous phase's experience first"
            )
        if capacity < 1:
            raise ValueError(f"stream capacity must be >= 1, got {capacity}")
        self._streaming = True
        self._store = None
        self._capacity = int(capacity)
        self._landed = 0
        self._full = None
        self._row_versions = np.zeros(self._capacity, np.int64)
        self._chunk_versions = []

    @property
    def streaming(self) -> bool:
        return self._streaming

    def push(self, batch: PPORolloutBatch, versions=None) -> None:
        """Append one rollout chunk. ``versions`` (optional, host ints of
        length ``batch_size``) tags each row with the behavior-policy
        version that generated it — the async actor–learner's staleness
        accounting reads the tags back via :meth:`row_versions`;
        untagged chunks default to version 0 (the phase snapshot)."""
        n = batch.batch_size
        v = (
            np.zeros(n, np.int64)
            if versions is None
            else np.asarray(versions, np.int64)
        )
        if v.shape != (n,):
            raise ValueError(
                f"versions must be [{n}] host ints, got shape {v.shape}"
            )
        if not self._streaming:
            self._chunks.append(batch)
            self._chunk_versions.append(v)
            self._full = None
            return
        if self._store is None:
            self._store = _alloc_store(batch, max(self._capacity, n))
            self._capacity = self._store.batch_size
            if len(self._row_versions) < self._capacity:
                self._row_versions = np.resize(
                    self._row_versions, self._capacity
                )
        if self._landed + n > self._capacity:
            # a non-dividing final chunk overshoots the planned capacity:
            # grow the store (same dynamic_update_slice discipline). The
            # new capacity is rounded up to a power-of-two bucket — an
            # exact `landed + n` capacity changes the store's (and every
            # downstream gather's) shapes on EVERY overflow, recompiling
            # the write/gather programs each time; bucketed growth
            # reaches a steady-state shape after one resize, so the
            # compile-stability audit sees one compile, not one per
            # overflow.
            need = self._landed + n
            new_capacity = max(self._capacity, 1)
            while new_capacity < need:
                new_capacity *= 2
            logger.warning(
                "PPORolloutBuffer stream store overflow: growing %d -> %d "
                "rows (power-of-two bucket for %d landed rollouts) — "
                "downstream jitted shapes change once for this bucket",
                self._capacity, new_capacity, need,
            )
            grown = _alloc_store(batch, new_capacity)
            grown = _write_rows(grown, self._store, 0)
            self._store, self._capacity = grown, new_capacity
            self._row_versions = np.resize(self._row_versions, new_capacity)
        # the donating jitted landing (one compiled program per phase;
        # in-place store update instead of a fresh full-capacity
        # allocation per chunk — see land_rows)
        self._store = _land_rows_jit(
            self._store, batch, jnp.int32(self._landed)
        )
        self._row_versions[self._landed : self._landed + n] = v
        self._landed += n
        self._full = None

    def clear_history(self) -> None:
        """Drop all experience (on-policy refresh, `ppo_pipeline.py:25-26`)."""
        self._chunks = []
        self._full = None
        self._store = None
        self._capacity = 0
        self._landed = 0
        self._streaming = False
        self._row_versions = None
        self._chunk_versions = []

    @property
    def full(self) -> PPORolloutBatch:
        if self._streaming:
            if self._store is None:
                raise ValueError("rollout buffer is empty")
            if self._landed == self._store.batch_size:
                return self._store
            # static python-int slice of the landed prefix
            return jax.tree_util.tree_map(
                lambda x: x[: self._landed], self._store
            )
        if self._full is None:
            if not self._chunks:
                raise ValueError("rollout buffer is empty")
            self._full = (
                self._chunks[0]
                if len(self._chunks) == 1
                else concat_rollouts(self._chunks)
            )
        return self._full

    def __len__(self) -> int:
        if self._streaming:
            return self._landed
        return sum(c.batch_size for c in self._chunks)

    def row_versions(self, idx) -> np.ndarray:
        """Behavior-policy version tag per row of ``idx`` (host ints, any
        shape). Rows pushed untagged read as version 0."""
        idx = np.asarray(idx)
        if self._streaming:
            if self._row_versions is None:
                raise ValueError("rollout buffer is empty")
            # idx is HOST numpy by contract (plan indices), same as
            # gather's guard: no device value is ever branched on
            if idx.size and int(idx.max()) >= self._landed:  # tpu-lint: disable=host-branch
                raise ValueError(
                    f"row_versions of row {int(idx.max())} but only "
                    f"{self._landed} rollouts have landed"
                )
            return self._row_versions[idx]
        if not self._chunks:
            raise ValueError("rollout buffer is empty")
        return np.concatenate(self._chunk_versions)[idx]

    def gather(self, idx: np.ndarray, sharding=None) -> PPORolloutBatch:
        """Device-side gather of rows by index — ``idx`` may be [B] (one
        minibatch) or [n, B] (stacked minibatches for the fused phase).
        In stream mode every index must already have landed."""
        idx = np.asarray(idx)
        # idx is HOST numpy by contract (plan indices): the int() never
        # touches a device value, and every host runs the identical plan,
        # so this branch cannot desynchronize hosts.
        if self._streaming and idx.size and int(idx.max()) >= self._landed:  # tpu-lint: disable=host-branch
            raise ValueError(
                f"gather of row {int(idx.max())} but only "
                f"{self._landed} rollouts have landed"
            )
        source = self._store if self._streaming else self.full
        mb = source.select(jnp.asarray(idx))
        if sharding is not None:
            mb = jax.device_put(mb, sharding)
        return mb

    def create_loader(
        self,
        batch_size: int,
        shuffle: bool = True,
        seed: int = 0,
        sharding=None,
    ) -> Iterator[PPORolloutBatch]:
        """Yield minibatches as device-side gathers of the full buffer.

        Indices are generated on host (cheap, shapes static); the gather and
        everything downstream stay on device. ``sharding`` (typically the
        mesh batch sharding) commits each minibatch's placement so the jitted
        train step sees its declared in_sharding.
        """
        full = self.full
        n = full.batch_size
        order = np.arange(n)
        if shuffle:
            np.random.default_rng(seed).shuffle(order)
        for start in range(0, n - batch_size + 1, batch_size):
            idx = jnp.asarray(order[start : start + batch_size])
            mb = full.select(idx)
            if sharding is not None:
                mb = jax.device_put(mb, sharding)
            yield mb

    def stacked_minibatches(
        self,
        batch_size: int,
        shuffle: bool = True,
        seed: int = 0,
        sharding=None,
        repeat: int = 1,
        n_minibatches: Optional[int] = None,
    ) -> PPORolloutBatch:
        """All minibatches of one buffer pass as a single [n_mb*repeat, B,
        ...] pytree — the input of the fused (one-dispatch) train phase,
        scanned on device instead of dispatched per minibatch.

        ``repeat`` duplicates each minibatch consecutively (PPO's
        ``ppo_epochs`` inner updates on the same minibatch), which keeps the
        fused phase a flat scan of one train-step body — far cheaper to
        compile than a nested/unrolled loop. ``sharding`` should be the
        mesh's ``stacked_batch_sharding`` so each scan slice lands with the
        train step's expected batch sharding.
        """
        full = self.full
        n = full.batch_size
        n_mb = n // batch_size
        if n_mb == 0:
            raise ValueError(f"buffer smaller than one minibatch ({n} < {batch_size})")
        if n_minibatches is not None:
            # caller-fixed pass size (learn() sizes every pass from the
            # PLANNED rollout count so step accounting agrees across the
            # streamed and fused paths even when a non-dividing final
            # chunk over-collected the buffer)
            n_mb = min(n_mb, n_minibatches)
        order = np.arange(n)
        if shuffle:
            np.random.default_rng(seed).shuffle(order)
        idx = order[: n_mb * batch_size].reshape(n_mb, 1, batch_size)
        idx = np.broadcast_to(idx, (n_mb, repeat, batch_size)).reshape(
            n_mb * repeat, batch_size
        )
        mbs = full.select(jnp.asarray(idx))  # leaves gain a leading dim
        if sharding is not None:
            mbs = jax.device_put(mbs, sharding)
        return mbs
