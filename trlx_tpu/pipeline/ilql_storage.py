"""ILQL rollout storage: padded offline experience arrays.

Re-design of ``ILQLRolloutStorage`` (``trlx/pipeline/offline_pipeline.py:57-112``):
the reference keeps six parallel lists of per-sample tensors and pads at
collate; here everything is padded once into one :class:`ILQLBatch` of
static-shape arrays, and minibatches are device gathers (same pattern as the
PPO buffer).
"""

from __future__ import annotations

from typing import Iterator, List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from trlx_tpu.data.ilql_types import ILQLBatch
from trlx_tpu.pipeline import BaseRolloutStore


def build_ilql_batch(
    token_lists: Sequence[Sequence[int]],
    action_starts: Sequence[int],
    rewards_per_sample: Sequence[Sequence[float]],
    pad_token_id: int = 0,
    max_length: int | None = None,
) -> ILQLBatch:
    """Pack tokenized samples into a padded ILQLBatch.

    For a sample of length L with actions starting at token index ``s``
    (i.e. tokens ``s..L-1`` are the response/actions):
    - ``actions_ixs``: hidden-state indices ``s-1 .. L-2`` (the state *before*
      each action token);
    - ``states_ixs``: ``s-1 .. L-1`` (actions_ixs + final state);
    - ``dones``: 1 for every state except the final one (0 = terminal), the
      reference's convention (`offline_orchestrator.py:28-49`).
    """
    n = len(token_lists)
    T = max_length or max(len(t) for t in token_lists)
    A = max(len(t) - max(s, 1) for t, s in zip(token_lists, action_starts))
    A = max(A, 1)
    S = A + 1

    input_ids = np.full((n, T), pad_token_id, np.int32)
    attention_mask = np.zeros((n, T), np.int32)
    rewards = np.zeros((n, A), np.float32)
    actions_ixs = np.zeros((n, A), np.int32)
    states_ixs = np.zeros((n, S), np.int32)
    dones = np.zeros((n, S), np.int32)
    actions_mask = np.zeros((n, A), np.int32)

    for i, (toks, s, rs) in enumerate(
        zip(token_lists, action_starts, rewards_per_sample)
    ):
        toks = list(toks)[:T]
        L = len(toks)
        s = max(min(s, L - 1), 1)
        input_ids[i, :L] = toks
        attention_mask[i, :L] = 1
        n_actions = L - s
        ixs = np.arange(s - 1, L - 1)
        actions_ixs[i, :n_actions] = ixs
        # pad action indices by repeating the last (masked out of the loss)
        actions_ixs[i, n_actions:] = ixs[-1] if n_actions else 0
        states_ixs[i, : n_actions + 1] = np.arange(s - 1, L)
        states_ixs[i, n_actions + 1 :] = L - 1
        dones[i, :n_actions] = 1  # all but final state non-terminal
        actions_mask[i, :n_actions] = 1
        rs = list(rs)
        if len(rs) > n_actions > 0:
            # truncation dropped trailing actions: fold their rewards onto
            # the last kept action so the total return is preserved
            tail = float(np.sum(rs[n_actions - 1 :]))
            rs = rs[: n_actions - 1] + [tail]
        rewards[i, : len(rs)] = rs

    return ILQLBatch(
        input_ids=jnp.asarray(input_ids),
        attention_mask=jnp.asarray(attention_mask),
        rewards=jnp.asarray(rewards),
        states_ixs=jnp.asarray(states_ixs),
        actions_ixs=jnp.asarray(actions_ixs),
        dones=jnp.asarray(dones),
        actions_mask=jnp.asarray(actions_mask),
    )


class ILQLRolloutStorage(BaseRolloutStore):
    """Holds one packed ILQLBatch; serves sharded shuffled minibatches."""

    def __init__(self, batch: ILQLBatch):
        self.batch = batch

    def push(self, exps) -> None:
        raise NotImplementedError("offline storage is built once")

    def __len__(self) -> int:
        return len(self.batch)

    def create_loader(
        self,
        batch_size: int,
        shuffle: bool = True,
        seed: int = 0,
        sharding=None,
    ) -> Iterator[ILQLBatch]:
        n = len(self)
        order = np.arange(n)
        if shuffle:
            np.random.default_rng(seed).shuffle(order)
        for start in range(0, n - batch_size + 1, batch_size):
            idx = jnp.asarray(order[start : start + batch_size])
            mb = self.batch.select(idx)
            if sharding is not None:
                mb = jax.device_put(mb, sharding)
            yield mb

    def epoch_order(self, batch_size: int, shuffle: bool = True, seed: int = 0):
        """Shuffled sample order for one epoch, truncated to whole
        minibatches — index source for chunked fused training scans."""
        n = len(self)
        order = np.arange(n)
        if shuffle:
            np.random.default_rng(seed).shuffle(order)
        n_mb = n // batch_size
        return order[: n_mb * batch_size].reshape(n_mb, batch_size)

    def stacked_slice(self, order_rows: np.ndarray, sharding=None) -> ILQLBatch:
        """Gather minibatch rows [k, B] into a stacked [k, B, ...] pytree
        (the input of one fused training scan)."""
        mbs = self.batch.select(jnp.asarray(order_rows))
        if sharding is not None:
            mbs = jax.device_put(mbs, sharding)
        return mbs
