"""Version-compat shims for JAX APIs that moved or renamed.

The framework targets current JAX, but must also run on the 0.4.x line
(the CI/test image): ``shard_map`` graduated from
``jax.experimental.shard_map`` to ``jax.shard_map``, and its replication
check kwarg renamed ``check_rep`` -> ``check_vma``. Import ``shard_map``
from here instead of from jax directly; the shim accepts the modern
``check_vma`` spelling and translates for older jaxlibs.
"""

from __future__ import annotations

import inspect

try:  # jax >= 0.6
    from jax import shard_map as _shard_map
except ImportError:  # jax 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map

# Newer jax replication checking (check_vma) infers varying-axes through
# psum; 0.4.x's check_rep is stricter and rejects some valid out_specs —
# call sites may key the check on this flag.
HAS_CHECK_VMA = "check_vma" in inspect.signature(_shard_map).parameters
_HAS_CHECK_VMA = HAS_CHECK_VMA


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` with the modern keyword surface on any jax."""
    kwargs = {}
    if check_vma is not None:
        kwargs["check_vma" if _HAS_CHECK_VMA else "check_rep"] = check_vma
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )


def pallas_tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams`` (modern name) / ``TPUCompilerParams``
    (jax 0.4.x) — same fields, renamed class."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams"
    )
    return cls(**kwargs)
