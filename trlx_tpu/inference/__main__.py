"""Serving smoke: ``python -m trlx_tpu.inference --smoke``.

The CI ``serving-smoke`` job's entry point (code_quality.yml): build the
tiny harness policy, save a real trainer checkpoint, load it through
:class:`~trlx_tpu.inference.server.InferenceServer` (no trainer in the
serving process path), submit a prompt batch, and assert every request
completes with zero health events. Prints one JSON line with the
completion lengths and the engine's occupancy stats so the job log shows
what the engine actually did.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile


def _force_cpu_platform() -> None:
    if "jax" in sys.modules:
        return
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()


def serving_smoke(mesh=None, n_prompts: int = 6) -> int:
    import numpy as np

    from trlx_tpu.analysis import harness
    from trlx_tpu.data.configs import TRLConfig
    from trlx_tpu.inference.server import InferenceServer
    from trlx_tpu.utils.checkpoint import save_checkpoint

    # a real checkpoint round-trip: the smoke must exercise the same
    # load path a served production policy takes
    cfg = harness.tiny_config_dict("ppo", mesh=mesh)
    from trlx_tpu.trainer.ppo_trainer import PPOTrainer

    trainer = PPOTrainer(TRLConfig.from_dict(cfg))
    ckpt = tempfile.mkdtemp(prefix="serving_smoke_ckpt_")
    save_checkpoint(ckpt, trainer.state, metadata={}, step=1)
    del trainer

    scfg = harness.tiny_config_dict("ppo", mesh=mesh)
    scfg["train"]["rollout"] = {
        "slots": 4, "admit_width": 2, "harvest_width": 2, "block_size": 4,
    }
    # CPU-tier SLO budgets: queue waits here include jit COMPILE walls
    # (seconds), which production latency never pays — a tight default
    # budget would trip slo-breach on a perfectly healthy run
    scfg["train"]["serving"] = {
        "slo_classes": {"standard": {"queue_wait_budget_ms": 120000}},
    }
    server = InferenceServer(TRLConfig.from_dict(scfg), checkpoint_dir=ckpt)

    rng = np.random.default_rng(0)
    prompts = [
        list(rng.integers(1, 30, int(rng.integers(2, 8))))
        for _ in range(n_prompts)
    ]
    ids = server.submit(prompts)
    results = server.wait(ids)

    failures = []
    for rid in ids:
        out = results.get(rid)
        if out is None or out["length"] < 1:
            failures.append(rid)
    events = server.health_events
    record = {
        "completed": len(ids) - len(failures),
        "submitted": len(ids),
        "lengths": [results[r]["length"] for r in ids if r in results],
        "health_events": [ev.to_dict() for ev in events],
        # per-request latency histograms (docs/observability.md,
        # "Serving metrics"): queue wait / prefill / TTFT / per-token
        # decode / e2e summaries — the CI job asserts these keys exist
        # with nonzero counts in the JSON artifact
        "serving_metrics": server.metrics(),
        **server.stats(),
    }
    print(json.dumps(record))
    if failures:
        print(f"serving-smoke FAIL: requests {failures} incomplete",
              file=sys.stderr)
        return 1
    if events:
        print(f"serving-smoke FAIL: {len(events)} health events on a "
              "clean run", file=sys.stderr)
        return 1
    from trlx_tpu import telemetry
    from trlx_tpu.inference.server import SERVE_HISTOGRAMS

    if telemetry.get_metrics().enabled:
        missing = [
            k for k in SERVE_HISTOGRAMS
            if not record["serving_metrics"].get(k, {}).get("count")
        ]
        if missing:
            print(f"serving-smoke FAIL: request-latency histograms "
                  f"{missing} missing/empty", file=sys.stderr)
            return 1
    else:
        # TRLX_TELEMETRY=0 (or non-rank-0): histograms are legitimately
        # absent — telemetry off is the operator's choice, not a wiring
        # regression; the completion/health gates above still hold
        print("serving-smoke: metrics registry disabled — skipping "
              "request-latency key check", file=sys.stderr)
    # run-ledger recording (docs/observability.md "Run ledger"): with
    # $TRLX_RUN_LEDGER set, each smoke appends a manifest — the CI
    # perf-budget job records two and diffs them via --compare
    if os.environ.get("TRLX_RUN_LEDGER"):
        from trlx_tpu.telemetry.run_ledger import (
            append_manifest,
            build_manifest,
            numeric_payload,
        )

        append_manifest(
            build_manifest("serving-smoke", payload=numeric_payload(record))
        )
    print("serving-smoke PASS: all requests completed, zero health events",
          file=sys.stderr)
    return 0


def multi_tenant_smoke(mesh=None, span_log=None) -> int:
    """The serving-tier QoS smoke (docs/serving.md; CI serving-smoke
    job, multi-tenant step). One CPU run must demonstrate:

    - **priority admission**: a high-priority tenant's requests,
      submitted AFTER a low-priority tenant's, complete strictly ahead
      of them (the slot pool is smaller than the request count, so
      ordering is a scheduling decision, not an accident);
    - **quota without starvation**: the low-priority tenant is
      token-bucket-throttled (observable throttled rounds) yet every
      one of its requests still completes;
    - **streamed TTFT < wait-for-harvest TTFT**: the first streamed
      token of a ``stream=True`` request arrives strictly before the
      same request's harvested result exists;
    - **prefix sharing**: a shared system-prompt prefix across tenants
      yields a nonzero ``engine/prefix_hit_rate``;
    - **per-tenant metrics**: ``serve/*[tenant=...]`` histogram keys
      land in the artifact with nonzero counts;
    - **request tracing**: every completed request emitted a closed
      ``serve/request`` span chain and the span ring dropped NOTHING
      (an evicting ring silently truncates traces — the assert is the
      capacity canary for telemetry.ring_size);
    - **chunked prefill**: the scenario runs with
      ``rollout.prefill_chunk`` enabled and a per-pump chunk budget
      (``prefill_chunks_per_pump`` — Sarathi-style stall-free
      admission), and must report ``engine/prefill_chunks > 0`` while
      staying bitwise-served (the parity contract is pinned in
      tests/test_chunked_prefill.py; here the gate is that the chunked
      serving path carries real multi-tenant traffic cleanly);
    - **speculative decoding**: the scenario serves through the
      trie-drafted spec path (``rollout.spec_decode`` with the
      ``drafter: trie`` wired to the shared-prefix pool), must report
      ``engine/spec_accept_rate > 0``, and a spec-off rerun over the
      same prompts must reproduce every served row bitwise (the verify
      step's acceptance contract, end to end);
    - **zero health events** on this clean run.

    ``span_log`` exports the whole span stream (phase + request spans
    and counter tracks, one Perfetto JSONL) — the CI job feeds it to
    ``python -m trlx_tpu.telemetry --trace-report``.
    """
    import numpy as np

    from trlx_tpu import telemetry
    from trlx_tpu.analysis import harness
    from trlx_tpu.data.configs import TRLConfig
    from trlx_tpu.inference.server import InferenceServer

    scfg = harness.tiny_config_dict("ppo", mesh=mesh)
    # near-greedy decode with a longer budget: random-init generation
    # falls into short loops the trie/n-gram drafter locks onto, so the
    # spec path sees real acceptance (same trick as ab_spec.py)
    scfg["method"]["gen_kwargs"].update(
        {"temperature": 0.05, "max_new_tokens": 16, "min_new_tokens": 8}
    )
    scfg["train"]["rollout"] = {
        # serving ignores the trainer-side engine choice, but
        # spec_decode's config validation pins it to "continuous"
        "engine": "continuous",
        "slots": 4, "admit_width": 2, "harvest_width": 2, "block_size": 4,
        # chunked prefill, serving tier: admission prefill runs as
        # need-gated prompt-column chunks, at most one chunk forward
        # per pump (stall-free admission under bursts)
        "prefill_chunk": 4, "prefill_chunks_per_pump": 1,
        # speculative decoding through the shared-prefix trie drafter
        # (docs/inference.md "Speculative decoding")
        "spec_decode": {"enabled": True, "max_draft": 4, "drafter": "trie"},
    }
    serving_cfg = {
        "prefix_cache_blocks": 16,
        # generous CPU-tier budgets (queue waits include compile
        # walls); the slo-breach detector is unit-tested with tight
        # budgets in tests/test_serving.py
        "slo_classes": {
            "interactive": {"queue_wait_budget_ms": 120000},
            "standard": {"queue_wait_budget_ms": 120000},
        },
        "tenants": {
            "gold": {"priority": 10, "slo_class": "interactive"},
            # burst covers ONE request's cost (Q + R tokens), the
            # rate refills roughly two requests/second: bronze is
            # throttled to a trickle but never starves
            "bronze": {
                "priority": 0, "rate": 60.0, "burst": 26.0,
                "slo_class": "standard",
            },
        },
    }
    server = InferenceServer(TRLConfig.from_dict(scfg), serving=serving_cfg)
    Q, R = server.query_length, server.engine.R
    rng = np.random.default_rng(0)
    system_prefix = [5, 6, 7, 8]  # shared across BOTH tenants
    def make_prompts(n):
        # cyclic two-token tails: every suffix recurs, so the drafter
        # has n-gram matches from the first decode step
        out = []
        for _ in range(n):
            a, b = (int(x) for x in rng.integers(1, 30, 2))
            tail = list(np.tile([a, b], Q))[: Q - len(system_prefix)]
            out.append(system_prefix + tail)
        return out

    bronze_prompts = make_prompts(4)
    gold_prompts = make_prompts(4)
    stream_prompts = make_prompts(1)
    # low-priority bronze submits FIRST; gold afterwards — priority
    # admission must still serve gold ahead of bronze
    bronze = server.submit(bronze_prompts, tenant="bronze")
    gold = server.submit(gold_prompts, tenant="gold")
    stream_rid = server.submit(
        stream_prompts, tenant="gold", stream=True
    )[0]

    # streamed TTFT: pull the first token through the stream iterator
    # (it pumps the serving loop); wait-for-harvest TTFT: keep pumping
    # until the SAME request's harvested result exists
    t0 = telemetry.monotonic()
    first_token = next(server.stream(stream_rid))
    ttft_stream_ms = (telemetry.monotonic() - t0) * 1000.0
    result_at_first_token = server.poll(stream_rid)
    while server.poll(stream_rid) is None:
        server._pump_once()
    ttft_harvest_ms = (telemetry.monotonic() - t0) * 1000.0

    server.flush()
    # engine rows are allocated in admission-feed order: the scheduler's
    # decision trail (captured before wait() pops the bookkeeping)
    admit_pos = dict(server._req_row)
    results = server.wait(bronze + gold + [stream_rid])

    order = server.completion_order
    rank = {rid: i for i, rid in enumerate(order)}
    gold_ranks = [rank[r] for r in gold + [stream_rid]]
    bronze_ranks = [rank[r] for r in bronze]
    gold_rows = [admit_pos[r] for r in gold + [stream_rid]]
    bronze_rows = [admit_pos[r] for r in bronze]
    stats = server.stats()
    metrics = server.metrics()
    events = server.health_events

    tracer = telemetry.get_tracer()
    request_spans = (
        [s for s in tracer.spans() if s.name == "serve/request"]
        if tracer.enabled
        else []
    )

    # spec-off rerun: the same config with spec_decode disabled, the
    # same prompts in the same submission order (=> identical draw
    # positions => identical per-row keys), so every served row must be
    # BITWISE what the one-token loop produces — the verify step's
    # acceptance contract, exercised end-to-end through real
    # multi-tenant traffic
    import copy

    scfg_off = copy.deepcopy(scfg)
    scfg_off["train"]["rollout"].pop("spec_decode")
    server_off = InferenceServer(
        TRLConfig.from_dict(scfg_off), serving=serving_cfg
    )
    off_bronze = server_off.submit(bronze_prompts, tenant="bronze")
    off_gold = server_off.submit(gold_prompts, tenant="gold")
    off_stream = server_off.submit(stream_prompts, tenant="gold")
    results_off = server_off.wait(off_bronze + off_gold + off_stream)
    spec_parity = all(
        results[a]["tokens"] == results_off[b]["tokens"]
        for a, b in zip(
            bronze + gold + [stream_rid],
            off_bronze + off_gold + off_stream,
        )
    )

    record = {
        "spec_drafter": type(server.engine.spec_drafter).__name__,
        "spec_off_row_parity": bool(spec_parity),
        "completion_order_tenants": [
            "gold" if r in set(gold + [stream_rid]) else "bronze"
            for r in order
        ],
        "gold_ranks": gold_ranks,
        "bronze_ranks": bronze_ranks,
        "gold_admission_rows": gold_rows,
        "bronze_admission_rows": bronze_rows,
        "first_streamed_token": int(first_token),
        "ttft_stream_ms": round(ttft_stream_ms, 3),
        "ttft_harvest_ms": round(ttft_harvest_ms, 3),
        "scheduler_throttled_rounds": stats["scheduler/throttled_rounds"],
        "prefix_hit_rate": stats["engine/prefix_hit_rate"],
        "prefix_blocks_saved": stats["engine/prefix_blocks_saved"],
        "prefill_chunks": stats["engine/prefill_chunks"],
        "prefill_cols_skipped": stats["engine/prefill_cols_skipped"],
        "prefill_flops_saved": stats["engine/prefill_flops_saved"],
        "released_placeholders": stats["engine/released"],
        "request_spans": len(request_spans),
        "spans_dropped": int(tracer.dropped),
        "health_events": [ev.to_dict() for ev in events],
        "serving_metrics": metrics,
        # the full engine/scheduler counter row (engine/prefix_hit_rate,
        # engine/released, scheduler/*) — the CI job asserts on these
        # keys in the artifact, same as the single-tenant smoke
        **stats,
    }
    print(json.dumps(record))
    if span_log and tracer.enabled:
        n_events = telemetry.export_chrome_jsonl(
            span_log,
            tracer.spans(),
            counters=telemetry.get_metrics().gauge_series(),
        )
        print(
            f"mt-smoke: exported {n_events} trace events to {span_log}",
            file=sys.stderr,
        )

    failures = []
    if len(results) != 9 or any(
        results[r]["length"] < 1 for r in results
    ):
        failures.append("not every request completed")
    if max(gold_rows) > min(bronze_rows):
        failures.append(
            "priority inversion: a bronze request was ADMITTED before "
            "the last gold request despite submitting earlier with "
            "lower priority"
        )
    if sorted(gold_ranks[:4]) != list(range(4)):
        failures.append(
            "the first completions were not the first gold wave"
        )
    # single-process CPU smoke: these are host-side scheduler/engine
    # counters (never device collectives), so branching cannot desync
    if stats["scheduler/throttled_rounds"] < 1:  # tpu-lint: disable=host-branch
        failures.append("bronze quota never throttled")
    if result_at_first_token is not None:
        failures.append("harvest completed before the first streamed token")
    if not ttft_stream_ms < ttft_harvest_ms:
        failures.append(
            f"streamed TTFT {ttft_stream_ms:.1f}ms not below "
            f"wait-for-harvest TTFT {ttft_harvest_ms:.1f}ms"
        )
    if not stats["engine/prefix_hit_rate"] > 0:  # tpu-lint: disable=host-branch
        failures.append("prefix sharing produced zero hits")
    if not stats["engine/prefill_chunks"] > 0:  # tpu-lint: disable=host-branch
        failures.append(
            "chunked prefill never ran (engine/prefill_chunks == 0) "
            "despite rollout.prefill_chunk being set"
        )
    if not stats["engine/spec_accept_rate"] > 0:  # tpu-lint: disable=host-branch
        failures.append(
            "spec decode accepted nothing (engine/spec_accept_rate == 0) "
            "despite rollout.spec_decode being enabled"
        )
    if not spec_parity:
        failures.append(
            "spec-on served rows are not bitwise-identical to the "
            "spec-off rerun"
        )
    if server_off.health_events:
        failures.append(
            f"{len(server_off.health_events)} health events on the "
            "spec-off rerun"
        )
    if telemetry.get_metrics().enabled:
        for tenant in ("gold", "bronze"):
            key = f"serve/queue_wait_ms[tenant={tenant}]"
            if not metrics.get(key, {}).get("count"):
                failures.append(f"missing per-tenant histogram {key}")
    if tracer.enabled:
        # trace completeness + capacity canary: one closed request-span
        # chain per completed request, zero ring evictions (a dropped
        # span truncates a trace silently — raise telemetry.ring_size)
        if len(request_spans) < len(results):
            failures.append(
                f"request tracing incomplete: {len(request_spans)} "
                f"serve/request spans for {len(results)} completed "
                "requests"
            )
        if telemetry.warn_on_span_drops(tracer):
            failures.append(
                f"span ring dropped {tracer.dropped} spans — raise "
                "telemetry.ring_size / TRLX_TELEMETRY_RING"
            )
    if events:
        failures.append(f"{len(events)} health events on a clean run")
    if failures:
        for f in failures:
            print(f"mt-smoke FAIL: {f}", file=sys.stderr)
        return 1
    print(
        "mt-smoke PASS: priority ordering, quota-throttle-no-starve, "
        f"streamed TTFT {ttft_stream_ms:.0f}ms < harvest "
        f"{ttft_harvest_ms:.0f}ms, prefix hit rate "
        f"{stats['engine/prefix_hit_rate']:.2f}, "
        f"{stats['engine/prefill_chunks']:.0f} prefill chunks "
        f"({stats['engine/prefill_cols_skipped']:.0f} cols skipped), "
        f"spec accept rate {stats['engine/spec_accept_rate']:.2f} "
        "(bitwise vs spec-off), zero health events",
        file=sys.stderr,
    )
    return 0


def main(argv=None) -> int:
    _force_cpu_platform()
    parser = argparse.ArgumentParser(
        prog="python -m trlx_tpu.inference",
        description="continuous-batching serving utilities",
    )
    parser.add_argument(
        "--smoke", action="store_true",
        help="run the serving smoke: checkpoint round-trip through "
        "InferenceServer, assert completions + zero health events",
    )
    parser.add_argument(
        "--mt-smoke", action="store_true",
        help="run the multi-tenant QoS smoke: priority ordering, "
        "quota throttling without starvation, streamed TTFT below "
        "harvest TTFT, nonzero prefix-sharing hit rate, per-tenant "
        "serve/* histograms, complete request traces with zero span "
        "drops, zero health events",
    )
    parser.add_argument(
        "--span-log", metavar="PATH", default=None,
        help="with --mt-smoke: export the run's span stream (phase + "
        "per-request spans + counter tracks) as Perfetto JSONL — the "
        "input of `python -m trlx_tpu.telemetry --trace-report`",
    )
    args = parser.parse_args(argv)
    if args.smoke:
        return serving_smoke()
    if args.mt_smoke:
        return multi_tenant_smoke(span_log=args.span_log)
    parser.print_help()
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
