"""Serving smoke: ``python -m trlx_tpu.inference --smoke``.

The CI ``serving-smoke`` job's entry point (code_quality.yml): build the
tiny harness policy, save a real trainer checkpoint, load it through
:class:`~trlx_tpu.inference.server.InferenceServer` (no trainer in the
serving process path), submit a prompt batch, and assert every request
completes with zero health events. Prints one JSON line with the
completion lengths and the engine's occupancy stats so the job log shows
what the engine actually did.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile


def _force_cpu_platform() -> None:
    if "jax" in sys.modules:
        return
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()


def serving_smoke(mesh=None, n_prompts: int = 6) -> int:
    import numpy as np

    from trlx_tpu.analysis import harness
    from trlx_tpu.data.configs import TRLConfig
    from trlx_tpu.inference.server import InferenceServer
    from trlx_tpu.utils.checkpoint import save_checkpoint

    # a real checkpoint round-trip: the smoke must exercise the same
    # load path a served production policy takes
    cfg = harness.tiny_config_dict("ppo", mesh=mesh)
    from trlx_tpu.trainer.ppo_trainer import PPOTrainer

    trainer = PPOTrainer(TRLConfig.from_dict(cfg))
    ckpt = tempfile.mkdtemp(prefix="serving_smoke_ckpt_")
    save_checkpoint(ckpt, trainer.state, metadata={}, step=1)
    del trainer

    scfg = harness.tiny_config_dict("ppo", mesh=mesh)
    scfg["train"]["rollout"] = {
        "slots": 4, "admit_width": 2, "harvest_width": 2, "block_size": 4,
    }
    server = InferenceServer(TRLConfig.from_dict(scfg), checkpoint_dir=ckpt)

    rng = np.random.default_rng(0)
    prompts = [
        list(rng.integers(1, 30, int(rng.integers(2, 8))))
        for _ in range(n_prompts)
    ]
    ids = server.submit(prompts)
    results = server.wait(ids)

    failures = []
    for rid in ids:
        out = results.get(rid)
        if out is None or out["length"] < 1:
            failures.append(rid)
    events = server.health_events
    record = {
        "completed": len(ids) - len(failures),
        "submitted": len(ids),
        "lengths": [results[r]["length"] for r in ids if r in results],
        "health_events": [ev.to_dict() for ev in events],
        # per-request latency histograms (docs/observability.md,
        # "Serving metrics"): queue wait / prefill / TTFT / per-token
        # decode / e2e summaries — the CI job asserts these keys exist
        # with nonzero counts in the JSON artifact
        "serving_metrics": server.metrics(),
        **server.stats(),
    }
    print(json.dumps(record))
    if failures:
        print(f"serving-smoke FAIL: requests {failures} incomplete",
              file=sys.stderr)
        return 1
    if events:
        print(f"serving-smoke FAIL: {len(events)} health events on a "
              "clean run", file=sys.stderr)
        return 1
    from trlx_tpu import telemetry
    from trlx_tpu.inference.server import SERVE_HISTOGRAMS

    if telemetry.get_metrics().enabled:
        missing = [
            k for k in SERVE_HISTOGRAMS
            if not record["serving_metrics"].get(k, {}).get("count")
        ]
        if missing:
            print(f"serving-smoke FAIL: request-latency histograms "
                  f"{missing} missing/empty", file=sys.stderr)
            return 1
    else:
        # TRLX_TELEMETRY=0 (or non-rank-0): histograms are legitimately
        # absent — telemetry off is the operator's choice, not a wiring
        # regression; the completion/health gates above still hold
        print("serving-smoke: metrics registry disabled — skipping "
              "request-latency key check", file=sys.stderr)
    # run-ledger recording (docs/observability.md "Run ledger"): with
    # $TRLX_RUN_LEDGER set, each smoke appends a manifest — the CI
    # perf-budget job records two and diffs them via --compare
    if os.environ.get("TRLX_RUN_LEDGER"):
        from trlx_tpu.telemetry.run_ledger import (
            append_manifest,
            build_manifest,
            numeric_payload,
        )

        append_manifest(
            build_manifest("serving-smoke", payload=numeric_payload(record))
        )
    print("serving-smoke PASS: all requests completed, zero health events",
          file=sys.stderr)
    return 0


def main(argv=None) -> int:
    _force_cpu_platform()
    parser = argparse.ArgumentParser(
        prog="python -m trlx_tpu.inference",
        description="continuous-batching serving utilities",
    )
    parser.add_argument(
        "--smoke", action="store_true",
        help="run the serving smoke: checkpoint round-trip through "
        "InferenceServer, assert completions + zero health events",
    )
    args = parser.parse_args(argv)
    if args.smoke:
        return serving_smoke()
    parser.print_help()
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
