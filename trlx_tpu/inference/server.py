"""Multi-tenant serving over the continuous-batching engine.

The request tier of the ROADMAP "millions of users" direction
(docs/serving.md): :class:`InferenceServer` is rebuilt on the
:mod:`trlx_tpu.serving` subsystem —

- **QoS scheduling**: every ``submit`` becomes a typed
  :class:`~trlx_tpu.serving.scheduler.Request` (tenant, priority, SLO
  class, deadline) in the :class:`~trlx_tpu.serving.scheduler.
  QoSScheduler`'s per-tenant queues; vacated decode slots are fed by
  priority-with-aging order under per-tenant token-bucket quotas, with
  SLO pressure read back from the ``serve/*`` latency histograms.
- **Cross-request prefix sharing**: with
  ``serving.prefix_cache_blocks > 0`` the engine carries a shared KV
  pool and the :class:`~trlx_tpu.serving.prefix_cache.PrefixBlockPool`
  maps common prompt prefixes (system prompts, few-shot headers) onto
  refcounted shared blocks — published once, gathered read-only by
  every later request with the same leading columns (bitwise-exact;
  docs/serving.md "Prefix sharing").
- **Streaming decode**: ``submit(..., stream=True)`` opens a bounded
  per-request token queue fed by the engine's per-decode-step tap —
  tokens arrive the step they exist, so TTFT decouples from
  harvest-group completion.
- The old padding waste is gone: partial final harvest groups pad with
  *placeholder* rows that are force-finished on admission (one decode
  step each), not decoded to their full token budget.

Request lifecycle: ``submit`` left-pads, types, and enqueues with the
scheduler (host); the serving pump moves scheduler picks into engine
slots as they vacate; ``flush``/``wait`` run the pump to completion;
results are retained until ``pop_result``/``wait`` hands them out.
A :class:`~trlx_tpu.telemetry.health.HealthMonitor` watches per-group
generation stats (non-finite logprobs/values trip ``nan-precursor``)
and the per-tenant SLO ratios (queue-wait p95 over the class budget
trips ``slo-breach``); the CI ``serving-smoke`` jobs assert clean runs
stay at zero events.
"""

from __future__ import annotations

import itertools
import time
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from trlx_tpu.data.configs import TRLConfig
from trlx_tpu.serving.scheduler import DEFAULT_TENANT, tenant_metric_key

#: the per-request latency histograms every served request feeds
#: (docs/observability.md "Serving metrics") — the series QoS
#: scheduling gates on; the CI serving-smoke asserts these keys
SERVE_HISTOGRAMS = (
    "serve/queue_wait_ms",
    "serve/prefill_ms",
    "serve/ttft_ms",
    "serve/decode_per_token_ms",
    "serve/e2e_ms",
)


def observe_request_metrics(
    registry,
    timing: Dict[str, float],
    tokens: int,
    tenant: Optional[str] = None,
) -> None:
    """Feed one completed request's engine timing decomposition
    (:meth:`~trlx_tpu.inference.engine.ContinuousBatchingEngine.
    pop_request_timing`) into the latency histograms: queue wait,
    prefill, time-to-first-token, per-token decode (``decode_ms`` over
    the generated token count), end-to-end. With ``tenant`` given, each
    observation ALSO lands in the tenant-labeled twin
    (``serve/queue_wait_ms[tenant=acme]``), so per-tenant SLOs are
    assertable — not just aggregates."""
    values = {
        "serve/queue_wait_ms": timing.get("queue_wait_ms", 0.0),
        "serve/prefill_ms": timing.get("prefill_ms", 0.0),
        "serve/ttft_ms": timing.get("ttft_ms", 0.0),
        "serve/decode_per_token_ms": (
            timing.get("decode_ms", 0.0) / max(1, int(tokens))
        ),
        "serve/e2e_ms": timing.get("e2e_ms", 0.0),
    }
    for key, value in values.items():
        registry.histogram(key).observe(value)
        if tenant is not None:
            registry.histogram(tenant_metric_key(key, tenant)).observe(
                value
            )
    registry.counter("serve/requests_completed").inc()
    if tenant is not None:
        registry.counter(
            tenant_metric_key("serve/requests_completed", tenant)
        ).inc()


class InferenceServer:
    """Submit/poll multi-tenant batched generation against a loaded
    policy.

    :param config: :class:`TRLConfig` (or its dict form) — ``model``
        selects the architecture/checkpoint conversion, ``train.mesh``
        the device mesh, ``method.gen_kwargs`` the generation
        parameters, ``train.rollout`` the engine geometry (slots /
        admit_width / harvest_width / block_size; the ``engine`` field
        is ignored — serving is always continuous), ``train.serving``
        the QoS/prefix/streaming section
        (:class:`~trlx_tpu.serving.ServingConfig`).
    :param checkpoint_dir: optional trainer checkpoint directory
        (``utils/checkpoint``): the policy params are restored from the
        saved train state (optimizer state is read but discarded).
    :param params: optional explicit policy param pytree (overrides
        ``checkpoint_dir``).
    :param tokenizer: optional tokenizer for string prompts / decoded
        results (falls back to ``model.tokenizer_path``).
    :param serving: optional dict overriding ``train.serving``.
    """

    def __init__(
        self,
        config: Union[TRLConfig, Dict[str, Any]],
        checkpoint_dir: Optional[str] = None,
        params=None,
        tokenizer=None,
        seed: int = 0,
        serving: Optional[Dict[str, Any]] = None,
    ):
        import jax
        import jax.numpy as jnp

        from trlx_tpu.inference import RolloutEngineConfig
        from trlx_tpu.inference.engine import ContinuousBatchingEngine
        from trlx_tpu.models.heads import CausalLMWithValueHead
        from trlx_tpu.ops.sampling import (
            GenerationConfig,
            validate_gen_config,
        )
        from trlx_tpu.parallel import make_mesh, make_partition_specs
        from trlx_tpu.serving import ServingConfig
        from trlx_tpu.serving.prefix_cache import PrefixBlockPool
        from trlx_tpu.serving.scheduler import build_scheduler
        from trlx_tpu.serving.streaming import StreamRouter
        from trlx_tpu.telemetry.health import HealthConfig, HealthMonitor
        from trlx_tpu.trainer.ppo_trainer import get_causal_arch

        if not isinstance(config, TRLConfig):
            config = TRLConfig.from_dict(config)
        self.config = config
        train = config.train
        self.mesh = make_mesh(train.mesh)
        if dict(self.mesh.shape).get("pp", 1) > 1:
            raise NotImplementedError(
                "InferenceServer serves under plain GSPMD; drop the pp "
                "mesh axis (pipeline decode is a trainer-path feature)"
            )

        self.family, self.model_config, init_params = get_causal_arch(config)
        self.model = CausalLMWithValueHead(
            self.model_config, backbone_cls=self.family.backbone_cls
        )

        self.tokenizer = tokenizer
        if tokenizer is None and config.model.tokenizer_path:
            from transformers import AutoTokenizer

            self.tokenizer = AutoTokenizer.from_pretrained(
                config.model.tokenizer_path, local_files_only=True
            )

        gen_kwargs = dict(config.method.gen_kwargs)
        self.gen_config = GenerationConfig.from_dict(gen_kwargs)
        validate_gen_config(
            self.gen_config,
            getattr(self.model_config, "vocab_size", None),
            provided=set(gen_kwargs),
        )
        self.query_length = train.seq_length

        # --- params: explicit > checkpoint > converted > from-scratch ---
        rng = jax.random.PRNGKey(seed)
        rng, init_rng = jax.random.split(rng)
        if params is None:
            params = self.model.init(
                init_rng, jnp.zeros((1, 8), jnp.int32)
            )["params"]
            if init_params is not None:
                params["transformer"] = init_params  # converted backbone
            if checkpoint_dir is not None:
                from trlx_tpu.utils.checkpoint import load_checkpoint

                # restore the checkpoint as saved (no abstract spec —
                # serving must not need the training run's optimizer
                # layout) and keep only the policy params
                state, _meta = load_checkpoint(checkpoint_dir, None)
                saved = state["params"] if isinstance(state, dict) else (
                    state.params
                )
                flat_live = jax.tree_util.tree_structure(params)
                flat_saved = jax.tree_util.tree_structure(saved)
                if flat_live != flat_saved:
                    raise ValueError(
                        f"checkpoint under {checkpoint_dir} holds a "
                        "different param structure than model config "
                        f"{type(self.model_config).__name__} builds — "
                        "check model.model_arch/model_type against the "
                        "training run"
                    )
                params = saved

        from jax.sharding import NamedSharding, PartitionSpec as P

        specs = make_partition_specs(
            params, self.mesh, self.family.partition_rules
        )
        self.param_shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s),
            specs,
            is_leaf=lambda x: isinstance(x, P),
        )
        self.params = jax.device_put(params, self.param_shardings)

        rollout = RolloutEngineConfig.from_dict(train.rollout)
        num_slots = rollout.slots or int(
            getattr(config.method, "chunk_size", 0) or train.batch_size
        )
        self.serving_config = ServingConfig.from_dict(
            serving if serving is not None else getattr(train, "serving", {})
        )

        def apply_fn(p, input_ids, attention_mask=None, position_ids=None,
                     cache=None, cache_index=None, last_only=False,
                     skip_heads=False):
            return self.model.apply(
                {"params": p},
                input_ids,
                attention_mask=attention_mask,
                position_ids=position_ids,
                cache=cache,
                cache_index=cache_index,
                last_only=last_only,
                skip_heads=skip_heads,
            )

        import functools

        spec = rollout.spec_decode
        spec_on = spec is not None and spec.enabled
        self.engine = ContinuousBatchingEngine(
            apply_fn=apply_fn,
            init_cache_fn=functools.partial(
                self.family.init_cache, self.model_config
            ),
            gen_config=self.gen_config,
            query_length=self.query_length,
            vocab_size=self.model_config.vocab_size,
            num_slots=num_slots,
            admit_width=rollout.admit_width,
            harvest_width=rollout.harvest_width,
            block_size=rollout.block_size,
            mesh=self.mesh,
            param_shardings=self.param_shardings,
            with_values=True,
            prefix_pool_blocks=self.serving_config.prefix_cache_blocks,
            stream_taps=True,
            prefill_chunk=rollout.prefill_chunk,
            prefill_chunks_per_pump=rollout.prefill_chunks_per_pump,
            spec_max_draft=spec.max_draft if spec_on else 0,
            spec_min_accept_ewma=(
                spec.min_accept_ewma if spec_on else 0.0
            ),
        )
        # fold_in consumes rng without a dangling split chain (the
        # key-lineage engine's key-discard rule)
        phase_key = jax.random.fold_in(rng, 7)
        self.engine.start_phase(self.params, phase_key)

        from trlx_tpu import telemetry

        # span-ring capacity (train.telemetry.ring_size): per-request
        # traces multiply span volume; size the ring before traffic
        telemetry.configure_from_dict(getattr(train, "telemetry", None))
        self._registry = telemetry.get_metrics()
        # request tracing (telemetry/request_trace.py): with the tracer
        # enabled the engine logs decode-step cadence and done marks so
        # every completed request emits a parented span chain; disabled
        # keeps the host loop's per-step cost at zero (NULL_SPAN contract)
        self.engine.trace_requests = telemetry.get_tracer().enabled
        self.scheduler = build_scheduler(
            self.serving_config, registry=self._registry
        )
        self.prefix_pool = (
            PrefixBlockPool(
                self.serving_config.prefix_cache_blocks,
                self.engine.block_size,
                self.engine.n_blocks,
            )
            if self.serving_config.prefix_cache_blocks > 0
            else None
        )
        if spec_on and spec.drafter == "trie" and self.engine.spec_max_draft:
            from trlx_tpu.serving.spec_drafter import TrieDrafter

            # rebind the engine's default per-row n-gram drafter to the
            # trie-backed one: the shared-prefix pool's published chains
            # become the global draft corpus (pool=None — sharing off —
            # keeps pure n-gram behavior)
            self.engine.spec_drafter = TrieDrafter(
                pool=self.prefix_pool,
                max_draft=self.engine.spec_max_draft,
                min_accept_ewma=spec.min_accept_ewma,
            )
        self._router = StreamRouter(
            maxlen=self.serving_config.stream_buffer
        )
        self.engine._admit_listener = self._on_admitted

        # generation-health watch: non-finite logprobs/values in a served
        # group trip nan-precursor, per-tenant queue-wait p95 over the
        # SLO budget trips slo-breach; zero events == healthy serving
        self.health_monitor = HealthMonitor(
            HealthConfig.from_dict({"enabled": True})
        )
        self._requests: Dict[int, Any] = {}  # request_id -> Request
        # trace-emission retention: Request refs (tenant/priority/trace
        # marks) kept until the row HARVESTS — pop_result may drop
        # _requests mid-flight, but an abandoned request's span chain
        # must still close when its row completes
        self._trace_reqs: Dict[int, Any] = {}
        self._plan_windows: Dict[int, Any] = {}  # rid -> (t0, t1)
        self._row_to_req: Dict[int, int] = {}  # engine row -> request_id
        self._req_row: Dict[int, int] = {}  # request_id -> engine row
        self._acquired: Dict[int, List[int]] = {}  # rid -> pool blocks
        self._published_by_row: Dict[int, List[int]] = {}
        self._streams: Dict[int, Any] = {}  # rid -> TokenStream
        self._results: Dict[int, Dict[str, Any]] = {}
        self._open: Dict[int, bool] = {}
        self._next_request = itertools.count()
        self.completion_order: List[int] = []
        self._groups_served = 0

    # ------------------------------ API -------------------------------- #

    @property
    def health_events(self) -> List[Any]:
        return list(self.health_monitor.events)

    def _encode(self, prompt) -> List[int]:
        if isinstance(prompt, str):
            if self.tokenizer is None:
                raise ValueError("string prompts require a tokenizer")
            return list(self.tokenizer.encode(prompt))
        return list(prompt)

    def _pad_prompt(self, toks: List[int], i: int):
        Q = self.query_length
        pad_id = self.gen_config.pad_token_id
        if not toks:
            raise ValueError(f"prompt {i} is empty")
        if len(toks) > Q:
            raise ValueError(
                f"prompt {i} has {len(toks)} tokens > seq_length={Q}"
            )
        ids = np.full((Q,), pad_id, np.int32)
        mask = np.zeros((Q,), np.int32)
        ids[Q - len(toks):] = toks  # left-pad, as the trainer does
        mask[Q - len(toks):] = 1
        return ids, mask

    def submit(
        self,
        prompts: Sequence[Any],
        tenant: str = DEFAULT_TENANT,
        priority: Optional[int] = None,
        slo_class: Optional[str] = None,
        deadline_ms: Optional[float] = None,
        stream: bool = False,
    ) -> List[int]:
        """Enqueue prompts (strings with a tokenizer, or token-id lists /
        arrays) with the QoS scheduler; returns request ids. Prompts
        longer than ``train.seq_length`` are refused (truncation would
        silently serve a different prompt).

        ``tenant``/``priority``/``slo_class``/``deadline_ms`` type the
        requests for admission (defaults inherit the tenant's
        ``train.serving.tenants`` entry); ``stream=True`` opens a
        per-request token stream (:meth:`stream`) fed per decode step.
        """
        from trlx_tpu import telemetry
        from trlx_tpu.serving.scheduler import Request
        from trlx_tpu.serving.streaming import TokenStream
        from trlx_tpu.telemetry.request_trace import mint_trace_id

        tenant_cfg = self.scheduler.tenant_config(tenant)
        prio = tenant_cfg.priority if priority is None else int(priority)
        slo = tenant_cfg.slo_class if slo_class is None else slo_class
        now = telemetry.monotonic()
        tracing = telemetry.get_tracer().enabled
        # build + validate the WHOLE batch before enqueueing anything:
        # a mid-batch refusal (over-long prompt, unadmittable cost)
        # must not orphan earlier requests whose ids the caller never
        # received
        reqs = []
        for i, p in enumerate(prompts):
            ids, mask = self._pad_prompt(self._encode(p), i)
            request_id = next(self._next_request)
            req = Request(
                request_id=request_id,
                tenant=tenant,
                prompt_ids=ids,
                prompt_mask=mask,
                priority=prio,
                slo_class=slo,
                max_tokens=self.engine.R,
                deadline=(
                    now + deadline_ms / 1000.0
                    if deadline_ms is not None
                    else None
                ),
                stream=bool(stream),
                cost=float(int(mask.sum()) + self.engine.R),
                submitted_at=now,
                trace_id=mint_trace_id(request_id),
            )
            self.scheduler.validate(req)
            reqs.append(req)
        rids = []
        for req in reqs:
            rid = req.request_id
            self.scheduler.submit(req)
            self._requests[rid] = req
            if tracing:
                self._trace_reqs[rid] = req
            self._open[rid] = True
            if stream:
                self._streams[rid] = TokenStream(
                    rid,
                    maxlen=self.serving_config.stream_buffer,
                    pump=self._pump_once,
                )
            rids.append(rid)
        return rids

    def stream(self, request_id: int):
        """The :class:`~trlx_tpu.serving.streaming.TokenStream` iterator
        of a ``stream=True`` request — pulls tokens per decode step,
        pumping the serving loop as needed."""
        s = self._streams.get(request_id)
        if s is None:
            raise KeyError(
                f"request {request_id} was not submitted with stream=True"
            )
        return s

    # --------------------------- serving pump --------------------------- #

    def _on_admitted(self, rows: List[int]) -> None:
        """Engine admit listener: newly published prefix blocks become
        readable for later admission groups (the publishing prefill has
        been dispatched — device order makes its writes land first)."""
        if self.prefix_pool is None:
            return
        for row in rows:
            published = self._published_by_row.pop(row, None)
            if published:
                self.prefix_pool.mark_ready(published)

    def _engine_submit(self, batch) -> None:
        """Move scheduler picks into the engine's admission queue."""
        from trlx_tpu import telemetry
        from trlx_tpu.utils.retry import retry_call

        tracing = telemetry.get_tracer().enabled
        n = len(batch)
        Q = self.query_length
        ids = np.zeros((n, Q), np.int32)
        mask = np.zeros((n, Q), np.int32)
        shared_maps = publish_maps = None
        plans = []
        for i, req in enumerate(batch):
            ids[i] = req.prompt_ids
            mask[i] = req.prompt_mask
            if self.prefix_pool is not None:
                t_plan = telemetry.monotonic() if tracing else 0.0
                plan = self.prefix_pool.plan_admission(
                    req.prompt_ids, req.prompt_mask,
                    eligible_blocks=Q // self.engine.block_size,
                )
                if tracing:
                    # prefix-plan overlay span of the request's trace
                    self._plan_windows[req.request_id] = (
                        t_plan, telemetry.monotonic()
                    )
                plans.append(plan)
        if plans:
            shared_maps = np.stack([p.shared_map for p in plans])
            publish_maps = np.stack([p.publish_map for p in plans])
        # admission is host-side bookkeeping, but it sits on the serving
        # request path — a transient failure (the engine.admit injection
        # site models one) retries with bounded backoff instead of
        # bouncing the request (docs/resilience.md)
        try:
            rows = retry_call(
                lambda: self.engine.submit(
                    ids,
                    mask,
                    shared_maps=shared_maps,
                    publish_maps=publish_maps,
                    submit_times=[req.submitted_at for req in batch],
                ),
                describe="inference-server admission",
            )
        except Exception:
            # permanent admission failure: roll the plans back, or the
            # acquired refcounts and never-ready publish blocks leak —
            # pinned forever (unevictable) and breaking every later
            # same-prefix trie walk
            if self.prefix_pool is not None:
                for plan in plans:
                    if plan.acquired:
                        self.prefix_pool.abandon(plan.acquired)
            for req in batch:
                self._plan_windows.pop(req.request_id, None)
            raise
        for i, (row, req) in enumerate(zip(rows, batch)):
            self._row_to_req[row] = req.request_id
            self._req_row[req.request_id] = row
            if self.engine.spec_drafter is not None:
                # tenant-scoped accept-rate EWMA: one tenant's
                # unpredictable text degrades that tenant's drafting,
                # not everyone's
                self.engine.spec_drafter.set_tenant(row, req.tenant)
            if plans:
                if plans[i].acquired:
                    self._acquired[req.request_id] = plans[i].acquired
                if plans[i].published:
                    self._published_by_row[row] = plans[i].published
            if req.stream:
                s = self._streams.get(req.request_id)
                if s is not None:
                    self._router.attach(row, s)

    def _submit_placeholders(self, n: int) -> None:
        """Pad the engine queue with ``n`` release-on-admission rows so
        the final partial harvest group fills WITHOUT decoding dummy
        rollouts to their full token budget (each placeholder costs one
        decode step — the PR-8 padding waste, fixed)."""
        Q = self.query_length
        ids = np.full((n, Q), self.gen_config.pad_token_id, np.int32)
        mask = np.zeros((n, Q), np.int32)
        ids[:, Q - 1] = self.gen_config.pad_token_id
        mask[:, Q - 1] = 1
        self.engine.submit(ids, mask, release=True)

    def _pump_once(self) -> bool:
        """One serving iteration: feed the engine from the scheduler,
        advance decode a step, land any harvested groups. Returns
        whether anything progressed.

        When the scheduler has nothing more to feed and the in-flight
        rows cannot fill the last fixed-width harvest group, the pump
        pads with release-on-admission placeholders — so a lone
        streaming request (or a trailing partial group) drains without
        waiting for traffic that may never come."""
        engine = self.engine
        free = engine.free_capacity
        if free > 0 and self.scheduler.has_work():
            batch = self.scheduler.next_batch(free)
            if batch:
                self._engine_submit(batch)
        Hw = engine.harvest_width
        if (
            not self.scheduler.has_work()
            and engine.pending
            and engine.pending % Hw
        ):
            self._submit_placeholders(Hw - engine.pending % Hw)
        # tap cost is per-step host fetches: only pay while someone is
        # actually streaming
        engine.token_sink = (
            self._router.on_tokens if self._router.active else None
        )
        busy_before = engine.pending
        groups = engine.pump()
        for group in groups:
            self._land_group(group)
        return bool(groups) or busy_before > 0

    def _observe_group(self, group) -> None:
        lp = np.asarray(group["logprobs"])
        vals = np.asarray(group["values"])
        m = np.asarray(group["response_mask"]).astype(bool)
        picked = lp[m] if m.any() else lp.ravel()
        row = {
            "health/logprob_mean": float(picked.mean()),
            "health/logprob_min": float(picked.min()),
            "health/value_mean": float(vals[m].mean() if m.any() else 0.0),
        }
        # per-tenant SLO watch: measured queue-wait p95 over the class
        # budget; a ratio > 1 trips the slo-breach detector
        row.update(self.scheduler.slo_ratio_rows())
        self.health_monitor.observe(row, step=self._groups_served)
        self._groups_served += 1

    def _land_group(self, group) -> None:
        import jax

        engine = self.engine
        toks = np.asarray(jax.device_get(group["tokens"]))
        mask = np.asarray(jax.device_get(group["response_mask"]))
        self._observe_group(group)
        for j, row in enumerate(group["rows"]):
            record = engine.pop_request_record(row)
            timing = record["timing"] if record else None
            rid = self._row_to_req.pop(row, None)
            self._published_by_row.pop(row, None)
            # refcounts drop for EVERY harvested row with a plan — also
            # rows whose request was closed early (pop_result mid-
            # flight), which would otherwise pin pool blocks forever
            if rid is not None:
                acquired = self._acquired.pop(rid, None)
                if acquired and self.prefix_pool is not None:
                    self.prefix_pool.release(acquired)
            # the router entry is keyed by ROW and must go even for an
            # early-closed request (pop_result mid-flight) — a leaked
            # not-closed stream would keep the engine's token tap (two
            # extra device fetches per decode step) on forever
            stream = self._router.pop(row)
            if stream is not None:
                stream.close()
            length = int(mask[j].sum()) if rid is not None else 0
            if rid is None or not self._open.get(rid):
                # placeholder / already-closed row. An early-popped
                # request's row still decoded to harvest — its span
                # chain closes here too (status=abandoned), so trace
                # completeness covers every completed row
                self._finish_trace(
                    rid, record, stream, length, status="abandoned"
                )
                continue
            req = self._requests[rid]
            if timing is not None:
                observe_request_metrics(
                    self._registry, timing, length, tenant=req.tenant
                )
            out: Dict[str, Any] = {
                "tokens": toks[j, :length].tolist(),
                "length": length,
                "tenant": req.tenant,
            }
            if self.tokenizer is not None:
                out["text"] = self.tokenizer.decode(
                    out["tokens"], skip_special_tokens=True
                )
            self._results[rid] = out
            self._open[rid] = False
            self.completion_order.append(rid)
            self._finish_trace(rid, record, stream, length)

    def _finish_trace(
        self, rid, record, stream, tokens: int, status: str = "ok"
    ) -> None:
        """Close one harvested request's distributed trace: turn the
        retained scheduler marks + the engine's popped record + the
        stream's delivery marks into the parented span chain
        (telemetry/request_trace.py). No-op for placeholder rows, for
        requests submitted while tracing was off, and when the tracer
        is disabled now."""
        req = self._trace_reqs.pop(rid, None) if rid is not None else None
        if req is None or record is None:
            if rid is not None:
                self._plan_windows.pop(rid, None)
            return
        from trlx_tpu import telemetry
        from trlx_tpu.telemetry.request_trace import emit_request_trace

        tracer = telemetry.get_tracer()
        if not tracer.enabled:
            self._plan_windows.pop(rid, None)
            return
        stream_window = None
        if stream is not None and stream.first_push_at is not None:
            stream_window = (
                stream.first_push_at,
                stream.closed_at or stream.first_push_at,
            )
        emit_request_trace(
            tracer,
            trace_id=req.trace_id,
            request_id=req.request_id,
            tenant=req.tenant,
            priority=req.priority,
            slo_class=req.slo_class,
            streamed=req.stream,
            tokens=tokens,
            marks=record["marks"],
            timing=record["timing"],
            delivered=telemetry.monotonic(),
            status=status,
            quota_blocked_at=req.quota_blocked_at,
            picked_at=req.picked_at or None,
            step_times=record.get("step_times"),
            step_epochs=record.get("step_epochs"),
            plan_window=self._plan_windows.pop(rid, None),
            stream_window=stream_window,
        )

    def flush(self) -> int:
        """Drive the serving loop until every submitted request has
        completed; returns the number of newly completed requests.
        Partial final harvest groups fill with release-on-admission
        placeholders (one decode step each) instead of fully-decoded
        dummy rows."""
        open_before = [r for r, o in self._open.items() if o]
        if not open_before:
            return 0
        while any(self._open.get(r) for r in open_before):
            progressed = self._pump_once()
            if not progressed:
                if self.scheduler.has_work():
                    # quota-throttled tenants: wait for bucket refill
                    time.sleep(0.002)
                else:
                    raise RuntimeError(
                        "serving pump stalled with open requests but "
                        "nothing pending — request bookkeeping bug"
                    )
        return sum(
            1 for r in open_before if not self._open.get(r)
        )

    def poll(self, request_id: int) -> Optional[Dict[str, Any]]:
        """Completed result for ``request_id`` (None while in flight);
        the result stays claimable until :meth:`pop_result`."""
        return self._results.get(request_id)

    def pop_result(self, request_id: int) -> Optional[Dict[str, Any]]:
        # an in-flight streaming request closes its stream NOW (the tap
        # stops paying per-step fetches once no stream is live); the
        # row-keyed router entry itself is popped at harvest
        row = self._req_row.pop(request_id, None)
        if row is not None:
            self._router.close(row)
        self._open.pop(request_id, None)
        self._requests.pop(request_id, None)
        self._streams.pop(request_id, None)
        return self._results.pop(request_id, None)

    def wait(self, request_ids: Sequence[int]) -> Dict[int, Dict[str, Any]]:
        """Drive until every id in ``request_ids`` has a result; returns
        and pops them."""
        missing = [r for r in request_ids if r not in self._results]
        if missing:
            self.flush()
        still = [r for r in request_ids if r not in self._results]
        if still:
            raise RuntimeError(
                f"requests {still} did not complete — were they submitted?"
            )
        return {r: self.pop_result(r) for r in request_ids}

    def generate(self, prompts: Sequence[Any], **submit_kwargs
                 ) -> List[Dict[str, Any]]:
        """Blocking convenience: submit + wait, results in prompt order."""
        rids = self.submit(prompts, **submit_kwargs)
        done = self.wait(rids)
        return [done[r] for r in rids]

    def stats(self) -> Dict[str, float]:
        """Engine occupancy/throughput counters (cumulative this phase)
        plus scheduler and prefix-pool accounting."""
        out = self.engine.stats.to_dict()
        out["scheduler/admitted"] = float(self.scheduler.admitted)
        out["scheduler/pending"] = float(self.scheduler.pending)
        out["scheduler/throttled_rounds"] = float(
            self.scheduler.throttled_rounds
        )
        if self.prefix_pool is not None:
            out.update(self.prefix_pool.stats())
        return out

    def metrics(self) -> Dict[str, Any]:
        """The ``serve/*`` slice of the metrics-registry snapshot: the
        per-request latency histograms (summaries) and counters this
        process accumulated — aggregate AND tenant-labeled keys."""
        snap = self._registry.snapshot()
        out: Dict[str, Any] = {}
        for section in ("counters", "gauges"):
            for name, value in snap.get(section, {}).items():
                if name.startswith("serve/"):
                    out[name] = value
        for name, summary in snap.get("histograms", {}).items():
            if name.startswith("serve/"):
                out[name] = summary
        return out
