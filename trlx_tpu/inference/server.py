"""Standalone batched serving over the continuous-batching engine.

The "millions of users" half of the ROADMAP item: the same
slot-admission engine the collect phase drives
(:mod:`trlx_tpu.inference.engine`) exposed as a trainer-less serving
API — load a policy (from-scratch config, HF conversion, or a trainer
checkpoint directory), ``submit`` prompt batches, ``poll`` completed
generations. No optimizer, no buffer, no orchestrator: the model
forward, the paged KV cache, and the admission loop are the whole
dependency surface.

Quickstart (docs/inference.md):

    from trlx_tpu.data.configs import TRLConfig
    from trlx_tpu.inference.server import InferenceServer

    server = InferenceServer(TRLConfig.load_yaml("configs/ppo_gpt2.yml"),
                             checkpoint_dir="ckpts")
    ids = server.submit([[464, 3290, 318], [1212, 318]])
    results = server.wait(ids)          # {id: {"tokens": ..., "text": ...}}

Request lifecycle: ``submit`` left-pads and enqueues (host), the engine
admits into vacated decode slots, ``flush``/``wait`` drive the loop;
results are retained until ``pop_result``/``wait`` hands them out. A
:class:`~trlx_tpu.telemetry.health.HealthMonitor` watches per-group
generation stats (``health/`` series — non-finite logprobs/values trip
``nan-precursor``), so a served checkpoint that decodes garbage
surfaces as health events, not silent junk; the CI ``serving-smoke``
job asserts a clean run stays at zero events.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from trlx_tpu.data.configs import TRLConfig

#: the per-request latency histograms every served request feeds
#: (docs/observability.md "Serving metrics") — the substrate QoS
#: scheduling will gate on; the CI serving-smoke asserts these keys
SERVE_HISTOGRAMS = (
    "serve/queue_wait_ms",
    "serve/prefill_ms",
    "serve/ttft_ms",
    "serve/decode_per_token_ms",
    "serve/e2e_ms",
)


def observe_request_metrics(
    registry, timing: Dict[str, float], tokens: int
) -> None:
    """Feed one completed request's engine timing decomposition
    (:meth:`~trlx_tpu.inference.engine.ContinuousBatchingEngine.
    pop_request_timing`) into the latency histograms: queue wait,
    prefill, time-to-first-token, per-token decode (``decode_ms`` over
    the generated token count), end-to-end."""
    registry.histogram("serve/queue_wait_ms").observe(
        timing.get("queue_wait_ms", 0.0)
    )
    registry.histogram("serve/prefill_ms").observe(
        timing.get("prefill_ms", 0.0)
    )
    registry.histogram("serve/ttft_ms").observe(timing.get("ttft_ms", 0.0))
    registry.histogram("serve/decode_per_token_ms").observe(
        timing.get("decode_ms", 0.0) / max(1, int(tokens))
    )
    registry.histogram("serve/e2e_ms").observe(timing.get("e2e_ms", 0.0))
    registry.counter("serve/requests_completed").inc()


class InferenceServer:
    """Submit/poll batched generation against a loaded policy.

    :param config: :class:`TRLConfig` (or its dict form) — ``model``
        selects the architecture/checkpoint conversion, ``train.mesh``
        the device mesh, ``method.gen_kwargs`` the generation
        parameters, ``train.rollout`` the engine geometry (slots /
        admit_width / harvest_width / block_size; the ``engine`` field
        is ignored — serving is always continuous).
    :param checkpoint_dir: optional trainer checkpoint directory
        (``utils/checkpoint``): the policy params are restored from the
        saved train state (optimizer state is read but discarded).
    :param params: optional explicit policy param pytree (overrides
        ``checkpoint_dir``).
    :param tokenizer: optional tokenizer for string prompts / decoded
        results (falls back to ``model.tokenizer_path``).
    """

    def __init__(
        self,
        config: Union[TRLConfig, Dict[str, Any]],
        checkpoint_dir: Optional[str] = None,
        params=None,
        tokenizer=None,
        seed: int = 0,
    ):
        import jax
        import jax.numpy as jnp

        from trlx_tpu.inference import RolloutEngineConfig
        from trlx_tpu.inference.engine import ContinuousBatchingEngine
        from trlx_tpu.models.heads import CausalLMWithValueHead
        from trlx_tpu.ops.sampling import (
            GenerationConfig,
            validate_gen_config,
        )
        from trlx_tpu.parallel import make_mesh, make_partition_specs
        from trlx_tpu.telemetry.health import HealthConfig, HealthMonitor
        from trlx_tpu.trainer.ppo_trainer import get_causal_arch

        if not isinstance(config, TRLConfig):
            config = TRLConfig.from_dict(config)
        self.config = config
        train = config.train
        self.mesh = make_mesh(train.mesh)
        if dict(self.mesh.shape).get("pp", 1) > 1:
            raise NotImplementedError(
                "InferenceServer serves under plain GSPMD; drop the pp "
                "mesh axis (pipeline decode is a trainer-path feature)"
            )

        self.family, self.model_config, init_params = get_causal_arch(config)
        self.model = CausalLMWithValueHead(
            self.model_config, backbone_cls=self.family.backbone_cls
        )

        self.tokenizer = tokenizer
        if tokenizer is None and config.model.tokenizer_path:
            from transformers import AutoTokenizer

            self.tokenizer = AutoTokenizer.from_pretrained(
                config.model.tokenizer_path, local_files_only=True
            )

        gen_kwargs = dict(config.method.gen_kwargs)
        self.gen_config = GenerationConfig.from_dict(gen_kwargs)
        validate_gen_config(
            self.gen_config,
            getattr(self.model_config, "vocab_size", None),
            provided=set(gen_kwargs),
        )
        self.query_length = train.seq_length

        # --- params: explicit > checkpoint > converted > from-scratch ---
        rng = jax.random.PRNGKey(seed)
        rng, init_rng = jax.random.split(rng)
        if params is None:
            params = self.model.init(
                init_rng, jnp.zeros((1, 8), jnp.int32)
            )["params"]
            if init_params is not None:
                params["transformer"] = init_params  # converted backbone
            if checkpoint_dir is not None:
                from trlx_tpu.utils.checkpoint import load_checkpoint

                # restore the checkpoint as saved (no abstract spec —
                # serving must not need the training run's optimizer
                # layout) and keep only the policy params
                state, _meta = load_checkpoint(checkpoint_dir, None)
                saved = state["params"] if isinstance(state, dict) else (
                    state.params
                )
                flat_live = jax.tree_util.tree_structure(params)
                flat_saved = jax.tree_util.tree_structure(saved)
                if flat_live != flat_saved:
                    raise ValueError(
                        f"checkpoint under {checkpoint_dir} holds a "
                        "different param structure than model config "
                        f"{type(self.model_config).__name__} builds — "
                        "check model.model_arch/model_type against the "
                        "training run"
                    )
                params = saved

        from jax.sharding import NamedSharding, PartitionSpec as P

        specs = make_partition_specs(
            params, self.mesh, self.family.partition_rules
        )
        self.param_shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s),
            specs,
            is_leaf=lambda x: isinstance(x, P),
        )
        self.params = jax.device_put(params, self.param_shardings)

        rollout = RolloutEngineConfig.from_dict(train.rollout)
        num_slots = rollout.slots or int(
            getattr(config.method, "chunk_size", 0) or train.batch_size
        )

        def apply_fn(p, input_ids, attention_mask=None, position_ids=None,
                     cache=None, cache_index=None, last_only=False):
            return self.model.apply(
                {"params": p},
                input_ids,
                attention_mask=attention_mask,
                position_ids=position_ids,
                cache=cache,
                cache_index=cache_index,
                last_only=last_only,
            )

        import functools

        self.engine = ContinuousBatchingEngine(
            apply_fn=apply_fn,
            init_cache_fn=functools.partial(
                self.family.init_cache, self.model_config
            ),
            gen_config=self.gen_config,
            query_length=self.query_length,
            vocab_size=self.model_config.vocab_size,
            num_slots=num_slots,
            admit_width=rollout.admit_width,
            harvest_width=rollout.harvest_width,
            block_size=rollout.block_size,
            mesh=self.mesh,
            param_shardings=self.param_shardings,
            with_values=True,
        )
        # fold_in consumes rng without a dangling split chain (the
        # key-lineage engine's key-discard rule)
        phase_key = jax.random.fold_in(rng, 7)
        self.engine.start_phase(self.params, phase_key)

        # generation-health watch: non-finite logprobs/values in a served
        # group trip nan-precursor; zero events == healthy checkpoint
        self.health_monitor = HealthMonitor(
            HealthConfig.from_dict({"enabled": True})
        )
        self._results: Dict[int, Dict[str, Any]] = {}
        self._open: Dict[int, bool] = {}
        self._groups_served = 0

    # ------------------------------ API -------------------------------- #

    @property
    def health_events(self) -> List[Any]:
        return list(self.health_monitor.events)

    def _encode(self, prompt) -> List[int]:
        if isinstance(prompt, str):
            if self.tokenizer is None:
                raise ValueError("string prompts require a tokenizer")
            return list(self.tokenizer.encode(prompt))
        return list(prompt)

    def submit(self, prompts: Sequence[Any]) -> List[int]:
        """Enqueue prompts (strings with a tokenizer, or token-id lists /
        arrays); returns request ids. Prompts longer than
        ``train.seq_length`` are refused (truncation would silently serve
        a different prompt)."""
        Q = self.query_length
        pad_id = self.gen_config.pad_token_id
        n = len(prompts)
        ids = np.full((n, Q), pad_id, np.int32)
        mask = np.zeros((n, Q), np.int32)
        for i, p in enumerate(prompts):
            toks = self._encode(p)
            if not toks:
                raise ValueError(f"prompt {i} is empty")
            if len(toks) > Q:
                raise ValueError(
                    f"prompt {i} has {len(toks)} tokens > seq_length={Q}"
                )
            ids[i, Q - len(toks):] = toks  # left-pad, as the trainer does
            mask[i, Q - len(toks):] = 1
        # admission is host-side bookkeeping, but it sits on the serving
        # request path — a transient failure (the engine.admit injection
        # site models one) retries with bounded backoff instead of
        # bouncing the request (docs/resilience.md)
        from trlx_tpu.utils.retry import retry_call

        rows = retry_call(
            lambda: self.engine.submit(ids, mask),
            describe="inference-server admission",
        )
        for r in rows:
            self._open[r] = True
        self._last_prompt = (ids[-1].copy(), mask[-1].copy())
        return rows

    def _observe_group(self, group) -> None:
        lp = np.asarray(group["logprobs"])
        vals = np.asarray(group["values"])
        m = np.asarray(group["response_mask"]).astype(bool)
        picked = lp[m] if m.any() else lp.ravel()
        row = {
            "health/logprob_mean": float(picked.mean()),
            "health/logprob_min": float(picked.min()),
            "health/value_mean": float(vals[m].mean() if m.any() else 0.0),
        }
        self.health_monitor.observe(row, step=self._groups_served)
        self._groups_served += 1

    def flush(self) -> int:
        """Drive the engine until every submitted request has completed;
        returns the number of newly completed requests. The queue is
        padded to a whole number of harvest groups with duplicate rows
        (discarded on harvest) so shapes stay fixed."""
        import jax

        engine = self.engine
        pending_rows = [r for r, open_ in self._open.items() if open_]
        if not pending_rows:
            return 0
        Hw = engine.harvest_width
        n = engine.pending
        target = ((n + Hw - 1) // Hw) * Hw
        if target > n:
            # pad the queue to a whole number of fixed-shape harvest
            # groups with copies of the last real prompt; their results
            # are discarded on harvest
            fill_ids, fill_mask = self._last_prompt
            pad_rows = engine.submit(
                np.repeat(fill_ids[None, :], target - n, axis=0),
                np.repeat(fill_mask[None, :], target - n, axis=0),
            )
        else:
            pad_rows = []
        pad_set = set(pad_rows)
        completed = 0
        from trlx_tpu import telemetry

        registry = telemetry.get_metrics()
        for group in engine.drive(target):
            toks = np.asarray(jax.device_get(group["tokens"]))
            mask = np.asarray(jax.device_get(group["response_mask"]))
            self._observe_group(group)
            for j, r in enumerate(group["rows"]):
                timing = engine.pop_request_timing(r)
                if r in pad_set or r not in self._open:
                    continue
                length = int(mask[j].sum())
                # per-request latency histograms through the shared
                # metrics registry (queue wait, prefill, TTFT,
                # per-token decode, e2e) — docs/observability.md
                if timing is not None:
                    observe_request_metrics(registry, timing, length)
                out: Dict[str, Any] = {
                    "tokens": toks[j, :length].tolist(),
                    "length": length,
                }
                if self.tokenizer is not None:
                    out["text"] = self.tokenizer.decode(
                        out["tokens"], skip_special_tokens=True
                    )
                self._results[r] = out
                self._open[r] = False
                completed += 1
        return completed

    def poll(self, request_id: int) -> Optional[Dict[str, Any]]:
        """Completed result for ``request_id`` (None while in flight);
        the result stays claimable until :meth:`pop_result`."""
        return self._results.get(request_id)

    def pop_result(self, request_id: int) -> Optional[Dict[str, Any]]:
        self._open.pop(request_id, None)
        return self._results.pop(request_id, None)

    def wait(self, request_ids: Sequence[int]) -> Dict[int, Dict[str, Any]]:
        """Drive until every id in ``request_ids`` has a result; returns
        and pops them."""
        missing = [r for r in request_ids if r not in self._results]
        if missing:
            self.flush()
        still = [r for r in request_ids if r not in self._results]
        if still:
            raise RuntimeError(
                f"requests {still} did not complete — were they submitted?"
            )
        return {r: self.pop_result(r) for r in request_ids}

    def generate(self, prompts: Sequence[Any]) -> List[Dict[str, Any]]:
        """Blocking convenience: submit + wait, results in prompt order."""
        rids = self.submit(prompts)
        done = self.wait(rids)
        return [done[r] for r in rids]

    def stats(self) -> Dict[str, float]:
        """Engine occupancy/throughput counters (cumulative this phase)."""
        return self.engine.stats.to_dict()

    def metrics(self) -> Dict[str, Any]:
        """The ``serve/*`` slice of the metrics-registry snapshot: the
        per-request latency histograms (summaries) and counters this
        process accumulated."""
        from trlx_tpu import telemetry

        snap = telemetry.get_metrics().snapshot()
        out: Dict[str, Any] = {}
        for section in ("counters", "gauges"):
            for name, value in snap.get(section, {}).items():
                if name.startswith("serve/"):
                    out[name] = value
        for name, summary in snap.get("histograms", {}).items():
            if name.startswith("serve/"):
                out[name] = summary
        return out
