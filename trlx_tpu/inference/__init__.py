"""Continuous-batching inference subsystem (docs/inference.md).

The collect-phase decode loop re-built as a real inference engine
(ROADMAP "make the rollout engine a real inference server"; PipelineRL's
continuous rollout streams in PAPERS.md):

- :mod:`trlx_tpu.inference.kv_cache` — paged/block KV cache: the same
  ``[B, capacity]`` physical buffers the fixed sampler uses, plus
  per-slot block tables indirecting logical positions through fixed-size
  blocks, honoring ``kv_cache_dtype`` (int8) and the sp-sharded-cache
  layout measured in LONGCTX.json;
- :mod:`trlx_tpu.inference.engine` — the continuous-batching decode
  loop: a fixed pool of decode slots, a host-side admission queue that
  prefills a fresh prompt into a slot the step after its row emits eos,
  per-row RNG keys (each row's tokens independent of admission order),
  and completed rollouts harvested in fixed-width groups;
- :mod:`trlx_tpu.inference.server` — the same engine as a standalone
  batched-serving path (submit/poll against a loaded policy checkpoint,
  no trainer required).

Config surface: ``train.rollout`` (see :class:`RolloutEngineConfig`),
e.g. ``rollout: {engine: continuous, slots: 128, block_size: 16}``. The
fixed-batch sampler stays the default (``engine: fixed``) and the parity
baseline: under per-row RNG the two engines produce per-row
token-identical rollouts (tests/test_inference_engine.py).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Dict, Optional

ROLLOUT_ENGINES = ("fixed", "continuous")
SPEC_DRAFTERS = ("trie", "ngram")


@dataclass(frozen=True)
class SpecDecodeConfig:
    """Parsed ``train.rollout.spec_decode`` section
    (docs/inference.md "Speculative decoding").

    :param enabled: turn drafted verify steps on. Off — the default —
        keeps every jitted engine program byte-identical to the
        spec-less build.
    :param max_draft: draft-token cap per slot per verify step (the
        verify program forwards ``max_draft + 1`` columns); clamped by
        the engine to ``max_new_tokens - 1``.
    :param drafter: ``"trie"`` (shared-prefix-trie corpus + per-row
        n-gram fallback, :class:`trlx_tpu.serving.TrieDrafter`) or
        ``"ngram"`` (per-row self-lookup only).
    :param min_accept_ewma: per-tenant accept-rate floor below which a
        tenant's rows degrade to one-token decode (graceful — drafting
        resumes if later probe drafts raise the EWMA back over the
        bar). 0 never degrades.
    """

    enabled: bool = False
    max_draft: int = 4
    drafter: str = "trie"
    min_accept_ewma: float = 0.0

    def __post_init__(self):
        if self.max_draft < 1:
            raise ValueError(
                f"train.rollout spec_decode.max_draft={self.max_draft} "
                "must be >= 1"
            )
        if self.drafter not in SPEC_DRAFTERS:
            raise ValueError(
                f"train.rollout spec_decode.drafter={self.drafter!r} is "
                f"not supported (choose one of {SPEC_DRAFTERS})"
            )
        if not 0.0 <= self.min_accept_ewma <= 1.0:
            raise ValueError(
                "train.rollout spec_decode.min_accept_ewma="
                f"{self.min_accept_ewma} must be in [0, 1]"
            )

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "SpecDecodeConfig":
        d = dict(d or {})
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"Unknown train.rollout spec_decode keys: "
                f"{sorted(unknown)} (known: {sorted(known)})"
            )
        if "enabled" in d and d["enabled"] is not None:
            d["enabled"] = bool(d["enabled"])
        if "max_draft" in d and d["max_draft"] is not None:
            d["max_draft"] = int(d["max_draft"])
        if "min_accept_ewma" in d and d["min_accept_ewma"] is not None:
            d["min_accept_ewma"] = float(d["min_accept_ewma"])
        return cls(**d)


@dataclass(frozen=True)
class RolloutEngineConfig:
    """Parsed ``train.rollout`` section.

    :param engine: ``"fixed"`` (the segmented-scan sampler,
        ``ops/sampling.py``) or ``"continuous"`` (the slot-admission
        engine, :mod:`trlx_tpu.inference.engine`).
    :param slots: decode-slot pool size B; 0 = the orchestrator's
        ``chunk_size`` (so the engine's steady-state batch matches the
        fixed sampler's).
    :param admit_width: static width of one admission/prefill call
        (padded with dummy rows); 0 = ``max(shard, slots // 4)`` where
        ``shard`` is the mesh's data-shard count. Smaller = prompter
        refills but more prefill dispatches.
    :param harvest_width: completed rollouts per harvest group — the
        downstream chunk size every scoring/ref/reward program compiles
        at; 0 = ``admit_width``. Must divide into ``slots`` (<= slots).
    :param block_size: paged-KV block size; auto-shrunk to the largest
        divisor of the cache capacity (Q + max_new_tokens) so the
        logical view stays exactly capacity-wide (bitwise parity with
        the fixed cache needs no tail padding).
    :param poll_interval: fetch the engine's [B] ``done`` flags every
        k-th decode step instead of every step (the flags are sticky, so
        the amortized poll is exact); 1 — the default — is bitwise the
        poll-every-step loop, larger values trade up to k-1 idle steps
        per finished slot for k× fewer host round-trips on the decode
        critical path (the tunneled-TPU fetch is a flat ~100ms).
    :param per_row_rng: force per-row RNG keys in the FIXED sampler too
        (``None`` = only when ``engine == "continuous"``, which always
        samples per-row). The parity tests run the fixed baseline with
        ``per_row_rng: true``.
    :param prefill_chunk: chunked-prefill width in prompt columns
        (docs/inference.md "Chunked prefill"). ``> 0`` replaces the
        engine's monolithic admission prefill with a scan over
        block-aligned prompt-column chunks whose ``lax.cond`` skips
        chunks no admitted row needs — leading all-pad columns of
        left-padded prompts and blocks served from the shared-prefix
        pool — so prefill compute scales with the group's real prompt
        length, and prefix sharing saves prefill FLOPs, not just HBM
        traffic. Rounded to a block-aligned divisor of the query length
        (``inference/kv_cache.py::choose_prefill_chunk``). Chunked and
        monolithic prefill are token/mask-bitwise-identical
        (logprobs/values at the engine's established bf16 resolution).
        0 — the default — keeps the monolithic program byte-identical.
    :param prefill_chunks_per_pump: serving-pump chunk budget
        (Sarathi-style stall-free admission; needs ``prefill_chunk``):
        one ``pump()`` dispatches at most this many prefill-chunk
        forwards before advancing decode, so an admission burst
        interleaves with decode steps instead of stalling them. 0 =
        unbounded; the trainer collect loop (``drive``) always completes
        an admission inline.
    :param spec_decode: speculative-decoding section
        (:class:`SpecDecodeConfig`): host drafter + multi-token verify
        steps, bitwise-pinned against the one-token loop
        (docs/inference.md "Speculative decoding"). ``None``/disabled
        keeps the engine's jitted programs byte-identical to the
        spec-less build. Continuous engine only.
    """

    engine: str = "fixed"
    slots: int = 0
    admit_width: int = 0
    harvest_width: int = 0
    block_size: int = 16
    poll_interval: int = 1
    per_row_rng: Optional[bool] = None
    prefill_chunk: int = 0
    prefill_chunks_per_pump: int = 0
    spec_decode: Optional[SpecDecodeConfig] = None

    def __post_init__(self):
        if (
            self.spec_decode is not None
            and self.spec_decode.enabled
            and self.engine != "continuous"
        ):
            raise ValueError(
                "train.rollout spec_decode.enabled needs the continuous "
                f"engine (got engine={self.engine!r}) — the fixed "
                "sampler has no verify step"
            )
        if self.engine not in ROLLOUT_ENGINES:
            raise ValueError(
                f"train.rollout engine={self.engine!r} is not supported "
                f"(choose one of {ROLLOUT_ENGINES})"
            )
        if self.block_size < 1:
            raise ValueError(
                f"train.rollout block_size={self.block_size} must be >= 1"
            )
        if self.poll_interval < 1:
            raise ValueError(
                f"train.rollout poll_interval={self.poll_interval} must "
                "be >= 1"
            )
        if self.prefill_chunk < 0:
            raise ValueError(
                f"train.rollout prefill_chunk={self.prefill_chunk} must "
                "be >= 0 (0 = monolithic prefill)"
            )
        if self.prefill_chunks_per_pump < 0:
            raise ValueError(
                "train.rollout prefill_chunks_per_pump="
                f"{self.prefill_chunks_per_pump} must be >= 0 "
                "(0 = unbounded)"
            )
        if self.prefill_chunks_per_pump and not self.prefill_chunk:
            raise ValueError(
                "train.rollout prefill_chunks_per_pump needs chunked "
                "prefill (prefill_chunk > 0) — the monolithic program "
                "has nothing to budget"
            )

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]) -> "RolloutEngineConfig":
        d = dict(d or {})
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"Unknown train.rollout keys: {sorted(unknown)} "
                f"(known: {sorted(known)})"
            )
        for name in (
            "slots", "admit_width", "harvest_width", "block_size",
            "poll_interval", "prefill_chunk", "prefill_chunks_per_pump",
        ):
            if name in d and d[name] is not None:
                d[name] = int(d[name])
        if "spec_decode" in d and isinstance(d["spec_decode"], dict):
            d["spec_decode"] = SpecDecodeConfig.from_dict(d["spec_decode"])
        return cls(**d)

    @property
    def rows_per_row_rng(self) -> bool:
        """Whether the FIXED sampler should use per-row keys under this
        config (the continuous engine always does)."""
        if self.per_row_rng is not None:
            return bool(self.per_row_rng)
        return self.engine == "continuous"


__all__ = [
    "ROLLOUT_ENGINES",
    "SPEC_DRAFTERS",
    "RolloutEngineConfig",
    "SpecDecodeConfig",
]
