"""Paged/block KV cache for the continuous-batching engine.

vLLM-style paging adapted to the TPU/GSPMD substrate: physical storage
keeps the fixed sampler's ``[B, capacity, heads, head_dim]`` per-layer
buffers (so the batch axis shards over dp×fsdp exactly like the fixed
cache, and an ``sp`` mesh axis shards the capacity axis per the
LONGCTX.json sp-sharded-cache row), while a per-slot **block table**
indirects logical token positions through fixed-size blocks:

- physical layout: capacity = ``n_blocks * block_size`` contiguous
  positions per slot; block ``j`` of slot ``b`` is positions
  ``[j*bs, (j+1)*bs)`` of ``pool[b]``;
- ``block_tables[b, j]`` maps *logical* block ``j`` to a *physical*
  block index inside slot ``b``'s region. Writes and reads both resolve
  through the table, so a recycled slot can be handed a permuted table
  (the engine rotates tables on recycle — the indirection is exercised,
  not decorative);
- reads materialize the slot's **logical view** — a per-position gather
  back into logical order — so attention over the paged cache is the
  exact computation the fixed cache runs (bitwise: a gather permutes,
  it never re-associates any reduction). This is what makes
  ``rollout.engine: continuous`` per-row token-identical to the fixed
  sampler.

``kv_cache_dtype`` is honored exactly as in the linear cache
(``models/gpt2.py::kv_buffers``): ``int8`` stores quantized values +
per-(position, head) bf16 scales and dequantizes on read — the same
absmax/127 quantizer, so int8 paged and int8 linear caches hold
identical bits per logical position.

Why per-slot block regions instead of one global pool: a single shared
pool would put every slot's blocks behind one un-sharded physical axis,
breaking the dp×fsdp batch sharding that keeps decode local to each data
shard. Per-slot regions keep GSPMD layouts identical to the fixed cache;
the paging machinery (tables, block-granular recycling) is unchanged,
only the allocator's arena is per-slot.

**Cross-request prefix sharing** (the serving tier,
:mod:`trlx_tpu.serving`): when the engine is built with
``prefix_pool_blocks > 0`` each layer additionally carries

- ``shared_k`` / ``shared_v`` (+ int8 scales) — a *replicated* flat pool
  of ``prefix_pool_blocks * block_size`` positions holding published
  prefix KV (replicated like the params: system prompts are small and
  every data shard reads them, so the pool is a broadcast structure, not
  a batch-sharded one — the per-slot regions' sharding story is
  untouched);
- ``shared_tables[b, j]`` — logical block ``j`` of slot ``b`` READS from
  shared-pool block ``shared_tables[b, j]`` when ``>= 0`` (else from the
  slot's private region through ``block_tables``);
- ``publish_tables[b, j]`` — prefill WRITES logical block ``j``'s K/V
  into shared-pool block ``publish_tables[b, j]`` when ``>= 0`` (the
  donor request publishing a new prefix).

Sharing semantics are exact, not approximate: a shared block's bits are
the donor prefill's bits, which equal the bits the reader's own prefill
computes for the same leading padded columns (causal attention — column
``j``'s K/V depends only on columns ``<= j``; same program shape, same
params, same columns ⇒ same bits), and the read side is a gather — a
permutation that re-associates nothing. Private writes to shared
columns are dropped (the region's leading blocks stay unwritten — the
``engine/prefix_blocks_saved`` accounting), writes during decode land at
positions ``>= Q`` which are never shared, so a shared block is
immutable after publication — copy-on-first-divergent-write degenerates
to "the first divergent block is private from admission", enforced
host-side by :class:`trlx_tpu.serving.prefix_cache.PrefixBlockPool`
(which only maps *fully-covered* leading blocks and allocates a fresh
pool block on any content divergence instead of mutating a published
one).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp


def choose_block_size(capacity: int, requested: int) -> int:
    """Largest divisor of ``capacity`` that is <= ``requested``.

    The logical view must be exactly ``capacity`` wide: a non-dividing
    block size would pad the view with tail positions whose masked-out
    (but present) slots change the softmax reduction shape — breaking
    bitwise parity with the fixed cache.
    """
    if capacity < 1:
        raise ValueError(f"cache capacity must be >= 1, got {capacity}")
    bs = max(1, min(int(requested), capacity))
    while capacity % bs:
        bs -= 1
    return bs


def choose_prefill_chunk(
    query_length: int, requested: int, block_size: int
) -> int:
    """Effective chunked-prefill width for ``rollout.prefill_chunk``.

    The chunk must tile the prompt columns exactly (divide Q — a ragged
    tail chunk would need its own program shape) and should align to the
    paged-KV block size so a pool-covered shared block is never split
    across a run/skip boundary. Returns the largest divisor of ``Q`` that
    is ``<= requested`` and a ``block_size`` multiple; when no aligned
    divisor exists (block size does not divide Q — e.g. the block was
    auto-shrunk against a capacity Q+R that Q does not share factors
    with), falls back to the largest plain divisor — chunk-skip decisions
    are column-granular, so correctness never depends on alignment, only
    the shared-skip efficiency does. ``requested <= 0`` disables chunking
    (the monolithic prefill).
    """
    if requested <= 0:
        return 0
    hi = min(int(requested), int(query_length))
    fallback = 1
    for w in range(hi, 0, -1):
        if query_length % w:
            continue
        if w % block_size == 0:
            return w
        if fallback == 1:
            fallback = w
    return fallback


def identity_block_tables(n_slots: int, n_blocks: int) -> jax.Array:
    """[B, n_blocks] int32 identity mapping (fresh slots)."""
    return jnp.broadcast_to(
        jnp.arange(n_blocks, dtype=jnp.int32)[None, :], (n_slots, n_blocks)
    )


def rotate_block_table(table, turns: int):
    """Rotate one slot's table by ``turns`` blocks (host or device array).

    The engine hands a recycled slot a rotated table so physical block
    reuse order differs from logical order — block-table indirection is
    exercised on every recycle, and a table-resolution bug shows up as a
    parity break instead of lying dormant behind identity tables.
    """
    n = table.shape[-1]
    k = int(turns) % n
    if k == 0:
        return table
    return jnp.concatenate([table[..., k:], table[..., :k]], axis=-1)


def init_paged_cache(
    n_layer: int,
    n_slots: int,
    capacity: int,
    n_head: int,
    head_dim: int,
    dtype,
    kv_cache_dtype: str = "bfloat16",
    block_size: int = 16,
) -> Tuple[Dict[str, jax.Array], ...]:
    """Per-layer paged KV buffers + shared block tables.

    Layer dicts carry the physical pools under the linear cache's key
    names ("k"/"v" [+ scales]) plus "block_tables" — the presence of
    that key is what routes ``models/gpt2.py::write_cache`` onto the
    paged write/read path, so every causal family decodes through the
    paged cache with no model changes.
    """
    from trlx_tpu.models.gpt2 import kv_buffers

    bs = choose_block_size(capacity, block_size)
    n_blocks = capacity // bs
    tables = identity_block_tables(n_slots, n_blocks)
    layers = kv_buffers(
        n_layer, n_slots, capacity, n_head, head_dim, dtype, kv_cache_dtype
    )
    # per-layer table copies: donated-state programs must not see one
    # buffer behind several arguments (XLA double-donation refusal)
    return tuple(
        dict(layer, block_tables=jnp.array(tables)) for layer in layers
    )


def empty_share_tables(n_slots: int, n_blocks: int) -> jax.Array:
    """[B, n_blocks] int32 all ``-1`` — no block shared/published."""
    return jnp.full((n_slots, n_blocks), -1, jnp.int32)


def init_shared_pool(
    pool_blocks: int,
    block_size: int,
    n_head: int,
    head_dim: int,
    dtype,
    kv_cache_dtype: str = "bfloat16",
) -> Dict[str, jax.Array]:
    """Per-layer shared-prefix pool buffers: ``pool_blocks * block_size``
    flat positions in the private regions' storage layout (int8 pools
    carry scales exactly like the int8 linear cache)."""
    if pool_blocks < 1:
        raise ValueError(
            f"prefix pool needs >= 1 block, got {pool_blocks}"
        )
    n_pos = pool_blocks * block_size
    shape = (n_pos, n_head, head_dim)
    if kv_cache_dtype == "int8":
        sshape = (n_pos, n_head, 1)
        return {
            "shared_k": jnp.zeros(shape, jnp.int8),
            "shared_v": jnp.zeros(shape, jnp.int8),
            "shared_k_scale": jnp.zeros(sshape, jnp.bfloat16),
            "shared_v_scale": jnp.zeros(sshape, jnp.bfloat16),
        }
    return {
        "shared_k": jnp.zeros(shape, jnp.dtype(dtype)),
        "shared_v": jnp.zeros(shape, jnp.dtype(dtype)),
    }


#: cache-dict keys that belong to the shared-prefix pool (global, never
#: sliced/merged along the slot axis) vs the per-slot share metadata
SHARED_POOL_KEYS = (
    "shared_k", "shared_v", "shared_k_scale", "shared_v_scale",
)
SHARE_TABLE_KEYS = ("shared_tables", "publish_tables")


def physical_positions(
    block_tables: jax.Array,  # [B, n_blocks] int32
    positions: jax.Array,  # [B, T] logical positions (may be >= capacity)
    capacity: int,
) -> jax.Array:
    """[B, T] physical positions; out-of-range logical positions map to
    ``capacity`` (out of bounds), which scatters DROP — the engine uses
    position >= capacity as the "discard this write" sentinel for
    finished/inactive slots."""
    n_blocks = block_tables.shape[-1]
    bs = capacity // n_blocks
    pos = jnp.asarray(positions, jnp.int32)
    blk = jnp.clip(pos // bs, 0, n_blocks - 1)
    phys_blk = jnp.take_along_axis(block_tables, blk, axis=1)
    phys = phys_blk * bs + pos % bs
    # preserve OOB-ness: the table gather above CLIPS, so a position past
    # capacity would otherwise alias the last block and corrupt it
    return jnp.where((pos >= 0) & (pos < capacity), phys, capacity)


def logical_view_index(block_tables: jax.Array, capacity: int) -> jax.Array:
    """[B, capacity] gather index: physical position of each logical
    position (the read-side permutation)."""
    n_blocks = block_tables.shape[-1]
    bs = capacity // n_blocks
    offs = jnp.arange(bs, dtype=jnp.int32)[None, None, :]
    phys = block_tables[:, :, None] * bs + offs  # [B, n_blocks, bs]
    return phys.reshape(block_tables.shape[0], capacity)


def _gather_logical(pool: jax.Array, view_idx: jax.Array) -> jax.Array:
    """Gather ``pool`` [B, cap, ...] rows into logical order."""
    b_idx = jnp.arange(pool.shape[0], dtype=jnp.int32)[:, None]
    return pool[b_idx, view_idx]


def _scatter_rows(pool: jax.Array, phys: jax.Array, rows: jax.Array) -> jax.Array:
    """Scatter ``rows`` [B, T, ...] into ``pool`` [B, cap, ...] at
    physical positions ``phys`` [B, T]; OOB positions drop (jax scatter
    semantics — the discard sentinel relies on this)."""
    b_idx = jnp.arange(pool.shape[0], dtype=jnp.int32)[:, None]
    return pool.at[b_idx, phys].set(rows.astype(pool.dtype), mode="drop")


def _publish_rows(
    pool: jax.Array, pub_pos: jax.Array, rows: jax.Array
) -> jax.Array:
    """Scatter ``rows`` [B, T, ...] into the flat shared pool
    [pool_positions, ...] at ``pub_pos`` [B, T]; OOB (== pool size)
    drops — rows without a publish assignment write nowhere. The host
    pool allocator guarantees distinct rows never publish to the same
    block, so the scatter is collision-free."""
    idx = pub_pos.reshape(-1)
    flat = rows.reshape((-1,) + rows.shape[2:])
    return pool.at[idx].set(flat.astype(pool.dtype), mode="drop")


def _shared_gather(
    shared_tables: jax.Array,  # [B, n_blocks] int32, -1 = private
    pool: jax.Array,  # [pool_positions, H, ...] shared values
    capacity: int,
    view_len: int = 0,
) -> Tuple[jax.Array, jax.Array]:
    """Per logical position, the shared-pool value (garbage where the
    block is private) and the [B, capacity] bool mask of shared
    positions — the read-side overlay inputs. ``view_len > 0`` narrows
    the overlay to the leading ``view_len`` logical positions (the
    chunked prefill's prompt-region view — shared prefix blocks all live
    there, so the narrowed overlay gathers strictly less)."""
    n_blocks = shared_tables.shape[-1]
    bs = capacity // n_blocks
    width = view_len if 0 < view_len < capacity else capacity
    cols = jnp.arange(width, dtype=jnp.int32)
    sh_blk = jnp.take(shared_tables, cols // bs, axis=1)  # [B, capacity]
    sh_pos = sh_blk * bs + cols[None, :] % bs
    safe = jnp.clip(sh_pos, 0, pool.shape[0] - 1)
    return pool[safe], sh_blk >= 0


def paged_write_read(
    cache_kv: Dict[str, jax.Array],
    k: jax.Array,  # [B, T, H, Dh] new keys (compute dtype)
    v: jax.Array,
    cache_index,  # scalar/[B] logical base position, or [B, T] per column
    dtype,
    view_len: int = 0,
) -> Tuple[jax.Array, jax.Array, Dict[str, jax.Array]]:
    """Paged counterpart of the linear ``write_cache`` arm: write the new
    K/V rows through the block table, then return the **logical view** of
    the whole buffer for attention (plus the updated cache dict).

    ``cache_index`` may be per-slot (the continuous engine's rows sit at
    different depths), scalar (broadcast), or a full [B, T] per-column
    position matrix — the speculative verify step's drafted window,
    where each row writes only its first ``draft_len + 1`` columns and
    parks the rest at ``capacity`` (the same OOB-drop sentinel idle
    slots use, applied per column instead of per row). int8 pools
    quantize on write and dequantize the gathered view — same bits as
    the linear int8 path per logical position.

    ``view_len > 0`` narrows the returned logical view (and the shared
    overlay) to the leading ``view_len`` positions — chunk-granular
    reads for the chunked prefill, whose prompt-chunk queries never
    attend the decode region. Writes are NEVER narrowed: positions
    resolve through the table at full capacity regardless.
    """
    B, T = k.shape[0], k.shape[1]
    capacity = cache_kv["k"].shape[1]
    tables = cache_kv["block_tables"]
    idx = jnp.asarray(cache_index, jnp.int32)
    if idx.ndim == 2:
        # per-column targets: the caller names every column's logical
        # position directly (OOB columns drop per element)
        positions = idx
    else:
        base = jnp.broadcast_to(idx, (B,))
        positions = base[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
    phys = physical_positions(tables, positions, capacity)
    view = logical_view_index(tables, capacity)
    if 0 < view_len < capacity:
        view = view[:, :view_len]

    sharing = "shared_tables" in cache_kv
    pub_pos = None
    if sharing:
        shared_tables = cache_kv["shared_tables"]
        publish_tables = cache_kv["publish_tables"]
        n_blocks = shared_tables.shape[-1]
        bs = capacity // n_blocks
        pool_size = cache_kv["shared_k"].shape[0]
        col_blk = jnp.clip(positions // bs, 0, n_blocks - 1)
        in_range = (positions >= 0) & (positions < capacity)
        # private writes to shared columns drop: the pool serves those
        # reads and the region's leading blocks stay unwritten (the
        # engine/prefix_blocks_saved accounting)
        shared_at = (
            jnp.take_along_axis(shared_tables, col_blk, axis=1) >= 0
        )
        phys = jnp.where(shared_at & in_range, capacity, phys)
        # publish: the donor's prefix columns scatter into the pool (a
        # reader mapped to the same blocks in the SAME call gathers the
        # just-written bits — identical to what it computed in-flight)
        pub_blk = jnp.take_along_axis(publish_tables, col_blk, axis=1)
        pub_pos = jnp.where(
            (pub_blk >= 0) & in_range,
            pub_blk * bs + positions % bs,
            pool_size,
        )

    def overlay(full, pool_key, scale_key=None):
        if not sharing:
            return full
        pool_vals, mask = _shared_gather(
            cache_kv["shared_tables"], new_kv[pool_key], capacity,
            view_len=view_len,
        )
        vals = pool_vals.astype(dtype)
        if scale_key is not None:
            scales, _ = _shared_gather(
                cache_kv["shared_tables"], new_kv[scale_key], capacity,
                view_len=view_len,
            )
            vals = vals * scales.astype(dtype)
        return jnp.where(mask[..., None, None], vals, full)

    def carry(new_kv):
        """Thread the share metadata (+ updated pools) through so the
        next step's cache dict keeps the full layout."""
        new_kv["block_tables"] = tables
        if sharing:
            new_kv["shared_tables"] = cache_kv["shared_tables"]
            new_kv["publish_tables"] = cache_kv["publish_tables"]
        return new_kv

    if "k_scale" in cache_kv:
        from trlx_tpu.models.gpt2 import quantize_kv

        k_q, k_s = quantize_kv(k)
        v_q, v_s = quantize_kv(v)
        new_kv = carry({
            "k": _scatter_rows(cache_kv["k"], phys, k_q),
            "v": _scatter_rows(cache_kv["v"], phys, v_q),
            "k_scale": _scatter_rows(cache_kv["k_scale"], phys, k_s),
            "v_scale": _scatter_rows(cache_kv["v_scale"], phys, v_s),
        })
        if sharing:
            new_kv["shared_k"] = _publish_rows(
                cache_kv["shared_k"], pub_pos, k_q
            )
            new_kv["shared_v"] = _publish_rows(
                cache_kv["shared_v"], pub_pos, v_q
            )
            new_kv["shared_k_scale"] = _publish_rows(
                cache_kv["shared_k_scale"], pub_pos, k_s
            )
            new_kv["shared_v_scale"] = _publish_rows(
                cache_kv["shared_v_scale"], pub_pos, v_s
            )
        k_full = _gather_logical(new_kv["k"], view).astype(dtype) * (
            _gather_logical(new_kv["k_scale"], view).astype(dtype)
        )
        v_full = _gather_logical(new_kv["v"], view).astype(dtype) * (
            _gather_logical(new_kv["v_scale"], view).astype(dtype)
        )
        k_full = overlay(k_full, "shared_k", "shared_k_scale")
        v_full = overlay(v_full, "shared_v", "shared_v_scale")
        return k_full, v_full, new_kv

    new_kv = carry({
        "k": _scatter_rows(cache_kv["k"], phys, k),
        "v": _scatter_rows(cache_kv["v"], phys, v),
    })
    if sharing:
        new_kv["shared_k"] = _publish_rows(cache_kv["shared_k"], pub_pos, k)
        new_kv["shared_v"] = _publish_rows(cache_kv["shared_v"], pub_pos, v)
    k_full = overlay(_gather_logical(new_kv["k"], view), "shared_k")
    v_full = overlay(_gather_logical(new_kv["v"], view), "shared_v")
    return k_full, v_full, new_kv
