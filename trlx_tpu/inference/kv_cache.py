"""Paged/block KV cache for the continuous-batching engine.

vLLM-style paging adapted to the TPU/GSPMD substrate: physical storage
keeps the fixed sampler's ``[B, capacity, heads, head_dim]`` per-layer
buffers (so the batch axis shards over dp×fsdp exactly like the fixed
cache, and an ``sp`` mesh axis shards the capacity axis per the
LONGCTX.json sp-sharded-cache row), while a per-slot **block table**
indirects logical token positions through fixed-size blocks:

- physical layout: capacity = ``n_blocks * block_size`` contiguous
  positions per slot; block ``j`` of slot ``b`` is positions
  ``[j*bs, (j+1)*bs)`` of ``pool[b]``;
- ``block_tables[b, j]`` maps *logical* block ``j`` to a *physical*
  block index inside slot ``b``'s region. Writes and reads both resolve
  through the table, so a recycled slot can be handed a permuted table
  (the engine rotates tables on recycle — the indirection is exercised,
  not decorative);
- reads materialize the slot's **logical view** — a per-position gather
  back into logical order — so attention over the paged cache is the
  exact computation the fixed cache runs (bitwise: a gather permutes,
  it never re-associates any reduction). This is what makes
  ``rollout.engine: continuous`` per-row token-identical to the fixed
  sampler.

``kv_cache_dtype`` is honored exactly as in the linear cache
(``models/gpt2.py::kv_buffers``): ``int8`` stores quantized values +
per-(position, head) bf16 scales and dequantizes on read — the same
absmax/127 quantizer, so int8 paged and int8 linear caches hold
identical bits per logical position.

Why per-slot block regions instead of one global pool: a single shared
pool would put every slot's blocks behind one un-sharded physical axis,
breaking the dp×fsdp batch sharding that keeps decode local to each data
shard. Per-slot regions keep GSPMD layouts identical to the fixed cache;
the paging machinery (tables, block-granular recycling) is unchanged,
only the allocator's arena is per-slot.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp


def choose_block_size(capacity: int, requested: int) -> int:
    """Largest divisor of ``capacity`` that is <= ``requested``.

    The logical view must be exactly ``capacity`` wide: a non-dividing
    block size would pad the view with tail positions whose masked-out
    (but present) slots change the softmax reduction shape — breaking
    bitwise parity with the fixed cache.
    """
    if capacity < 1:
        raise ValueError(f"cache capacity must be >= 1, got {capacity}")
    bs = max(1, min(int(requested), capacity))
    while capacity % bs:
        bs -= 1
    return bs


def identity_block_tables(n_slots: int, n_blocks: int) -> jax.Array:
    """[B, n_blocks] int32 identity mapping (fresh slots)."""
    return jnp.broadcast_to(
        jnp.arange(n_blocks, dtype=jnp.int32)[None, :], (n_slots, n_blocks)
    )


def rotate_block_table(table, turns: int):
    """Rotate one slot's table by ``turns`` blocks (host or device array).

    The engine hands a recycled slot a rotated table so physical block
    reuse order differs from logical order — block-table indirection is
    exercised on every recycle, and a table-resolution bug shows up as a
    parity break instead of lying dormant behind identity tables.
    """
    n = table.shape[-1]
    k = int(turns) % n
    if k == 0:
        return table
    return jnp.concatenate([table[..., k:], table[..., :k]], axis=-1)


def init_paged_cache(
    n_layer: int,
    n_slots: int,
    capacity: int,
    n_head: int,
    head_dim: int,
    dtype,
    kv_cache_dtype: str = "bfloat16",
    block_size: int = 16,
) -> Tuple[Dict[str, jax.Array], ...]:
    """Per-layer paged KV buffers + shared block tables.

    Layer dicts carry the physical pools under the linear cache's key
    names ("k"/"v" [+ scales]) plus "block_tables" — the presence of
    that key is what routes ``models/gpt2.py::write_cache`` onto the
    paged write/read path, so every causal family decodes through the
    paged cache with no model changes.
    """
    from trlx_tpu.models.gpt2 import kv_buffers

    bs = choose_block_size(capacity, block_size)
    n_blocks = capacity // bs
    tables = identity_block_tables(n_slots, n_blocks)
    layers = kv_buffers(
        n_layer, n_slots, capacity, n_head, head_dim, dtype, kv_cache_dtype
    )
    # per-layer table copies: donated-state programs must not see one
    # buffer behind several arguments (XLA double-donation refusal)
    return tuple(
        dict(layer, block_tables=jnp.array(tables)) for layer in layers
    )


def physical_positions(
    block_tables: jax.Array,  # [B, n_blocks] int32
    positions: jax.Array,  # [B, T] logical positions (may be >= capacity)
    capacity: int,
) -> jax.Array:
    """[B, T] physical positions; out-of-range logical positions map to
    ``capacity`` (out of bounds), which scatters DROP — the engine uses
    position >= capacity as the "discard this write" sentinel for
    finished/inactive slots."""
    n_blocks = block_tables.shape[-1]
    bs = capacity // n_blocks
    pos = jnp.asarray(positions, jnp.int32)
    blk = jnp.clip(pos // bs, 0, n_blocks - 1)
    phys_blk = jnp.take_along_axis(block_tables, blk, axis=1)
    phys = phys_blk * bs + pos % bs
    # preserve OOB-ness: the table gather above CLIPS, so a position past
    # capacity would otherwise alias the last block and corrupt it
    return jnp.where((pos >= 0) & (pos < capacity), phys, capacity)


def logical_view_index(block_tables: jax.Array, capacity: int) -> jax.Array:
    """[B, capacity] gather index: physical position of each logical
    position (the read-side permutation)."""
    n_blocks = block_tables.shape[-1]
    bs = capacity // n_blocks
    offs = jnp.arange(bs, dtype=jnp.int32)[None, None, :]
    phys = block_tables[:, :, None] * bs + offs  # [B, n_blocks, bs]
    return phys.reshape(block_tables.shape[0], capacity)


def _gather_logical(pool: jax.Array, view_idx: jax.Array) -> jax.Array:
    """Gather ``pool`` [B, cap, ...] rows into logical order."""
    b_idx = jnp.arange(pool.shape[0], dtype=jnp.int32)[:, None]
    return pool[b_idx, view_idx]


def _scatter_rows(pool: jax.Array, phys: jax.Array, rows: jax.Array) -> jax.Array:
    """Scatter ``rows`` [B, T, ...] into ``pool`` [B, cap, ...] at
    physical positions ``phys`` [B, T]; OOB positions drop (jax scatter
    semantics — the discard sentinel relies on this)."""
    b_idx = jnp.arange(pool.shape[0], dtype=jnp.int32)[:, None]
    return pool.at[b_idx, phys].set(rows.astype(pool.dtype), mode="drop")


def paged_write_read(
    cache_kv: Dict[str, jax.Array],
    k: jax.Array,  # [B, T, H, Dh] new keys (compute dtype)
    v: jax.Array,
    cache_index,  # scalar or [B] logical base position of the new rows
    dtype,
) -> Tuple[jax.Array, jax.Array, Dict[str, jax.Array]]:
    """Paged counterpart of the linear ``write_cache`` arm: write the new
    K/V rows through the block table, then return the **logical view** of
    the whole buffer for attention (plus the updated cache dict).

    ``cache_index`` may be per-slot (the continuous engine's rows sit at
    different depths) or scalar (broadcast). int8 pools quantize on write
    and dequantize the gathered view — same bits as the linear int8 path
    per logical position.
    """
    B, T = k.shape[0], k.shape[1]
    capacity = cache_kv["k"].shape[1]
    tables = cache_kv["block_tables"]
    base = jnp.broadcast_to(jnp.asarray(cache_index, jnp.int32), (B,))
    positions = base[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
    phys = physical_positions(tables, positions, capacity)
    view = logical_view_index(tables, capacity)

    if "k_scale" in cache_kv:
        from trlx_tpu.models.gpt2 import quantize_kv

        k_q, k_s = quantize_kv(k)
        v_q, v_s = quantize_kv(v)
        new_kv = {
            "k": _scatter_rows(cache_kv["k"], phys, k_q),
            "v": _scatter_rows(cache_kv["v"], phys, v_q),
            "k_scale": _scatter_rows(cache_kv["k_scale"], phys, k_s),
            "v_scale": _scatter_rows(cache_kv["v_scale"], phys, v_s),
            "block_tables": tables,
        }
        k_full = _gather_logical(new_kv["k"], view).astype(dtype) * (
            _gather_logical(new_kv["k_scale"], view).astype(dtype)
        )
        v_full = _gather_logical(new_kv["v"], view).astype(dtype) * (
            _gather_logical(new_kv["v_scale"], view).astype(dtype)
        )
        return k_full, v_full, new_kv

    new_kv = {
        "k": _scatter_rows(cache_kv["k"], phys, k),
        "v": _scatter_rows(cache_kv["v"], phys, v),
        "block_tables": tables,
    }
    k_full = _gather_logical(new_kv["k"], view)
    v_full = _gather_logical(new_kv["v"], view)
    return k_full, v_full, new_kv
